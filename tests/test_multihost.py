"""Executed multi-host path (VERDICT r3 item 5 + r4 item 1; SURVEY §3.1
bring-up, §3.5 train path, §5.8 DCN half): 2 OS processes x 4 virtual CPU
devices each, through python -m paddle_tpu.distributed.launch -> TCPStore
rendezvous -> init_parallel_env -> jax.distributed.initialize (gloo CPU
collectives) -> (a) a psum across all 8 global devices, (b) a HYBRID
TRAIN STEP (dp x mp x ZeRO and pp x mp x dp tiny-llama) over the global
mesh with per-step loss parity vs the single-process 8-device run. Plus
the elastic relaunch-with-new-ranks flow (ref: ElasticManager scale-in ->
rank regen -> respawn)."""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ASSETS = os.path.join(REPO, "tests", "assets")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _launch_node(node_rank, nnodes, master, script, log_dir, out_dir,
                 extra_env=None):
    env = dict(os.environ)
    env["MH_OUT"] = out_dir
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", str(nnodes), "--node_rank", str(node_rank),
         "--nproc_per_node", "1", "--master", master,
         "--log_dir", os.path.join(log_dir, f"node{node_rank}"),
         "--rdzv_timeout", "120", script],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _wait_all(procs, timeout):
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        remaining = max(5.0, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out.decode(errors="replace"))
    return outs


def _wait_and_assert_ok(procs, tmp_path, timeout, nnodes=2):
    """Wait for all launched nodes, collect workerlogs (launcher names them
    workerlog.{global_rank} under node{r}/), assert zero exit codes."""
    outs = _wait_all(procs, timeout)
    logs = []
    for r in range(nnodes):
        d = tmp_path / f"node{r}" / "workerlog.{}".format(r)
        logs.append(d.read_text(errors="replace") if d.exists() else "")
    assert all(p.returncode == 0 for p in procs), (
        [p.returncode for p in procs], outs, logs)
    return outs, logs


class TestMultiHostPsum:
    def test_two_process_launch_psum_across_8_devices(self, tmp_path):
        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        procs = [
            _launch_node(r, 2, master, os.path.join(
                ASSETS, "multihost_psum_worker.py"),
                str(tmp_path), out_dir)
            for r in range(2)]
        outs, logs = _wait_and_assert_ok(procs, tmp_path, timeout=420)
        for r in range(2):
            f = os.path.join(out_dir, f"ok.{r}")
            assert os.path.exists(f), (outs, logs)
            # psum over [0..3]+[10..13] across the 8-device global mesh
            assert float(open(f).read()) == 52.0


class TestMultiHostTrain:
    """VERDICT r4 item 1: the actual §3.5 path — launcher -> rendezvous ->
    jax.distributed -> GLOBAL 8-device mesh -> hybrid TRAIN step with
    GSPMD collectives crossing the OS-process boundary -> loss parity
    vs the same routine on the single-process 8-device mesh."""

    @pytest.mark.parametrize("cfg_name", ["dp2mp2zero2", "pp2mp2dp2"])
    def test_two_process_hybrid_train_loss_parity(self, tmp_path, cfg_name):
        import json
        sys.path.insert(0, ASSETS)
        from mh_train_common import run_train

        # baseline: SAME routine, single process, pytest's 8-device mesh
        baseline = run_train(cfg_name)
        assert all(np.isfinite(v) for v in baseline), baseline

        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        procs = [
            _launch_node(r, 2, master,
                         os.path.join(ASSETS, "multihost_train_worker.py"),
                         str(tmp_path), out_dir,
                         extra_env={"MH_TRAIN_CFG": cfg_name})
            for r in range(2)]
        outs, logs = _wait_and_assert_ok(procs, tmp_path, timeout=420)
        for r in range(2):
            f = os.path.join(out_dir, f"losses.{r}.json")
            assert os.path.exists(f), (outs, logs)
            got = json.load(open(f))
            # per-step loss parity: the 2-process global-mesh program is
            # the same SPMD program; only collective reduction order may
            # differ (gloo ring vs shared-memory)
            assert np.allclose(got, baseline, rtol=1e-5, atol=1e-5), (
                got, baseline)


class TestMultiHostRunPretrain:
    """r5: the reference's NAMED workflow end to end across processes —
    `paddle_tpu.distributed.launch` -> run_pretrain CLI on 2 OS processes
    x 4 devices, dp2 x mp2 x zero2 over the global 8-device mesh, with
    loss parity vs the identical single-process CLI run."""

    def test_launcher_driven_cli_loss_parity(self, tmp_path):
        import json

        def write_cfg(out_name, max_steps=6, cfg_name=None):
            cfg = {"model": {"preset": "tiny", "num_hidden_layers": 2},
                   "data": {"corpus": None},
                   "seq_len": 64, "global_batch": 8, "max_steps": max_steps,
                   "parallel": {"dp": 2, "mp": 2, "sharding": 2},
                   "save_interval": 3, "log_interval": 6, "remat": "none",
                   "output_dir": str(tmp_path / out_name)}
            p = tmp_path / f"{cfg_name or out_name}.json"
            p.write_text(json.dumps(cfg))
            return str(p), cfg

        # single-process reference run of the SAME config
        ref_cfg_path, ref_cfg = write_cfg("ref")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.trainer.run_pretrain",
             "--config", ref_cfg_path],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, (r.stdout, r.stderr)
        ref = [json.loads(x)["loss"] for x in open(
            os.path.join(ref_cfg["output_dir"], "losses.jsonl"))]

        # 2-process launcher-driven run of the SAME config, in TWO stages:
        # stage A stops at step 3 (checkpoint), stage B auto-RESUMES the
        # multi-process sharded checkpoint and runs to 6 — so the
        # cross-process save -> union-meta load path is what produces
        # steps 4-6, and any dropped rank's shards would show up as a
        # loss divergence immediately
        stage_a, mh_cfg = write_cfg("mh", max_steps=3, cfg_name="mh_a")
        stage_b, _ = write_cfg("mh", max_steps=6, cfg_name="mh_b")
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        for stage_path in (stage_a, stage_b):
            master = f"127.0.0.1:{_free_port()}"
            procs = [
                _launch_node(rk, 2, master,
                             os.path.join(ASSETS,
                                          "multihost_pretrain_worker.py"),
                             str(tmp_path), out_dir,
                             extra_env={"MH_CFG": stage_path})
                for rk in range(2)]
            outs, logs = _wait_and_assert_ok(procs, tmp_path, timeout=420)
        assert any("resumed from ckpt_step3" in lg for lg in logs), logs
        got = {}
        for x in open(os.path.join(mh_cfg["output_dir"], "losses.jsonl")):
            rec = json.loads(x)
            got[rec["step"]] = rec["loss"]
        assert sorted(got) == [1, 2, 3, 4, 5, 6], (got, outs, logs)
        assert np.allclose([got[s] for s in range(1, 7)], ref,
                           rtol=1e-5, atol=1e-5), (got, ref)
        # the sharded checkpoint has shards AND shard maps from BOTH
        # processes
        ck = os.path.join(mh_cfg["output_dir"], "ckpt_step6")
        files = os.listdir(ck)
        assert any(".r0." in f for f in files) \
            and any(".r1." in f for f in files), files
        assert "metadata.json.r0" in files and "metadata.json.r1" in files


class TestElasticScaleUpAndHold:
    """r5 (VERDICT r4 weak #7): real elastic semantics — a JOIN claims a
    free heartbeat slot and triggers a scale-up relaunch that includes the
    newcomer (EXECUTED through the launcher); a LEAVE below min_nnodes is
    a HOLD, not a smaller relaunch."""

    def test_scale_up_mid_run_and_min_nnodes_hold(self, tmp_path):
        from paddle_tpu.native import TCPStore
        from paddle_tpu.distributed.launch.controllers import ElasticManager

        store = TCPStore(host="127.0.0.1", port=0, is_master=True,
                         world_size=1, timeout=30)
        try:
            # a 2-node world under --nnodes 2:3
            m0 = ElasticManager(store, 0, ttl=5.0, min_nodes=2, max_nodes=3)
            m1 = ElasticManager(store, 1, ttl=5.0, min_nodes=2, max_nodes=3)
            m0.heartbeat()
            m1.heartbeat()
            assert m0.watch_once(current=[0, 1]) is None   # stable

            # a NEW node joins: claims the first free slot -> slot 2
            joiner = ElasticManager(store, -1, ttl=5.0, min_nodes=2,
                                    max_nodes=3)
            slot = joiner.claim_slot()
            assert slot == 2
            ev = m0.watch_once(current=[0, 1])
            assert ev == {"event": "scale_up", "alive": [0, 1, 2],
                          "ranks": {0: 0, 1: 1, 2: 2}}

            # a 4th joiner is refused: job at max_nnodes
            with pytest.raises(RuntimeError, match="max_nnodes"):
                ElasticManager(store, -1, ttl=5.0, min_nodes=2,
                               max_nodes=3).claim_slot()

            # EXECUTE the scale-up relaunch: 3 nodes through the launcher
            master = f"127.0.0.1:{_free_port()}"
            out_dir = str(tmp_path / "out")
            os.makedirs(out_dir)
            procs = [
                _launch_node(new_rank, len(ev["ranks"]), master,
                             os.path.join(ASSETS, "rank_echo_worker.py"),
                             str(tmp_path), out_dir)
                for new_rank in ev["ranks"].values()]
            _wait_and_assert_ok(procs, tmp_path, timeout=120, nnodes=3)
            got = {open(os.path.join(out_dir, f"rank.{r}")).read()
                   for r in range(3)}
            assert got == {"0/3", "1/3", "2/3"}

            # LEAVE below quorum: nodes 1 and 2 age out -> 1 alive < min=2
            # -> HOLD (no relaunch map), the reference's pause semantics
            m0.heartbeat()   # the launcher run above outlived the 5s TTL
            store.set("heartbeat/1", str(time.time() - 100))
            store.set("heartbeat/2", str(time.time() - 100))
            ev2 = m0.watch_once(current=[0, 1, 2])
            assert ev2 == {"event": "hold", "alive": [0], "ranks": None}
            # node 1 rejoins -> quorum restored -> scale-in relaunch map
            m1.heartbeat()
            ev3 = m0.watch_once(current=[0, 1, 2])
            assert ev3 == {"event": "scale_in", "alive": [0, 1],
                           "ranks": {0: 0, 1: 1}}
        finally:
            store.close()


class TestElasticLauncherScaleUp:
    """r5: the IN-LAUNCHER elastic path — a 2-node job launched with
    --nnodes 2:3 is JOINED mid-run by a third launcher (--elastic_join);
    the leader detects the new heartbeat, publishes generation 1 with 3
    nodes, every controller kills+respawns its workers with the new
    ranks, and the job completes. No test-harness orchestration of the
    relaunch: the controllers do it themselves."""

    def test_third_node_joins_running_job(self, tmp_path):
        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)

        def launch(node_rank, extra):
            env = dict(os.environ)
            env["MH_OUT"] = out_dir
            env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
            return subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--nnodes", "2:3", "--node_rank", str(node_rank),
                 "--nproc_per_node", "1", "--master", master,
                 "--log_dir", str(tmp_path / f"node{node_rank}"),
                 "--rdzv_timeout", "120", "--elastic_ttl", "20",
                 *extra,
                 os.path.join(ASSETS, "elastic_worker.py")],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        founders = [launch(r, []) for r in range(2)]
        # wait for the 2-node generation-0 markers
        deadline = time.time() + 90
        while time.time() < deadline:
            if all(os.path.exists(os.path.join(out_dir, f"g0.{r}of2"))
                   for r in range(2)):
                break
            time.sleep(0.25)
        else:
            outs = _wait_all(founders, timeout=5)
            raise AssertionError(f"gen-0 never came up: {outs}")

        # a third launcher JOINS the running job
        joiner = launch(2, ["--elastic_join"])
        outs = _wait_all(founders + [joiner], timeout=180)
        rcs = [p.returncode for p in founders + [joiner]]
        logs = [(tmp_path / f"node{r}" / f"workerlog.{x}").read_text(
                    errors="replace")
                for r in range(3)
                for x in range(3)
                if (tmp_path / f"node{r}" / f"workerlog.{x}").exists()]
        assert all(rc == 0 for rc in rcs), (rcs, outs, logs,
                                            sorted(os.listdir(out_dir)))
        # generation 1 spawned all three ranks at world size 3
        for r in range(3):
            assert os.path.exists(os.path.join(out_dir, f"g1.{r}of3")), \
                (sorted(os.listdir(out_dir)), outs)


class TestElasticRelaunch:
    def test_membership_loss_rank_regen_and_relaunch(self, tmp_path):
        from paddle_tpu.native import TCPStore
        from paddle_tpu.distributed.launch.controllers import ElasticManager

        store = TCPStore(host="127.0.0.1", port=0, is_master=True,
                         world_size=1, timeout=30)
        try:
            mgrs = [ElasticManager(store, i, ttl=5.0) for i in range(3)]
            for m in mgrs:
                m.heartbeat()
            assert mgrs[0].alive_nodes(3) == [0, 1, 2]
            assert not mgrs[0].membership_changed(3)
            # node 1 dies: age out its heartbeat
            store.set("heartbeat/1", str(time.time() - 100))
            assert mgrs[0].membership_changed(3)
            ranks = mgrs[0].regenerate_ranks(3)
            assert ranks == {0: 0, 2: 1}
        finally:
            store.close()

        # EXECUTE the relaunch with the regenerated ranks: the survivors
        # come back as a 2-node world with compacted node_ranks
        master = f"127.0.0.1:{_free_port()}"
        out_dir = str(tmp_path / "out")
        os.makedirs(out_dir)
        procs = [
            _launch_node(new_rank, len(ranks), master,
                         os.path.join(ASSETS, "rank_echo_worker.py"),
                         str(tmp_path), out_dir)
            for new_rank in ranks.values()]
        outs = _wait_all(procs, timeout=120)
        assert all(p.returncode == 0 for p in procs), (outs,)
        got = set()
        for r in range(2):
            f = os.path.join(out_dir, f"rank.{r}")
            assert os.path.exists(f), outs
            got.add(open(f).read())
        assert got == {"0/2", "1/2"}
