"""Declarative per-op test harness — the OpTest triangle of SURVEY §4.1.

The reference's backbone harness (test/legacy_test/op_test.py) checks every
operator three ways; this is the TPU-native equivalent:

  (a) check_output  — op(Tensors) vs a NumPy reference, across dtypes
  (b) check_grad    — tape-autograd gradients vs central finite differences
  (c) check_traced  — eager execution vs the traced/compiled (`jit.to_static`)
                      program (the reference's dygraph-vs-static sweep)

Usage: declare `OpCase`s and call `run_case` (see tests/test_op_suite.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import paddle_tpu as paddle


@dataclasses.dataclass
class OpCase:
    name: str
    op: Callable          # takes Tensors (+ attrs), returns Tensor(s)
    ref: Callable         # same signature over np arrays
    inputs: Sequence[np.ndarray]
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # indices of `inputs` whose gradient is checked (None = all float inputs)
    grad_inputs: Optional[Sequence[int]] = None
    rtol: float = 1e-5
    atol: float = 1e-6
    grad_rtol: float = 5e-2
    grad_atol: float = 5e-3
    check_grad: bool = True
    check_traced: bool = True
    # per-dtype sweeps: check_output re-run with inputs cast to these
    extra_dtypes: Sequence[str] = ()


def _as_tuple(x):
    return x if isinstance(x, (tuple, list)) else (x,)


def check_output(case: OpCase):
    outs = _as_tuple(case.op(*[paddle.to_tensor(i) for i in case.inputs],
                             **case.attrs))
    refs = _as_tuple(case.ref(*case.inputs, **case.attrs))
    assert len(outs) == len(refs), f"{case.name}: arity mismatch"
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o.numpy(), r, rtol=case.rtol,
                                   atol=case.atol, err_msg=case.name)
    for dt in case.extra_dtypes:
        cast = [i.astype(dt) if np.issubdtype(i.dtype, np.floating) else i
                for i in case.inputs]
        outs = _as_tuple(case.op(*[paddle.to_tensor(i) for i in cast],
                                 **case.attrs))
        refs = _as_tuple(case.ref(*[c.astype(np.float32) for c in cast],
                                  **case.attrs))
        # reduced-precision pass: compare against f32 reference loosely
        for o, r in zip(outs, refs):
            np.testing.assert_allclose(
                o.numpy().astype(np.float32), r, rtol=2e-2, atol=2e-2,
                err_msg=f"{case.name}[{dt}]")


def _scalarize(op, inputs_np, attrs, weights):
    """loss(inputs) = sum_k sum(op_out_k * w_k) — a fixed random projection
    so gradients of every output element are exercised."""
    def loss_np(*arrs):
        outs = _as_tuple(op(*[paddle.to_tensor(a) for a in arrs], **attrs))
        total = None
        for o, w in zip(outs, weights):
            term = (o * paddle.to_tensor(w)).sum()
            total = term if total is None else total + term
        return total
    return loss_np


def check_grad(case: OpCase, eps: float = 1e-3):
    grad_idx = case.grad_inputs
    if grad_idx is None:
        grad_idx = [i for i, a in enumerate(case.inputs)
                    if np.issubdtype(a.dtype, np.floating)]
    refs = _as_tuple(case.ref(*case.inputs, **case.attrs))
    rng = np.random.RandomState(0)
    weights = [rng.uniform(0.5, 1.5, np.shape(r)).astype(np.float32)
               for r in refs]
    loss = _scalarize(case.op, case.inputs, case.attrs, weights)

    # analytic grads via the tape
    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in case.inputs]
    outs = _as_tuple(case.op(*tensors, **case.attrs))
    total = None
    for o, w in zip(outs, weights):
        term = (o * paddle.to_tensor(w)).sum()
        total = term if total is None else total + term
    total.backward()

    for i in grad_idx:
        analytic = tensors[i].grad
        assert analytic is not None, f"{case.name}: no grad for input {i}"
        analytic = analytic.numpy()
        # numeric central differences on a sample of elements (full sweep on
        # small inputs, random sample on large — OpTest does the same)
        a = case.inputs[i].astype(np.float64)
        flat_n = a.size
        idxs = (range(flat_n) if flat_n <= 64 else
                rng.choice(flat_n, 24, replace=False))
        for fi in idxs:
            pert = case.inputs[i].copy().astype(np.float64)
            orig = pert.flat[fi]
            h = max(eps, eps * abs(orig))
            pert.flat[fi] = orig + h
            args_p = list(case.inputs); args_p[i] = pert.astype(np.float32)
            lp = float(loss(*args_p).numpy())
            pert.flat[fi] = orig - h
            args_m = list(case.inputs); args_m[i] = pert.astype(np.float32)
            lm = float(loss(*args_m).numpy())
            numeric = (lp - lm) / (2 * h)
            got = analytic.flat[fi]
            denom = max(abs(numeric), abs(got), 1.0 / case.grad_rtol)
            assert abs(numeric - got) <= (
                case.grad_atol + case.grad_rtol * denom), (
                f"{case.name}: grad input {i} elem {fi}: "
                f"analytic {got} vs numeric {numeric}")


def check_traced(case: OpCase):
    from paddle_tpu import jit

    def fn(*ts):
        return case.op(*ts, **case.attrs)

    traced = jit.to_static(fn)
    tensors = [paddle.to_tensor(a) for a in case.inputs]
    eager = _as_tuple(fn(*tensors))
    comp = _as_tuple(traced(*tensors))
    for e, c in zip(eager, comp):
        np.testing.assert_allclose(c.numpy(), e.numpy(), rtol=1e-6,
                                   atol=1e-6,
                                   err_msg=f"{case.name}: traced != eager")


def run_case(case: OpCase):
    check_output(case)
    if case.check_grad:
        check_grad(case)
    if case.check_traced:
        check_traced(case)
