"""Flash-attention routing + kernel parity (SURVEY §4.1: Pallas-vs-XLA
reference checks; on the CPU suite the routing must fall back cleanly, the
chip-side parity runs in verify/bench scripts)."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.flash_attention import (sdpa, sdpa_reference,
                                            _largest_dividing_block)


def test_block_size_contract():
    assert _largest_dividing_block(512) == 512
    assert _largest_dividing_block(640) == 128   # 640 % 512 != 0
    assert _largest_dividing_block(768) == 384
    assert _largest_dividing_block(100) == 0
    assert _largest_dividing_block(2048) == 512


def test_sdpa_routes_to_reference_on_cpu():
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 128, 4, 64), jnp.float32)
    k = jnp.asarray(rng.randn(2, 128, 4, 64), jnp.float32)
    v = jnp.asarray(rng.randn(2, 128, 4, 64), jnp.float32)
    out = sdpa(q, k, v, causal=True)
    ref = sdpa_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_f_sdpa_uses_routing():
    from paddle_tpu.nn import functional as F
    rng = np.random.RandomState(1)
    q = paddle.to_tensor(rng.randn(1, 64, 2, 32).astype(np.float32))
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    ref = sdpa_reference(q._data, q._data, q._data, causal=True)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-5)
