"""Shape/layout manipulation ops (ref surface: python/paddle/tensor/manipulation.py).

XLA note: everything here is static-shape by construction; the few genuinely
dynamic-shape APIs (masked_select, nonzero, unique) execute eagerly on host
values and raise under tracing, matching SURVEY §7.2's bucketing stance.
"""

from __future__ import annotations

import builtins
from typing import List, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.dtypes import convert_dtype
from ..core.tensor import Tensor

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "concat", "stack", "split", "chunk", "unbind", "unstack",
    "transpose", "moveaxis", "tile", "expand", "expand_as", "broadcast_to",
    "cast", "slice", "gather", "gather_nd", "scatter", "scatter_nd_add",
    "index_select", "index_add", "index_put", "take_along_axis",
    "put_along_axis", "roll", "flip", "rot90", "repeat_interleave", "where",
    "masked_select", "masked_fill", "nonzero", "unique", "strided_slice",
    "as_strided", "view", "tensor_split", "atleast_1d", "atleast_2d",
    "atleast_3d", "broadcast_tensors", "crop", "pad_nd",
]


def _is_traced(x) -> bool:
    return isinstance(x._data, jax.core.Tracer)


def reshape(x, shape, name=None) -> Tensor:
    if isinstance(shape, Tensor):
        shape = [int(s) for s in np.asarray(shape._data)]
    shape = [int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape]
    return apply("reshape", lambda a: jnp.reshape(a, shape), [x])


def reshape_(x, shape, name=None) -> Tensor:
    return x._inplace_from(reshape(x._snapshot(), shape))


def view(x, shape_or_dtype, name=None) -> Tensor:
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply("view_dtype",
                 lambda a: jax.lax.bitcast_convert_type(
                     a, convert_dtype(shape_or_dtype)), [x])


def flatten(x, start_axis=0, stop_axis=-1, name=None) -> Tensor:
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shape = x.shape
    new_shape = shape[:s] + [int(np.prod(shape[s:e + 1]) or 1)] + shape[e + 1:]
    return reshape(x, new_shape)


def squeeze(x, axis=None, name=None) -> Tensor:
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return apply("squeeze", lambda a: jnp.squeeze(a, axis=ax), [x])


def squeeze_(x, axis=None, name=None) -> Tensor:
    return x._inplace_from(squeeze(x._snapshot(), axis))


def unsqueeze(x, axis, name=None) -> Tensor:
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]
    def impl(a):
        out = a
        for ax in sorted(ax_ % (out.ndim + 1) for ax_ in axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply("unsqueeze", impl, [x])


def unsqueeze_(x, axis, name=None) -> Tensor:
    return x._inplace_from(unsqueeze(x._snapshot(), axis))


def concat(x: Sequence[Tensor], axis=0, name=None) -> Tensor:
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply("concat", lambda *arrs: jnp.concatenate(arrs, axis=axis), list(x))


def stack(x: Sequence[Tensor], axis=0, name=None) -> Tensor:
    return apply("stack", lambda *arrs: jnp.stack(arrs, axis=axis), list(x))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {axis} length {dim} is not divisible by "
                f"{num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_minus = sizes.count(-1)
        if n_minus:
            rest = dim - builtins.sum(s for s in sizes if s != -1)
            sizes = [rest // n_minus if s == -1 else s for s in sizes]
        if builtins.sum(sizes) != dim:
            raise ValueError(
                f"split: sections {sizes} do not sum to axis length {dim}")
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    def impl(a):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis)
                     for o, s in zip(offsets, sizes))
    outs = apply("split", impl, [x])
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    dim = x.shape[axis]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, extra = divmod(dim, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
        return split(x, sizes, axis)
    idxs = [0] + list(num_or_indices) + [dim]
    sizes = [idxs[i + 1] - idxs[i] for i in range(len(idxs) - 1)]
    return split(x, sizes, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    def impl(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))
    return list(apply("unbind", impl, [x]))


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def transpose(x, perm, name=None) -> Tensor:
    perm = [int(p) for p in perm]
    return apply("transpose", lambda a: jnp.transpose(a, perm), [x])


def moveaxis(x, source, destination, name=None) -> Tensor:
    return apply("moveaxis", lambda a: jnp.moveaxis(a, source, destination), [x])


def tile(x, repeat_times, name=None) -> Tensor:
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(v) for v in np.asarray(repeat_times._data)]
    return apply("tile", lambda a: jnp.tile(a, repeat_times), [x])


def expand(x, shape, name=None) -> Tensor:
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape._data)]
    tgt = []
    xs = x.shape
    pad = len(shape) - len(xs)
    for i, s in enumerate(shape):
        if s == -1:
            tgt.append(xs[i - pad] if i >= pad else 1)
        else:
            tgt.append(int(s))
    return apply("expand", lambda a: jnp.broadcast_to(a, tgt), [x])


def expand_as(x, y, name=None) -> Tensor:
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None) -> Tensor:
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [expand(t, list(out_shape)) for t in inputs]


def atleast_1d(*xs, name=None):
    outs = [x if x.ndim >= 1 else reshape(x, [1]) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs, name=None):
    outs = []
    for x in xs:
        while x.ndim < 2:
            x = unsqueeze(x, 0)
        outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs, name=None):
    outs = []
    for x in xs:
        while x.ndim < 3:
            x = unsqueeze(x, x.ndim)
        outs.append(x)
    return outs[0] if len(outs) == 1 else outs


def cast(x, dtype) -> Tensor:
    dt = convert_dtype(dtype)
    return apply("cast", lambda a: a.astype(dt), [x])


def slice(x, axes, starts, ends, name=None) -> Tensor:
    def _v(v):
        return int(v.item()) if isinstance(v, Tensor) else int(v)
    axes = [int(a) for a in axes]
    starts = [_v(s) for s in (starts if isinstance(starts, (list, tuple)) else [starts])]
    ends = [_v(e) for e in (ends if isinstance(ends, (list, tuple)) else [ends])]
    def impl(a):
        idx = [slice_builtin(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = slice_builtin(s, e)
        return a[tuple(idx)]
    return apply("slice", impl, [x])


slice_builtin = __import__("builtins").slice


def strided_slice(x, axes, starts, ends, strides, name=None) -> Tensor:
    def impl(a):
        idx = [slice_builtin(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[int(ax)] = slice_builtin(int(s), int(e), int(st))
        return a[tuple(idx)]
    return apply("strided_slice", impl, [x])


def as_strided(x, shape, stride, offset=0, name=None) -> Tensor:
    def impl(a):
        flat = a.reshape(-1)
        idx = np.zeros(shape, dtype=np.int64) + offset
        for dim, (sz, st) in enumerate(zip(shape, stride)):
            r = np.arange(sz) * st
            idx += r.reshape([-1 if i == dim else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]
    return apply("as_strided", impl, [x])


def gather(x, index, axis=0, name=None) -> Tensor:
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    if idx.ndim == 0:
        idx = idx[None]
    return apply("gather", lambda a: jnp.take(a, idx, axis=axis), [x])


def gather_nd(x, index, name=None) -> Tensor:
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    def impl(a):
        k = idx.shape[-1]
        return a[tuple(jnp.moveaxis(idx, -1, 0))] if k > 0 else a
    return apply("gather_nd", impl, [x])


def scatter(x, index, updates, overwrite=True, name=None) -> Tensor:
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    if overwrite:
        return apply("scatter",
                     lambda a, u: a.at[idx].set(u), [x, updates])
    return apply("scatter_add",
                 lambda a, u: a.at[idx].add(u), [x, updates])


def scatter_nd_add(x, index, updates, name=None) -> Tensor:
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    def impl(a, u):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return apply("scatter_nd_add", impl, [x, updates])


def index_select(x, index, axis=0, name=None) -> Tensor:
    return gather(x, index, axis)


def index_add(x, index, axis, value, name=None) -> Tensor:
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    def impl(a, v):
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)
    return apply("index_add", impl, [x, value])


def index_put(x, indices, value, accumulate=False, name=None) -> Tensor:
    idx = tuple(i._data if isinstance(i, Tensor) else jnp.asarray(i)
                for i in indices)
    def impl(a, v):
        return a.at[idx].add(v) if accumulate else a.at[idx].set(v)
    return apply("index_put", impl, [x, value])


def take_along_axis(arr, indices, axis, broadcast=True, name=None) -> Tensor:
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
    return apply("take_along_axis",
                 lambda a: jnp.take_along_axis(a, idx, axis=axis), [arr])


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None) -> Tensor:
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
    def impl(a, v):
        v = jnp.broadcast_to(v, idx.shape) if np.ndim(v) else jnp.full(idx.shape, v, a.dtype)
        ix = _along_axis_index(idx, axis % a.ndim, a.ndim)
        if reduce == "assign":
            return a.at[ix].set(v)
        if reduce in ("add",):
            return a.at[ix].add(v)
        if reduce in ("multiply", "mul"):
            return a.at[ix].multiply(v)
        raise ValueError(f"unsupported reduce mode: {reduce}")
    vt = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    return apply("put_along_axis", impl, [arr, vt])


def _along_axis_index(idx, axis, ndim):
    ix = []
    for d in range(ndim):
        if d == axis % ndim:
            ix.append(idx)
        else:
            shape = [1] * ndim
            shape[d] = idx.shape[d]
            ix.append(jnp.arange(idx.shape[d]).reshape(shape))
    return tuple(ix)


def roll(x, shifts, axis=None, name=None) -> Tensor:
    return apply("roll", lambda a: jnp.roll(a, shifts, axis=axis), [x])


def flip(x, axis, name=None) -> Tensor:
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda a: jnp.flip(a, axis=tuple(ax)), [x])


def rot90(x, k=1, axes=(0, 1), name=None) -> Tensor:
    return apply("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [x])


def repeat_interleave(x, repeats, axis=None, name=None) -> Tensor:
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    if not isinstance(r, int):
        # per-element repeats give dynamic shapes; require host execution
        if _is_traced(x):
            raise NotImplementedError(
                "repeat_interleave with tensor repeats is dynamic-shape; "
                "not supported under tracing (XLA static shapes)")
        total = int(np.asarray(r).sum())
        out = np.repeat(np.asarray(x._data), np.asarray(r), axis=axis)
        return Tensor(jnp.asarray(out))
    return apply("repeat_interleave",
                 lambda a: jnp.repeat(a, r, axis=axis), [x])


def where(condition, x=None, y=None, name=None):
    cond = condition._data if isinstance(condition, Tensor) else jnp.asarray(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    def impl(a, b):
        return jnp.where(cond, a, b)
    xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))
    return apply("where", impl, [xt, yt])


def masked_fill(x, mask, value, name=None) -> Tensor:
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    v = value._data if isinstance(value, Tensor) else value
    return apply("masked_fill",
                 lambda a: jnp.where(m, jnp.asarray(v, a.dtype), a), [x])


def masked_select(x, mask, name=None) -> Tensor:
    """Dynamic-shape: eager-only (host fallback); raises under tracing."""
    if _is_traced(x):
        raise NotImplementedError(
            "masked_select has data-dependent output shape; not supported "
            "under tracing — use where()/masked_fill for traced code")
    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(np.asarray(x._data)[m]))


def nonzero(x, as_tuple=False):
    if _is_traced(x):
        raise NotImplementedError(
            "nonzero has data-dependent output shape; not supported under "
            "tracing")
    nz = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n)[:, None]) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    if _is_traced(x):
        raise NotImplementedError(
            "unique has data-dependent output shape; not supported under "
            "tracing")
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def crop(x, shape=None, offsets=None, name=None) -> Tensor:
    offs = offsets or [0] * x.ndim
    shp = shape or x.shape
    def impl(a):
        idx = tuple(slice_builtin(int(o), int(o) + int(s))
                    for o, s in zip(offs, shp))
        return a[idx]
    return apply("crop", impl, [x])


def pad_nd(x, pad, mode="constant", value=0.0, name=None) -> Tensor:
    """N-d pad with paddle's flat pad list convention (last dim first)."""
    nd = x.ndim
    pairs = [(0, 0)] * nd
    half = len(pad) // 2
    for i in range(half):
        d = nd - 1 - i
        pairs[d] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    def impl(a):
        if mode == "constant":
            return jnp.pad(a, pairs, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        return jnp.pad(a, pairs, mode=jmode)
    return apply("pad", impl, [x])


# ---------------------------------------------------------------------------
# long-tail manipulation surface
# ---------------------------------------------------------------------------
def permute(x, perm, name=None) -> Tensor:
    return transpose(x, perm)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None) -> Tensor:
    return apply("diagonal",
                 lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2), [x])


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None) -> Tensor:
    """Batched diagonal-matrix construction (last dim becomes a diagonal)."""
    def impl(a):
        n = a.shape[-1]
        size = n + builtins.abs(offset)
        rows = jnp.arange(n) + (-offset if offset < 0 else 0)
        cols = jnp.arange(n) + (offset if offset > 0 else 0)
        out = jnp.zeros(a.shape[:-1] + (size, size), a.dtype)
        out = out.at[..., rows, cols].set(a)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out
    return apply("diag_embed", impl, [x])


def hsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=2)


def unflatten(x, axis, shape, name=None) -> Tensor:
    ax = axis % x.ndim
    shape = list(shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = x.shape[ax] // known
    new_shape = list(x.shape[:ax]) + shape + list(x.shape[ax + 1:])
    return reshape(x, new_shape)


def unfold(x, axis, size, step, name=None) -> Tensor:
    """Sliding windows along `axis`: axis → num_windows, window size appended
    as the last dim (torch/paddle Tensor.unfold semantics)."""
    ax = axis % x.ndim
    L = x.shape[ax]
    starts = np.arange(0, L - size + 1, step)
    idx = jnp.asarray(starts[:, None] + np.arange(size)[None, :])
    def impl(a):
        y = jnp.take(a, idx, axis=ax)  # axis expands to (n_win, size)
        return jnp.moveaxis(y, ax + 1, -1)
    return apply("unfold", impl, [x])


def select_scatter(x, values, axis, index, name=None) -> Tensor:
    def impl(a, v):
        m = jnp.moveaxis(a, axis, 0)
        m = m.at[index].set(v.astype(a.dtype))
        return jnp.moveaxis(m, 0, axis)
    return apply("select_scatter", impl, [x, values])


def slice_scatter(x, value, axes, starts, ends, strides=None, name=None) -> Tensor:
    strides = strides or [1] * len(axes)
    # NB: `slice` the builtin is shadowed by paddle's slice() op above
    sls = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        sls[ax] = builtins.slice(st, en, sd)
    def impl(a, v):
        return a.at[tuple(sls)].set(v.astype(a.dtype))
    return apply("slice_scatter", impl, [x, value])


def masked_scatter(x, mask, value, name=None) -> Tensor:
    """Fill True positions of mask with consecutive elements of value.
    Static-shape formulation (gather by prefix-sum) — traces fine."""
    def impl(a, m, v):
        mb = jnp.broadcast_to(m, a.shape)
        pos = jnp.cumsum(mb.ravel().astype(jnp.int32)) - 1
        vals = jnp.take(v.ravel(), jnp.clip(pos, 0, v.size - 1))
        return jnp.where(mb, vals.reshape(a.shape).astype(a.dtype), a)
    return apply("masked_scatter", impl, [x, mask, value])


def index_fill(x, index, axis, fill_value, name=None) -> Tensor:
    def impl(a, idx):
        m = jnp.moveaxis(a, axis, 0)
        m = m.at[idx].set(jnp.asarray(fill_value, a.dtype))
        return jnp.moveaxis(m, 0, axis)
    return apply("index_fill", impl, [x, index])


def take(x, index, mode="raise", name=None) -> Tensor:
    """Flat-index take with paddle's out-of-range modes."""
    if mode == "raise" and not _is_traced(x) and not _is_traced(index):
        n = int(np.prod(x.shape))
        idx_host = np.asarray(index._data if isinstance(index, Tensor)
                              else index)
        if idx_host.size and (idx_host.min() < -n or idx_host.max() >= n):
            raise ValueError(
                f"take index out of range for input with {n} elements")
    jmode = {"raise": "clip", "wrap": "wrap", "clip": "clip"}[mode]
    return apply("take",
                 lambda a, i: jnp.take(a.ravel(), i, mode=jmode), [x, index])


def multiplex(inputs, index, name=None) -> Tensor:
    """out[i] = inputs[index[i, 0]][i] (ref: multiplex op)."""
    def impl(idx, *arrs):
        stk = jnp.stack(arrs)  # [K, d0, ...]
        rows = idx.reshape(-1).astype(jnp.int32)
        return stk[rows, jnp.arange(stk.shape[1])]
    return apply("multiplex", impl, [index, *inputs])


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None) -> Tensor:
    """Vocab-shard label remap (ref: shard_index op, used by
    VocabParallelEmbedding/ParallelCrossEntropy data prep)."""
    shard_size = (index_num + nshards - 1) // nshards
    def impl(a):
        in_shard = (a // shard_size) == shard_id
        return jnp.where(in_shard, a % shard_size,
                         jnp.asarray(ignore_value, a.dtype))
    return apply("shard_index", impl, [input])


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, name=None):
    """Dynamic-shape: eager-only (host fallback); raises under tracing."""
    if _is_traced(x):
        raise NotImplementedError(
            "unique_consecutive has data-dependent output shape; not "
            "supported under tracing")
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    if axis is None:
        a = a.ravel()
        keep = np.concatenate([[True], a[1:] != a[:-1]])
        out = a[keep]
    else:
        moved = np.moveaxis(a, axis, 0)
        keep = np.concatenate(
            [[True],
             np.any(moved[1:] != moved[:-1],
                    axis=tuple(range(1, moved.ndim)))])
        out = np.moveaxis(moved[keep], 0, axis)
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        outs.append(Tensor(jnp.asarray(
            np.diff(np.append(np.nonzero(keep)[0], len(keep))))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def scatter_nd(index, updates, shape, name=None) -> Tensor:
    def impl(idx, upd):
        out = jnp.zeros(tuple(shape), upd.dtype)
        return out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply("scatter_nd", impl, [index, updates])


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def vander(x, n=None, increasing=False, name=None) -> Tensor:
    return apply("vander",
                 lambda a: jnp.vander(a, N=n, increasing=increasing), [x])


__all__ += ["permute", "diagonal", "diag_embed", "hsplit", "vsplit",
            "dsplit", "unflatten", "unfold", "select_scatter",
            "slice_scatter", "masked_scatter", "index_fill", "take",
            "multiplex", "shard_index", "unique_consecutive", "scatter_nd",
            "broadcast_shape", "vander"]
