"""Ragged mixed prefill+decode paged-attention kernel.

Reference capability: Ragged Paged Attention (arXiv 2604.15464) — ONE
`pallas_call` serves a mixed batch of prefill chunks and decode tokens
over the paged KV cache, replacing the engine's alternating
`_prefill_chunk` / `_decode` dispatches.

Layout: the step's new tokens ride in a FLAT buffer q [T, H, D] with
per-sequence row tables as scalar prefetch:

  - seq_start [S]:  first flat row of sequence i's new tokens;
  - num_tokens [S]: how many new tokens sequence i contributes this step
    (1 for a decode slot, the chunk length for a prefill row, 0 for an
    inactive slot — its rows emit zeros);
  - kv_lengths [S]: sequence i's KV length INCLUDING its new tokens
    (append-then-attend: the new K/V rows are already in the pages);
  - page_tables [S, pages_per_seq]: physical pages, sentinel entries
    clamped like pallas_paged._page_map.

Causality is per sequence over its new tokens: local token t (0-based)
attends KV positions 0 .. kv_lengths[i] - num_tokens[i] + t. A decode
row (num_tokens=1) therefore sees its whole context; a prefill chunk is
causal within the chunk and sees everything before it (shared-prefix
pages included).

Same machinery family as pallas_paged.py: grid (KV, S, pages), page
gather through the BlockSpec index_map (never materialized), GQA-native
[T*rep, D] query groups per KV head, online-softmax f32 scratch,
pl.when skips for dead pages/slots, interpret mode off-TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_paged import paged_kernel_eligible

# jax renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["ragged_paged_attention", "ragged_attention_reference",
           "ragged_kernel_eligible"]

_NEG = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def ragged_kernel_eligible(H: int, KV: int, D: int,
                           page_size: int) -> bool:
    """Same tiling constraints as the decode kernel: the [rows, D] query
    group wants MXU-friendly D; any page_size >= 8 works (masks handle
    partial pages and ragged chunk tails)."""
    return paged_kernel_eligible(H, KV, D, page_size)


def _ragged_page_map(h, i, j, ss, nt, kvl, tab, *, page_size,
                     total_pages):
    # clamp j to the last LIVE page of sequence i and the table value to
    # a real physical page: dead pages then re-reference the previous
    # block (Pallas elides the copy) and sentinel/-1 entries never emit
    # an out-of-range DMA, even though compute is pl.when-skipped
    jmax = jnp.maximum(kvl[i] - 1, 0) // page_size
    phys = jnp.clip(tab[i, jnp.minimum(j, jmax)], 0, total_pages - 1)
    return (h, phys, 0, 0)


def _ragged_kernel(ss_ref, nt_ref, kvl_ref, tab_ref,    # scalar prefetch
                   q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, rep, scale):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    # the whole [T*rep, D] output block stays resident for one KV head's
    # full (i, j) sweep; zero it once so inactive rows read as zeros and
    # each sequence's emit only merges its own rows
    @pl.when((i == 0) & (j == 0))
    def _zero_out():
        o_ref[:] = jnp.zeros_like(o_ref)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    start = ss_ref[i]
    nt = nt_ref[i]
    kvl = kvl_ref[i]
    rows = q_ref.shape[1]
    # flat token index of each query row ([T*rep, 1]: rep query heads of
    # one token are adjacent rows of the same KV head's group)
    tok = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // rep
    row_valid = (tok >= start) & (tok < start + nt)

    @pl.when((nt > 0) & (j * page_size < kvl))
    def _compute():
        q = q_ref[0]                                     # [T*rep, D]
        k = k_ref[0, 0]                                  # [psz, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [T*rep, psz]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        # local token t of this sequence attends positions <= limit
        limit = kvl - nt + (tok - start)
        masked = jnp.logical_not(row_valid & (pos <= limit))
        s = jnp.where(masked, _NEG, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(masked, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _emit():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        vals = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        o_ref[0] = jnp.where(row_valid, vals, o_ref[0])


def ragged_paged_attention(q, k_pages, v_pages, seq_start, num_tokens,
                           kv_lengths, page_tables,
                           scale: Optional[float] = None):
    """q [T, H, D] flat new-token buffer; k/v_pages [KV, total_pages,
    page_size, D]; seq_start/num_tokens/kv_lengths [S] int32;
    page_tables [S, pages_per_seq] int32. Sequences own DISJOINT row
    ranges [seq_start[i], seq_start[i]+num_tokens[i]); rows covered by
    no sequence return zeros. Returns [T, H, D].

    VMEM residency note: the whole [T*rep, D] query group and output
    block of one KV head stay resident across that head's page sweep —
    T is an engine-step batch (max_slots + prefill_chunk), not a full
    sequence, so the block is small by construction."""
    T, H, D = q.shape
    KV, total, psz, _ = k_pages.shape
    rep = H // KV
    S, nj = page_tables.shape
    if scale is None:
        scale = D ** -0.5
    # [T, H, D] -> [KV, T*rep, D]: one grid cell owns one KV head's
    # whole flat query group (rep rows per token, token-major)
    qg = (q.reshape(T, KV, rep, D).transpose(1, 0, 2, 3)
          .reshape(KV, T * rep, D))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,      # seq_start, num_tokens, kv_lengths,
        grid=(KV, S, nj),           # page tables
        in_specs=[
            pl.BlockSpec((1, T * rep, D),
                         lambda h, i, j, ss, nt, kvl, tab: (h, 0, 0)),
            pl.BlockSpec((1, 1, psz, D), functools.partial(
                _ragged_page_map, page_size=psz, total_pages=total)),
            pl.BlockSpec((1, 1, psz, D), functools.partial(
                _ragged_page_map, page_size=psz, total_pages=total)),
        ],
        out_specs=pl.BlockSpec(
            (1, T * rep, D),
            lambda h, i, j, ss, nt, kvl, tab: (h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((T * rep, D), jnp.float32),
                        pltpu.VMEM((T * rep, 1), jnp.float32),
                        pltpu.VMEM((T * rep, 1), jnp.float32)],
    )
    # i is sequential ("arbitrary"): every sequence read-modify-writes
    # the same resident output block
    cparams = _CompilerParams(
        dimension_semantics=("parallel", "arbitrary", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, page_size=psz, rep=rep,
                          scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KV, T * rep, D), q.dtype),
        compiler_params=cparams,
        interpret=_interpret(),
    )(seq_start.astype(jnp.int32), num_tokens.astype(jnp.int32),
      kv_lengths.astype(jnp.int32), page_tables.astype(jnp.int32),
      qg, k_pages, v_pages)
    return (out.reshape(KV, T, rep, D).transpose(1, 0, 2, 3)
            .reshape(T, H, D))


def ragged_attention_reference(q, k_pages, v_pages, seq_start,
                               num_tokens, kv_lengths, page_tables,
                               scale: Optional[float] = None):
    """Plain-XLA oracle with the same ragged semantics (full-softmax,
    gathered pages, jnp.repeat GQA — everything the kernel avoids)."""
    T, H, D = q.shape
    KV, total, psz, _ = k_pages.shape
    rep = H // KV
    S, nj = page_tables.shape
    if scale is None:
        scale = D ** -0.5
    ss = seq_start.astype(jnp.int32)
    nt = num_tokens.astype(jnp.int32)
    kvl = kv_lengths.astype(jnp.int32)
    tabs = jnp.clip(page_tables.astype(jnp.int32), 0, total - 1)
    Tk = nj * psz
    ks = k_pages[:, tabs].transpose(1, 0, 2, 3, 4).reshape(S, KV, Tk, D)
    vs = v_pages[:, tabs].transpose(1, 0, 2, 3, 4).reshape(S, KV, Tk, D)
    kr = jnp.repeat(ks, rep, axis=1)                      # [S, H, Tk, D]
    vr = jnp.repeat(vs, rep, axis=1)
    logits = jnp.einsum("thd,shld->shtl", q.astype(jnp.float32),
                        kr.astype(jnp.float32)) * scale   # [S,H,T,Tk]
    t_idx = jnp.arange(T)
    rv = (t_idx[None, :] >= ss[:, None]) & \
        (t_idx[None, :] < (ss + nt)[:, None])             # [S, T]
    limit = (kvl - nt)[:, None] + (t_idx[None, :] - ss[:, None])
    pos = jnp.arange(Tk)
    mask = rv[:, None, :, None] & \
        (pos[None, None, None, :] <= limit[:, None, :, None])
    logits = jnp.where(mask, logits, _NEG)
    m = jnp.max(logits, -1, keepdims=True)
    p = jnp.where(mask, jnp.exp(logits - m), 0.0)
    l = jnp.sum(p, -1, keepdims=True)
    o = jnp.einsum("shtl,shld->shtd", p / jnp.where(l == 0.0, 1.0, l),
                   vr.astype(jnp.float32))                # [S, H, T, D]
    out = jnp.sum(jnp.where(rv[:, None, :, None], o, 0.0), axis=0)
    return out.transpose(1, 0, 2).astype(q.dtype)


# certification (ROADMAP item 5 / paddlelint PK105)
from .oracles import register_oracle  # noqa: E402

register_oracle(
    "ragged_paged_attention", kernel=ragged_paged_attention,
    reference=ragged_attention_reference,
    parity_test="tests/test_ragged_kernel.py::TestRaggedKernelParity")
