"""paddle.vision.datasets parity (ref: python/paddle/vision/datasets/).

This environment has zero egress, so the download paths the reference uses
are unavailable; datasets load from local files when present and `FakeData`
provides deterministic synthetic data for tests/benchmarks (the reference's
own unit tests use small fake batches the same way).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10"]


class FakeData(Dataset):
    """Deterministic synthetic image dataset."""

    def __init__(self, num_samples=64, image_shape=(3, 32, 32),
                 num_classes=10, transform: Optional[Callable] = None,
                 seed=0):
        self.n = num_samples
        self.shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self.images = rng.rand(num_samples, *self.shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes, num_samples) \
            .astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


class MNIST(Dataset):
    """Loads the standard IDX files from ``root`` (no download)."""

    FILES = {
        "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }

    def __init__(self, root: str = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False):
        self.transform = transform
        if root is None or not os.path.isdir(root):
            raise RuntimeError(
                "MNIST requires local IDX files (zero-egress environment): "
                "pass root= pointing at train-images-idx3-ubyte.gz etc.")
        img_f, lab_f = self.FILES["train" if mode == "train" else "test"]
        self.images = self._read_images(os.path.join(root, img_f))
        self.labels = self._read_labels(os.path.join(root, lab_f))

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            _, n, h, w = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, h, w)

    def _read_labels(self, path):
        with self._open(path) as f:
            struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar10(Dataset):
    """Loads the python-pickle CIFAR-10 batches from ``root``."""

    def __init__(self, root: str = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False):
        import pickle
        self.transform = transform
        if root is None or not os.path.isdir(root):
            raise RuntimeError(
                "Cifar10 requires the local cifar-10-batches-py directory "
                "(zero-egress environment)")
        names = [f"data_batch_{i}" for i in range(1, 6)] \
            if mode == "train" else ["test_batch"]
        xs, ys = [], []
        for nm in names:
            with open(os.path.join(root, nm), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8)
                      .reshape(-1, 3, 32, 32))
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs)
        self.labels = np.asarray(ys, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


def _default_image_loader(path):
    """Load an image file to an HWC numpy array: .npy passthrough, PIL for
    the standard formats when installed, and a native binary-PPM/PGM
    fallback (8-bit and 16-bit, comment-tolerant) otherwise."""
    if path.endswith(".npy"):
        return np.load(path)
    if not path.endswith((".ppm", ".pgm")):
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError(
                f"no loader available for {path} (PIL not installed); "
                "provide loader=")
    else:   # PNM: exact native parse (keeps grayscale un-RGB-converted)
        with open(path, "rb") as f:
            def token():
                t = b""
                while True:
                    ch = f.read(1)
                    if not ch:
                        raise ValueError(f"truncated header in {path}")
                    if ch == b"#":          # comment to end of line
                        while f.read(1) not in (b"\n", b""):
                            pass
                        continue
                    if ch.isspace():
                        if t:
                            return t
                        continue
                    t += ch
            magic = token()
            w, h = int(token()), int(token())
            maxv = int(token())
            dt = np.uint8 if maxv < 256 else np.dtype(">u2")
            data = np.frombuffer(f.read(), dt)
            if magic == b"P6":
                return data.reshape(h, w, 3)
            if magic == b"P5":
                return data.reshape(h, w)
            raise ValueError(f"unsupported PNM magic {magic!r} in {path}")


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _walk_files(root, extensions, is_valid_file):
    """Recursive sorted file listing with the extension/predicate filter
    shared by DatasetFolder and ImageFolder."""
    exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fn in sorted(files):
            p = os.path.join(dirpath, fn)
            ok = is_valid_file(p) if is_valid_file else \
                fn.lower().endswith(exts)
            if ok:
                out.append(p)
    return out


class DatasetFolder(Dataset):
    """ref: paddle.vision.datasets.DatasetFolder — samples arranged as
    root/class_x/xxx.ext; classes sorted alphabetically to indices."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_image_loader
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for p in _walk_files(os.path.join(root, c), extensions,
                                 is_valid_file):
                self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """ref: paddle.vision.datasets.ImageFolder — flat/recursive listing of
    images under root, NO labels (returns [img])."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_image_loader
        self.transform = transform
        self.samples = _walk_files(root, extensions, is_valid_file)
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


__all__ += ["DatasetFolder", "ImageFolder", "IMG_EXTENSIONS"]
