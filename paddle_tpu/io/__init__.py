"""paddle.io parity: Dataset / DataLoader / samplers
(ref: python/paddle/io/ — dataloader with multiprocess workers, shared-mem
queues, DistributedBatchSampler).

TPU-native shape: the loader produces *host numpy batches*; device transfer
happens at the jit boundary (or via Trainer prefetch with sharded device_put)
— the analog of the reference's pin-memory + h2d stream. Worker parallelism
uses threads (numpy collation releases the GIL enough for IO-bound datasets);
a grain-backed loader can swap in transparently for heavy input pipelines.
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..framework.random import default_generator
from .. import resilience as _res

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "random_split", "Sampler",
           "SequenceSampler", "RandomSampler", "BatchSampler",
           "DistributedBatchSampler", "WeightedRandomSampler", "DataLoader",
           "default_collate_fn", "numpy_collate_fn", "get_worker_info"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Tensor]):
        self.tensors = list(tensors)
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset size")
    g = generator or default_generator
    perm = np.random.RandomState(g._seed).permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(default_generator._seed
                                    + default_generator._counter)
        default_generator._counter += 1
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.RandomState(default_generator._seed
                                    + default_generator._counter)
        default_generator._counter += 1
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks (ref:
    python/paddle/io/dataloader/batch_sampler.py). On TPU, num_replicas/rank
    default to the data-parallel submesh coordinates (per-host sharded input).
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as _env
            num_replicas = num_replicas if num_replicas is not None \
                else _env.get_world_size()
            rank = rank if rank is not None else _env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        n = len(dataset)
        if drop_last:
            self.num_samples = n // self.nranks
        else:
            self.num_samples = (n + self.nranks - 1) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to be evenly divisible
        if not self.drop_last and len(indices) < self.total_size:
            indices += indices[: self.total_size - len(indices)]
        indices = indices[: self.total_size]
        local = indices[self.local_rank::self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class _WorkerInfo:
    def __init__(self, id_, num_workers, dataset):
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def numpy_collate_fn(batch: List[Any]):
    """Stack samples into HOST numpy arrays — the worker-process-safe
    collate (no jax/device touch; workers must never initialize the TPU
    client)."""
    first = batch[0]
    if isinstance(first, Tensor):
        return np.stack([np.asarray(b._data) for b in batch])
    if isinstance(first, np.ndarray):
        return np.stack(batch)
    if isinstance(first, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(first, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(first, (str, bytes)):
        return list(batch)
    if isinstance(first, dict):
        return {k: numpy_collate_fn([b[k] for b in batch]) for k in first}
    if isinstance(first, (tuple, list)):
        transposed = list(zip(*batch))
        return type(first)(numpy_collate_fn(list(s)) for s in transposed)
    raise TypeError(f"cannot collate type {type(first)}")


def _tensorize_tree(x):
    if isinstance(x, np.ndarray):
        return Tensor(x)
    if isinstance(x, dict):
        return {k: _tensorize_tree(v) for k, v in x.items()}
    if isinstance(x, (tuple, list)):
        return type(x)(_tensorize_tree(v) for v in x)
    return x


def default_collate_fn(batch: List[Any]):
    """Stack samples into device tensors (numpy-first, single h2d per field)."""
    return _tensorize_tree(numpy_collate_fn(batch))


class DataLoader:
    """ref: paddle.io.DataLoader. num_workers>0 prefetches batches off
    the training thread. Two worker modes:

      worker_mode="thread" (default fast path): one producer thread with
        a bounded queue — numpy collation releases the GIL, and device
        feeding is the usual bottleneck on TPU hosts;
      worker_mode="process": the reference's multiprocess workers
        (python/paddle/io/dataloader/worker.py) — forked worker
        processes each own a round-robin share of the batches, collate
        with the numpy-safe collate (never touching jax/the TPU client),
        and ship pickled arrays back over an mp queue; the parent
        restores batch order and converts to Tensors. Use it when
        __getitem__ transforms are CPU-bound python (the OCR/vision
        pipelines). Workers are seeded per-worker (base_seed + id) and
        run worker_init_fn(worker_id).
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, worker_mode: str = "thread",
                 mp_context: Optional[str] = None,
                 max_batch_retries: int = 0):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        # >0 tolerates transient __getitem__/collate failures: a failed
        # batch is re-fetched up to this many times before the error
        # propagates (resilience.loader_retries counts each retry)
        self.max_batch_retries = max(int(max_batch_retries), 0)
        self.prefetch_factor = max(prefetch_factor, 1)
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.use_shared_memory = use_shared_memory
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode {worker_mode!r}: expected "
                             "'thread' or 'process'")
        self.worker_mode = worker_mode
        # None (default) resolves per-iteration: "fork" while the parent
        # has NOT initialized a jax backend (cheap, nothing to pickle —
        # the reference's default), "spawn" once it has. Forking a
        # jax-initialized parent duplicates the client's locked mutexes
        # and cached device handles into the child — workers are
        # forbidden to touch device state (enforced in _process_worker)
        # but the runtime's own background threads make even innocent
        # forks flaky, so isolation wins. Under spawn the dataset,
        # collate_fn and worker_init_fn must be picklable. Pass "fork"/
        # "spawn"/"forkserver" explicitly to pin a context.
        self.mp_context = mp_context
        self.is_iterable = isinstance(dataset, IterableDataset)
        if worker_mode == "process" and self.is_iterable:
            raise NotImplementedError(
                "process workers support map-style datasets; shard an "
                "IterableDataset via get_worker_info with thread mode")
        if self.is_iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self.is_iterable:
            raise TypeError("IterableDataset has no definite length")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        it = iter(self.dataset)
        while True:
            batch = list(itertools.islice(it, self.batch_size))
            if not batch:
                return
            if len(batch) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn(batch)

    def _fetch(self, indices):
        rule = _res.inject("loader_raise")
        if rule is not None:
            raise _res.InjectedFault("loader_raise injected", rule)
        return self.collate_fn([self.dataset[i] for i in indices])

    def _fetch_retrying(self, indices):
        for attempt in range(self.max_batch_retries + 1):
            try:
                return self._fetch(indices)
            except Exception:
                if attempt >= self.max_batch_retries:
                    raise
                _res._count_loader_retry()

    def __iter__(self):
        if self.is_iterable:
            yield from self._iter_iterable()
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield self._fetch_retrying(indices)
            return
        if self.worker_mode == "process":
            yield from self._iter_processes()
            return
        # threaded prefetch pipeline
        q: _queue.Queue = _queue.Queue(self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                if self.worker_init_fn is not None:
                    self.worker_init_fn(0)
                _worker_info.info = _WorkerInfo(0, self.num_workers,
                                               self.dataset)
                for indices in self.batch_sampler:
                    q.put(self._fetch_retrying(indices))
            except BaseException as e:  # propagate to consumer
                q.put(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    def _resolve_mp_context(self) -> str:
        if self.mp_context is not None:
            return self.mp_context
        from jax._src import xla_bridge
        return "spawn" if getattr(xla_bridge, "_backends", None) else "fork"

    def _iter_processes(self):
        import multiprocessing as mp
        ctx = mp.get_context(self._resolve_mp_context())
        batches = list(self.batch_sampler)
        if not batches:
            return
        W = min(self.num_workers, len(batches))
        base_seed = int(np.random.randint(0, 2 ** 31 - 1))
        result_q = ctx.Queue(maxsize=W * self.prefetch_factor)
        task_q = ctx.Queue()
        user_collate = None if self.collate_fn is default_collate_fn \
            else self.collate_fn
        procs = []
        for w in range(W):
            p = ctx.Process(
                target=_process_worker,
                args=(self.dataset, user_collate, task_q,
                      w, W, base_seed, self.worker_init_fn, result_q,
                      self.use_shared_memory, _res._FAULT_FLAG.value),
                daemon=True)
            p.start()
            procs.append(p)
        try:
            total = len(batches)
            # outstanding-capacity window: only ~W*prefetch_factor index
            # batches are in flight at once, so a fast worker cannot run
            # the whole epoch ahead of a slow one — `pending` (and shm
            # segments) stay bounded by the window, not the dataset
            window = W * (self.prefetch_factor + 1)
            dispatched = 0
            while dispatched < min(window, total):
                task_q.put((dispatched, batches[dispatched]))
                dispatched += 1
            if dispatched == total:
                for _ in range(W):
                    task_q.put(None)
            pending: dict = {}
            exited: set = set()
            nxt = 0
            while nxt < total:
                if nxt in pending:
                    item = pending.pop(nxt)
                else:
                    try:
                        got = result_q.get(
                            timeout=self.timeout if self.timeout
                            else 5.0)
                    except _queue.Empty:
                        if self.timeout:
                            raise RuntimeError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s waiting for batch "
                                f"{nxt} (num_workers={W}, "
                                f"worker_mode='process')") from None
                        # liveness poll: a worker that died without its
                        # sentinel (segfault / OOM-kill) would otherwise
                        # block this get() forever
                        dead = [p for i, p in enumerate(procs)
                                if i not in exited and not p.is_alive()]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker pid={dead[0].pid} "
                                f"died (exitcode {dead[0].exitcode}) "
                                f"before finishing its share") from None
                        continue
                    if got[0] is None:       # worker finished / failed
                        w, err = got[1]
                        exited.add(w)
                        if err is not None:
                            raise err
                        if len(exited) == W and nxt not in pending \
                                and nxt < total:
                            raise RuntimeError(
                                "dataloader workers exited before "
                                f"producing batch {nxt}")
                        continue
                    if got[0] != nxt:
                        pending[got[0]] = got[1]
                        continue
                    item = got[1]
                # one batch consumed -> refill the dispatch window
                if dispatched < total:
                    task_q.put((dispatched, batches[dispatched]))
                    dispatched += 1
                    if dispatched == total:
                        for _ in range(W):
                            task_q.put(None)
                if isinstance(item, _BatchError):
                    # the worker failed this batch but stayed alive;
                    # re-fetch inline in the parent when a retry budget
                    # exists, else surface the worker's error
                    if self.max_batch_retries <= 0:
                        raise RuntimeError(
                            f"DataLoader worker failed batch {nxt}: "
                            f"{item.err}")
                    _res._count_loader_retry()
                    samples = [self.dataset[i] for i in batches[nxt]]
                    item = (user_collate or numpy_collate_fn)(samples)
                else:
                    item = _shm_decode(item)
                yield item if user_collate is not None \
                    else _tensorize_tree(item)
                nxt += 1
        finally:
            # early exit: children may never drain task_q; don't let the
            # parent's queue feeder thread block interpreter shutdown
            task_q.cancel_join_thread()
            task_q.close()
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)
            # early exit / error: unlink shm segments of batches never
            # consumed. NO queue drain here — get_nowait can block
            # forever on a truncated pickle a terminated worker left in
            # the pipe; the pid-scoped sweep below covers queued AND
            # never-enqueued segments, and pickle-mode queue leftovers
            # hold no resources
            for it in pending.values():
                _shm_discard(it)
            if self.use_shared_memory:
                import glob as _glob
                import os as _os
                for p in procs:
                    for path in _glob.glob(f"/dev/shm/ppio{p.pid}_*"):
                        try:
                            _os.unlink(path)
                        except OSError:
                            pass


class _ShmBatch:
    """A collated batch whose array leaves live in ONE shared-memory
    segment (ref: python/paddle/io/dataloader use_shared_memory — the
    reference ships _array_to_share_memory_tensor; here the stdlib
    SharedMemory block is the transport). Only the (name, metadata)
    tuple crosses the queue; the parent maps + copies + unlinks."""

    def __init__(self, shm_name, leaves, treedef):
        self.shm_name = shm_name
        self.leaves = leaves      # [(offset, shape, dtype_str) | raw obj]
        self.treedef = treedef    # nested structure with _Leaf markers


class _Leaf:
    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


def _shm_encode(item, name=None):
    """Pack the numpy leaves of a collated tree into one shm segment.
    `name` makes the segment attributable (ppio<pid>_<bid>) so the
    parent can sweep segments a terminated worker never handed over."""
    from multiprocessing import shared_memory
    arrays = []

    def strip(x):
        if isinstance(x, np.ndarray):
            if x.dtype.hasobject:
                return x  # PyObject pointers can't cross processes:
                          # object arrays stay on the pickle path
            arrays.append(x)
            return _Leaf(len(arrays) - 1)
        if isinstance(x, dict):
            return {k: strip(v) for k, v in x.items()}
        if isinstance(x, (tuple, list)):
            return type(x)(strip(v) for v in x)
        return x
    tree = strip(item)
    if not arrays:
        return item
    total = sum(int(a.nbytes) for a in arrays)
    shm = shared_memory.SharedMemory(create=True, size=max(total, 1),
                                     name=name)
    metas = []
    off = 0
    for a in arrays:
        view = np.ndarray(a.shape, a.dtype, buffer=shm.buf, offset=off)
        view[...] = a
        metas.append((off, a.shape, str(a.dtype)))
        off += int(a.nbytes)
    shm.close()
    # ownership transfers to the parent (it unlinks after copying):
    # unregister from THIS process's resource tracker or it warns about
    # (and on exit double-unlinks) a segment it no longer owns
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    return _ShmBatch(shm.name, metas, tree)


def _shm_discard(item):
    """Unlink an unconsumed _ShmBatch segment (early-exit cleanup)."""
    if isinstance(item, _ShmBatch):
        from multiprocessing import shared_memory
        try:
            shm = shared_memory.SharedMemory(name=item.shm_name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass


def _shm_decode(item):
    if not isinstance(item, _ShmBatch):
        return item
    from multiprocessing import shared_memory
    shm = shared_memory.SharedMemory(name=item.shm_name)
    try:
        def restore(x):
            if isinstance(x, _Leaf):
                off, shape, dt = item.leaves[x.idx]
                view = np.ndarray(shape, np.dtype(dt), buffer=shm.buf,
                                  offset=off)
                return view.copy()  # own the memory before unlink
            if isinstance(x, dict):
                return {k: restore(v) for k, v in x.items()}
            if isinstance(x, (tuple, list)):
                return type(x)(restore(v) for v in x)
            return x
        return restore(item.treedef)
    finally:
        shm.close()
        shm.unlink()


def _has_tensor_leaf(x):
    """True if any leaf of a sample tree is a device Tensor (the guard
    must walk tuples/dicts — the common dataset return shapes — not
    just the top level)."""
    if isinstance(x, Tensor):
        return True
    if isinstance(x, dict):
        return any(_has_tensor_leaf(v) for v in x.values())
    if isinstance(x, (tuple, list)):
        return any(_has_tensor_leaf(v) for v in x)
    return False


class _BatchError:
    """Picklable marker a process worker ships in place of a batch it
    failed to produce — the worker itself stays alive for later tasks."""

    __slots__ = ("err",)

    def __init__(self, err: str):
        self.err = err


def _process_worker(dataset, user_collate, task_q, worker_id,
                    num_workers, base_seed, init_fn, out_q,
                    use_shared_memory=True, fault_spec=""):
    """Worker-process body: seed, run init_fn, then pull (batch_idx,
    indices) tasks from the shared task queue until a None stop token.
    Sends (global_batch_idx, collated_numpy) tuples — array leaves ride
    a shared-memory segment when use_shared_memory — then a
    (None, (worker_id, exception_or_None)) sentinel."""
    import random as _random
    err = None
    try:
        if fault_spec:
            # spawned workers don't inherit the parent's FLAGS state the
            # way forked ones do — re-arm worker-targeted fault rules
            _res.set_fault_spec(fault_spec)
        np.random.seed((base_seed + worker_id) % (2 ** 32))
        _random.seed(base_seed + worker_id)
        _worker_info.info = _WorkerInfo(worker_id, num_workers, dataset)
        if init_fn is not None:
            init_fn(worker_id)
        collate = user_collate if user_collate is not None \
            else numpy_collate_fn
        while True:
            task = task_q.get()
            if task is None:
                break
            bid, indices = task
            try:
                rule = _res.inject("loader_raise", worker=worker_id)
                if rule is not None:
                    raise _res.InjectedFault("loader_raise injected", rule)
                samples = [dataset[i] for i in indices]
                for s in samples:
                    if _has_tensor_leaf(s):
                        # converting an inherited device array in a
                        # forked child touches the (fork-unsafe)
                        # runtime — fail loudly instead of deadlocking
                        raise RuntimeError(
                            "process workers require host (numpy/"
                            "python) samples; this dataset returned a "
                            "device Tensor — convert to numpy in "
                            "__getitem__ or use worker_mode='thread'")
                batch = collate(samples)
                if use_shared_memory:
                    import os as _os
                    batch = _shm_encode(batch,
                                        name=f"ppio{_os.getpid()}_{bid}")
            except Exception as e:  # per-task: ship a marker, stay alive
                out_q.put((bid, _BatchError(repr(e))))
                continue
            out_q.put((bid, batch))
    except BaseException as e:  # noqa: BLE001 — shipped to the parent
        err = e
    out_q.put((None, (worker_id, err)))
