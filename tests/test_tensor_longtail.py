"""Long-tail tensor API sweep (the ~700-function reference surface,
SURVEY §2.2 'Tensor API' row) — numerics vs NumPy/SciPy references."""

import numpy as np
import pytest
from scipy import special as sps

import paddle_tpu as paddle

R = np.random.RandomState(7)


def T(a):
    return paddle.to_tensor(np.asarray(a))


def test_special_functions():
    x = R.uniform(0.5, 3.0, (3, 4)).astype(np.float32)
    np.testing.assert_allclose(paddle.digamma(T(x)).numpy(), sps.digamma(x),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.lgamma(T(x)).numpy(), sps.gammaln(x),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.i0(T(x)).numpy(), sps.i0(x), rtol=1e-4)
    np.testing.assert_allclose(paddle.i1(T(x)).numpy(), sps.i1(x), rtol=1e-4)
    np.testing.assert_allclose(paddle.polygamma(T(x), 1).numpy(),
                               sps.polygamma(1, x), rtol=1e-3)
    np.testing.assert_allclose(paddle.sinc(T(x)).numpy(), np.sinc(x),
                               rtol=1e-5, atol=1e-6)


def test_binary_math_tail():
    a = R.randn(3, 4).astype(np.float32)
    b = R.uniform(0.5, 2, (3, 4)).astype(np.float32)
    np.testing.assert_allclose(paddle.hypot(T(a), T(b)).numpy(),
                               np.hypot(a, b), rtol=1e-6)
    np.testing.assert_allclose(paddle.logaddexp(T(a), T(b)).numpy(),
                               np.logaddexp(a, b), rtol=1e-5)
    np.testing.assert_allclose(paddle.nextafter(T(a), T(b)).numpy(),
                               np.nextafter(a, b))
    np.testing.assert_allclose(
        paddle.ldexp(T(a), T(np.full((3, 4), 2, np.int32))).numpy(),
        np.ldexp(a, np.full((3, 4), 2)))
    np.testing.assert_allclose(paddle.floor_mod(T(a), T(b)).numpy(),
                               np.mod(a, b), rtol=1e-5, atol=1e-6)


def test_reductions_tail():
    x = R.randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(paddle.count_nonzero(T(x > 0)).numpy(),
                               np.count_nonzero(x > 0))
    np.testing.assert_allclose(
        paddle.logcumsumexp(T(x), axis=1).numpy(),
        np.logaddexp.accumulate(x, axis=1), rtol=1e-4)
    np.testing.assert_allclose(paddle.trapezoid(T(x), axis=1).numpy(),
                               np.trapezoid(x, axis=1), rtol=1e-5)


def test_linalg_tail():
    a = R.randn(2, 3, 4).astype(np.float32)
    b = R.randn(2, 4, 5).astype(np.float32)
    np.testing.assert_allclose(paddle.bmm(T(a), T(b)).numpy(), a @ b,
                               rtol=1e-4, atol=1e-5)
    m = R.randn(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    np.testing.assert_allclose(paddle.inverse(T(m)).numpy(),
                               np.linalg.inv(m), rtol=1e-3, atol=1e-4)
    v = R.randn(3).astype(np.float32)
    np.testing.assert_allclose(paddle.mv(T(m), T(v)).numpy(), m @ v,
                               rtol=1e-4, atol=1e-5)
    i = R.randn(2, 5).astype(np.float32)
    x2 = R.randn(2, 3).astype(np.float32)
    y2 = R.randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        paddle.addmm(T(i), T(x2), T(y2), beta=0.5, alpha=2.0).numpy(),
        0.5 * i + 2.0 * (x2 @ y2), rtol=1e-4, atol=1e-5)
    # cdist vs scipy-style loop
    xa = R.randn(4, 3).astype(np.float32)
    xb = R.randn(5, 3).astype(np.float32)
    ref = np.sqrt(((xa[:, None] - xb[None]) ** 2).sum(-1))
    np.testing.assert_allclose(paddle.cdist(T(xa), T(xb)).numpy(), ref,
                               rtol=1e-4, atol=1e-5)
    refp = ref if False else np.sqrt(((xa[:, None] - xa[None]) ** 2).sum(-1))
    iu = np.triu_indices(4, 1)
    np.testing.assert_allclose(paddle.pdist(T(xa)).numpy(), refp[iu],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.tensordot(T(a), T(b[0]), axes=1).numpy()[0],
        np.tensordot(a[0], b[0], axes=1), rtol=1e-4, atol=1e-5)


def test_manipulation_tail():
    x = R.randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(paddle.diagonal(T(x)).numpy(), np.diagonal(x))
    d = R.randn(2, 3).astype(np.float32)
    de = paddle.diag_embed(T(d))
    assert de.shape == [2, 3, 3]
    np.testing.assert_allclose(de.numpy()[1], np.diag(d[1]))
    de_off = paddle.diag_embed(T(d), offset=1)
    assert de_off.shape == [2, 4, 4]
    parts = paddle.hsplit(T(x), 2)
    assert [p.shape for p in parts] == [[4, 3], [4, 3]]
    parts = paddle.vsplit(T(x), 2)
    assert [p.shape for p in parts] == [[2, 6], [2, 6]]
    uf = paddle.unflatten(T(x), 1, [2, 3])
    assert uf.shape == [4, 2, 3]
    w = paddle.unfold(T(np.arange(10, dtype=np.float32)), 0, 4, 2)
    np.testing.assert_allclose(w.numpy()[1], [2, 3, 4, 5])
    ss = paddle.select_scatter(T(np.zeros((3, 4), np.float32)),
                               T(np.ones(4, np.float32)), 0, 1)
    np.testing.assert_allclose(ss.numpy()[1], np.ones(4))
    sl = paddle.slice_scatter(T(np.zeros((3, 4), np.float32)),
                              T(np.ones((3, 2), np.float32)),
                              axes=[1], starts=[1], ends=[3])
    np.testing.assert_allclose(sl.numpy()[:, 1:3], np.ones((3, 2)))
    fi = paddle.index_fill(T(x), T(np.array([0, 2])), 0, -1.0)
    assert (fi.numpy()[[0, 2]] == -1).all()
    tk = paddle.take(T(x), T(np.array([0, 7])))
    np.testing.assert_allclose(tk.numpy(), x.ravel()[[0, 7]])
    np.testing.assert_allclose(
        paddle.vander(T(np.array([1., 2., 3.], np.float32)), 3).numpy(),
        np.vander([1., 2., 3.], 3))
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]


def test_scatter_and_sharding_ops():
    out = paddle.scatter_nd(T(np.array([[0], [2], [0]], np.int64)),
                            T(np.array([1., 2., 3.], np.float32)), [4])
    np.testing.assert_allclose(out.numpy(), [4., 0., 2., 0.])
    si = paddle.shard_index(T(np.array([0, 5, 9, 3], np.int64)), 10, 2, 1)
    np.testing.assert_allclose(si.numpy(), [-1, 0, 4, -1])
    mx = paddle.multiplex(
        [T(np.array([[1., 2.], [3., 4.]], np.float32)),
         T(np.array([[5., 6.], [7., 8.]], np.float32))],
        T(np.array([[1], [0]], np.int32)))
    np.testing.assert_allclose(mx.numpy(), [[5., 6.], [3., 4.]])


def test_masked_scatter_and_unique_consecutive():
    m = paddle.masked_scatter(
        T(np.zeros((2, 2), np.float32)),
        T(np.array([[True, False], [True, True]])),
        T(np.array([1., 2., 3.], np.float32)))
    np.testing.assert_allclose(m.numpy(), [[1., 0.], [2., 3.]])
    u = paddle.unique_consecutive(T(np.array([1, 1, 2, 2, 3, 1])))
    np.testing.assert_allclose(u.numpy(), [1, 2, 3, 1])
    u, inv, cnt = paddle.unique_consecutive(
        T(np.array([1, 1, 2, 3, 3])), return_inverse=True,
        return_counts=True)
    np.testing.assert_allclose(u.numpy(), [1, 2, 3])
    np.testing.assert_allclose(inv.numpy(), [0, 0, 1, 2, 2])
    np.testing.assert_allclose(cnt.numpy(), [2, 1, 2])


def test_search_attr_tail():
    seq = np.array([1., 3., 5., 7.], np.float32)
    x = np.array([0.5, 3., 6.], np.float32)
    np.testing.assert_allclose(paddle.bucketize(T(x), T(seq)).numpy(),
                               np.searchsorted(seq, x))
    np.testing.assert_allclose(
        paddle.bucketize(T(x), T(seq), right=True).numpy(),
        np.searchsorted(seq, x, side="right"))
    assert not paddle.is_empty(T(np.ones(3))).item()
    assert paddle.is_empty(paddle.zeros([0, 3])).item()
    assert paddle.tolist(T(np.array([1, 2]))) == [1, 2]


def test_complex_pack_roundtrip():
    pairs = R.randn(3, 2).astype(np.float32)
    c = paddle.as_complex(T(pairs))
    assert paddle.is_complex(c)
    back = paddle.as_real(c)
    np.testing.assert_allclose(back.numpy(), pairs)
    r = np.array([1.0, 2.0], np.float32)
    th = np.array([0.0, np.pi / 2], np.float32)
    pol = paddle.polar(T(r), T(th))
    np.testing.assert_allclose(pol.numpy(), r * np.exp(1j * th), atol=1e-6)


def test_tail_grads():
    x = paddle.to_tensor(np.array([1.5, 2.5], np.float32),
                         stop_gradient=False)
    y = paddle.lgamma(x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               sps.digamma([1.5, 2.5]), rtol=1e-4)
    a = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32),
                         stop_gradient=False)
    paddle.diagonal(a).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), np.eye(2))
