"""Deterministic proxy quality gates (VERDICT r1 item 10; SURVEY §6).

The reference's quality bars (BERT-base SST-2 92-93%, PP-OCRv4 accuracy)
need corpora this environment cannot download, so these gates train the
SAME model/loss/optimizer stacks on bundled synthetic data with fixed
seeds and assert accuracy thresholds — a regression tripwire for the
end-to-end training paths, not a replica of the published numbers
(documented in BASELINE.md rows 4-5).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def _sentiment_corpus(n, seed, seq=16):
    """Label = which polarity's words dominate; >=5-token margin keeps
    the task separable for a tiny counting transformer; token 1 = CLS."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, seq), np.int32)
    y = np.zeros((n,), np.int64)
    for i in range(n):
        while True:
            k = rng.randint(2, seq - 2)
            if abs(2 * k - (seq - 1)) >= 5:
                break
        pos = rng.choice(np.arange(10, 30), k)
        neg = rng.choice(np.arange(30, 50), seq - 1 - k)
        toks = np.concatenate([pos, neg])
        rng.shuffle(toks)
        X[i, 0] = 1
        X[i, 1:] = toks
        y[i] = int(k > (seq - 1 - k))
    return X, y


class TestClassificationGate:
    def test_bert_style_finetune_accuracy(self):
        """The SST-2 fine-tune path (model + CE loss + AdamW + scheduler)
        must reach >= 90% on the separable synthetic dev set."""
        from paddle_tpu.models.bert import (BertForSequenceClassification,
                                            bert_tiny_config)
        paddle.seed(0)
        cfg = bert_tiny_config(vocab_size=64, hidden_size=64,
                               num_hidden_layers=2, num_attention_heads=4,
                               intermediate_size=128,
                               max_position_embeddings=32, num_labels=2)
        model = BertForSequenceClassification(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=list(model.parameters()))
        Xtr, ytr = _sentiment_corpus(512, 0)
        Xdev, ydev = _sentiment_corpus(128, 1)
        B = 32
        for epoch in range(10):
            perm = np.random.RandomState(epoch).permutation(len(Xtr))
            for i in range(0, len(Xtr), B):
                idx = perm[i:i + B]
                loss, _ = model(paddle.to_tensor(Xtr[idx]),
                                labels=paddle.to_tensor(ytr[idx]))
                loss.backward()
                opt.step()
                opt.clear_grad()
        model.eval()
        logits = model(paddle.to_tensor(Xdev))
        pred = np.asarray(logits.numpy()).argmax(-1)
        acc = (pred == ydev).mean()
        assert acc >= 0.92, f"classification gate: dev acc {acc:.3f}"


def _glyph(d):
    """5x3 bitmap font for digits 0-9."""
    F = {
        0: "111101101101111", 1: "010110010010111",
        2: "111001111100111", 3: "111001111001111",
        4: "101101111001001", 5: "111100111001111",
        6: "111100111101111", 7: "111001001001001",
        8: "111101111101111", 9: "111101111001111",
    }
    return np.asarray([int(c) for c in F[d]], np.float32).reshape(5, 3)


def _rec_sample(rng, n_digits, H=32, pitch=16):
    """Render a digit string into a [1, H, W] image at fixed pitch.
    W = n_digits*16 gives the rec backbone (W/2 time axis) T=32 CTC
    steps for 4 labels."""
    W = n_digits * pitch
    img = np.zeros((1, H, W), np.float32)
    label = rng.randint(0, 10, n_digits)
    for i, d in enumerate(label):
        g = np.kron(_glyph(int(d)), np.ones((4, 4), np.float32))  # 20x12
        img[0, 6:26, i * pitch + 2:i * pitch + 14] = g
    return img, label


class TestOCRRecGate:
    def test_ctc_rec_char_accuracy(self):
        """The PP-OCR rec path (rec_mode backbone + CTC head + CTC loss)
        must read >= 80% of characters on the synthetic glyph set."""
        from paddle_tpu.models.ocr import PPOCRRec
        paddle.seed(1)
        n_digits = 4
        model = PPOCRRec(num_classes=11, in_channels=1)  # blank + 10
        opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=list(model.parameters()))
        rng = np.random.RandomState(0)
        B = 16

        def batch():
            imgs, labs = [], []
            for _ in range(B):
                im, lb = _rec_sample(rng, n_digits)
                imgs.append(im)
                labs.append(lb + 1)  # 0 is the CTC blank
            return (np.stack(imgs), np.stack(labs).astype(np.int32),
                    np.full((B,), n_digits, np.int32))

        for step in range(50):
            imgs, labs, lens = batch()
            logits = model(paddle.to_tensor(imgs))
            loss = model.loss(logits, paddle.to_tensor(labs),
                              paddle.to_tensor(lens))
            loss.backward()
            opt.step()
            opt.clear_grad()

        # recalibrate BatchNorm running stats against the FINAL weights
        # (they lag by ~1/(1-momentum) steps on this short schedule; the
        # update_bn pass torch's SWA uses for the same reason)
        from paddle_tpu.core import autograd as ag
        with ag.no_grad():
            for _ in range(15):
                imgs, _, _ = batch()
                model(paddle.to_tensor(imgs))

        # greedy CTC decode on a fresh eval batch
        rng_eval = np.random.RandomState(99)
        imgs, labs = [], []
        for _ in range(B):
            im, lb = _rec_sample(rng_eval, n_digits)
            imgs.append(im)
            labs.append(lb + 1)
        model.eval()
        logits = np.asarray(model(paddle.to_tensor(np.stack(imgs))).numpy())
        total = correct = 0
        for b in range(B):
            path = logits[b].argmax(-1)
            dec = []
            prev = -1
            for p in path:
                if p != prev and p != 0:
                    dec.append(int(p))
                prev = p
            ref = list(labs[b])
            L = min(len(dec), len(ref))
            correct += sum(1 for i in range(L) if dec[i] == ref[i])
            total += len(ref)
        acc = correct / total
        assert acc >= 0.80, f"ocr rec gate: char acc {acc:.3f}"


def _det_sample(rng, H=64, W=64):
    """1-2 textured (checkerboard) rectangles on a noisy background +
    DB targets (shrink map, border-band threshold map/mask) + GT boxes."""
    img = rng.uniform(0.0, 0.15, (1, H, W)).astype(np.float32)
    shrink = np.zeros((H, W), np.float32)
    tmap = np.zeros((H, W), np.float32)
    tmask = np.zeros((H, W), np.float32)
    boxes = []
    for _ in range(rng.randint(1, 3)):
        for _try in range(20):
            bh, bw = rng.randint(12, 22), rng.randint(14, 26)
            y0 = rng.randint(2, H - bh - 2)
            x0 = rng.randint(2, W - bw - 2)
            if all(x0 + bw + 4 < px0 or px1 + 4 < x0
                   or y0 + bh + 4 < py0 or py1 + 4 < y0
                   for (px0, py0, px1, py1) in boxes):
                break
        else:
            continue
        yy, xx = np.mgrid[0:bh, 0:bw]
        img[0, y0:y0 + bh, x0:x0 + bw] = \
            0.55 + 0.45 * (((yy // 2) + (xx // 2)) % 2)
        shrink[y0 + 2:y0 + bh - 2, x0 + 2:x0 + bw - 2] = 1.0
        band = np.zeros((H, W), np.float32)
        band[max(0, y0 - 2):y0 + bh + 2, max(0, x0 - 2):x0 + bw + 2] = 1.0
        band[y0 + 2:y0 + bh - 2, x0 + 2:x0 + bw - 2] = 0.0
        tmap = np.maximum(tmap, band * 0.55)
        tmask = np.maximum(tmask, band)
        boxes.append((x0, y0, x0 + bw - 1, y0 + bh - 1))
    return img, shrink, tmap, tmask, boxes


def _det_batch(rng, B):
    cols = [[], [], [], [], []]
    for _ in range(B):
        for c, v in zip(cols, _det_sample(rng)):
            c.append(v)
    return (np.stack(cols[0]), np.stack(cols[1]), np.stack(cols[2]),
            np.stack(cols[3]), cols[4])


def _iou(a, b):
    ix = max(0, min(a[2], b[2]) - max(a[0], b[0]) + 1)
    iy = max(0, min(a[3], b[3]) - max(a[1], b[1]) + 1)
    inter = ix * iy
    ua = ((a[2] - a[0] + 1) * (a[3] - a[1] + 1)
          + (b[2] - b[0] + 1) * (b[3] - b[1] + 1) - inter)
    return inter / ua


class TestOCRDetGate:
    def test_db_det_hmean(self):
        """The PP-OCR det path (backbone + DBFPN + DBHead + db_loss with
        OHEM/dice/threshold terms + db_postprocess) must reach hmean
        >= 0.70 at IoU 0.5 on the synthetic textured-box set (measured
        1.00 at these settings; the bar leaves seed/backend slack)."""
        from paddle_tpu.models.ocr import PPOCRDet, db_loss, db_postprocess
        paddle.seed(7)
        model = PPOCRDet(in_channels=1, scale=0.5)
        opt = paddle.optimizer.Adam(learning_rate=3e-3,
                                    parameters=list(model.parameters()))
        rng = np.random.RandomState(0)
        for step in range(60):
            imgs, shr, tm, tk, _ = _det_batch(rng, 8)
            out = model(paddle.to_tensor(imgs))["maps"]
            loss = db_loss(out, shr, np.ones_like(shr), tm, tk)
            loss.backward()
            opt.step()
            opt.clear_grad()
        from paddle_tpu.core import autograd as ag
        with ag.no_grad():   # recalibrate BN running stats (as rec gate)
            for _ in range(10):
                imgs, *_ = _det_batch(rng, 8)
                model(paddle.to_tensor(imgs))
        model.eval()
        rng_eval = np.random.RandomState(123)
        tp = fp = fn = 0
        for _ in range(4):
            imgs, _, _, _, gtb = _det_batch(rng_eval, 4)
            probs = np.asarray(
                model(paddle.to_tensor(imgs))["maps"].numpy())
            for b in range(4):
                pred = db_postprocess(probs[b, 0], thresh=0.5, min_area=16)
                matched = set()
                for pb in pred:
                    best, bi = 0.0, -1
                    for gi, g in enumerate(gtb[b]):
                        if gi not in matched and _iou(pb, g) > best:
                            best, bi = _iou(pb, g), gi
                    if best >= 0.5:
                        matched.add(bi)
                        tp += 1
                    else:
                        fp += 1
                fn += len(gtb[b]) - len(matched)
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        hmean = 2 * prec * rec / max(prec + rec, 1e-9)
        assert hmean >= 0.70, \
            f"ocr det gate: hmean {hmean:.3f} (p={prec:.3f} r={rec:.3f})"


class TestOCREndToEnd:
    def test_det_crop_rec_pipeline(self):
        """End-to-end PP-OCR pipeline (VERDICT r2 item 8): train det on
        64x64 scenes with a digit line at a random vertical offset, train
        rec on 32x64 line strips, then det -> band crop -> rec on fresh
        scenes must read >= 50% of characters (measured ~0.9 at these
        settings; the bar leaves slack for seed/backend drift)."""
        from paddle_tpu.models.ocr import (PPOCRDet, PPOCRRec, db_loss,
                                           db_postprocess)
        from paddle_tpu.core import autograd as ag
        paddle.seed(11)
        rng = np.random.RandomState(0)

        def line(rng):
            strip = np.zeros((20, 64), np.float32)
            label = rng.randint(0, 10, 4)
            for i, d in enumerate(label):
                g = np.kron(_glyph(int(d)), np.ones((4, 4), np.float32))
                strip[:, i * 16 + 2:i * 16 + 14] = g
            return strip, label

        def scene(rng):
            img = np.zeros((1, 64, 64), np.float32)
            strip, label = line(rng)
            dy = rng.randint(2, 42)
            img[0, dy:dy + 20] = strip
            shrink = np.zeros((64, 64), np.float32)
            shrink[dy + 2:dy + 18, 4:60] = 1.0
            return img, shrink, label

        det = PPOCRDet(in_channels=1, scale=0.5)
        dopt = paddle.optimizer.Adam(learning_rate=3e-3,
                                     parameters=list(det.parameters()))
        for _ in range(35):
            imgs, shr = zip(*((im, s) for im, s, _ in
                              (scene(rng) for _ in range(8))))
            imgs, shr = np.stack(imgs), np.stack(shr)
            out = det(paddle.to_tensor(imgs))["maps"]
            loss = db_loss(out, shr, np.ones_like(shr))
            loss.backward()
            dopt.step()
            dopt.clear_grad()

        rec = PPOCRRec(num_classes=11, in_channels=1)
        ropt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                      parameters=list(rec.parameters()))
        for _ in range(60):
            imgs, labs = [], []
            for _ in range(16):
                strip, lb = line(rng)
                im = np.zeros((1, 32, 64), np.float32)
                # random vertical offset: the det crop centers the line
                # only approximately, so rec must train offset-robust
                off = rng.randint(0, 12)
                im[0, off:off + 20] = strip
                imgs.append(im)
                labs.append(lb + 1)
            logits = rec(paddle.to_tensor(np.stack(imgs)))
            loss = rec.loss(logits, paddle.to_tensor(
                np.stack(labs).astype(np.int32)),
                paddle.to_tensor(np.full((16,), 4, np.int32)))
            loss.backward()
            ropt.step()
            ropt.clear_grad()

        with ag.no_grad():   # BN recalibration for both nets
            for _ in range(8):
                det(paddle.to_tensor(np.stack(
                    [scene(rng)[0] for _ in range(8)])))
                imgs = []
                for _ in range(16):
                    strip, _ = line(rng)
                    im = np.zeros((1, 32, 64), np.float32)
                    off = rng.randint(0, 12)
                    im[0, off:off + 20] = strip
                    imgs.append(im)
                rec(paddle.to_tensor(np.stack(imgs)))

        det.eval()
        rec.eval()
        rng_eval = np.random.RandomState(321)
        total = correct = found = 0
        N = 12
        for _ in range(N):
            im, _, label = scene(rng_eval)
            pm = np.asarray(det(paddle.to_tensor(im[None]))["maps"].numpy())
            boxes = db_postprocess(pm[0, 0], thresh=0.5, min_area=16)
            total += 4
            if not boxes:
                continue
            found += 1
            x0, y0, x1, y1 = max(
                boxes, key=lambda b: (b[2] - b[0]) * (b[3] - b[1]))
            top = int(np.clip((y0 + y1) // 2 - 16, 0, 32))
            crop = im[0, top:top + 32, :64]
            logits = np.asarray(
                rec(paddle.to_tensor(crop[None, None])).numpy())
            path = logits[0].argmax(-1)
            dec, prev = [], -1
            for p in path:
                if p != prev and p != 0:
                    dec.append(int(p) - 1)
                prev = p
            correct += sum(1 for i in range(min(len(dec), 4))
                           if dec[i] == label[i])
        assert found >= N - 2, f"det found only {found}/{N} lines"
        acc = correct / total
        assert acc >= 0.50, f"ocr e2e gate: char acc {acc:.3f}"
