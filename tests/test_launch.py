"""Launcher CLI: spawn, rank env, workerlogs, restart policy (SURVEY P14)."""

import os
import textwrap

from paddle_tpu.distributed.launch import launch


def _write_script(tmp_path, body):
    p = tmp_path / "trainer.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_spawn_two_ranks_env_and_logs(tmp_path):
    out = tmp_path / "env"
    out.mkdir()
    script = _write_script(tmp_path, f"""
        import os, json
        rank = os.environ["PADDLE_TRAINER_ID"]
        keep = {{k: v for k, v in os.environ.items()
                if k.startswith(("PADDLE_", "JAX_", "COORDINATOR"))}}
        with open(os.path.join({str(out)!r}, rank + ".json"), "w") as f:
            json.dump(keep, f)
        print("rank", rank, "done")
    """)
    rc = launch(["--nproc_per_node", "2", "--log_dir",
                 str(tmp_path / "log"), script])
    assert rc == 0
    import json
    e0 = json.load(open(out / "0.json"))
    e1 = json.load(open(out / "1.json"))
    assert e0["PADDLE_TRAINERS_NUM"] == "2"
    assert e1["PADDLE_TRAINER_ID"] == "1"
    assert e0["JAX_NUM_PROCESSES"] == "2"
    assert e0["COORDINATOR_ADDRESS"] == e1["COORDINATOR_ADDRESS"]
    assert len(e0["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 2
    # per-rank logs written (ref: workerlog.N)
    log0 = (tmp_path / "log" / "workerlog.0").read_text()
    assert "rank 0 done" in log0
    assert "rank 1 done" in (tmp_path / "log" / "workerlog.1").read_text()


def test_nonzero_exit_propagates(tmp_path):
    script = _write_script(tmp_path, """
        import sys
        sys.exit(3)
    """)
    rc = launch(["--nproc_per_node", "1", "--log_dir",
                 str(tmp_path / "log"), script])
    assert rc == 3


def test_restart_policy_recovers(tmp_path):
    sentinel = tmp_path / "came_before"
    script = _write_script(tmp_path, f"""
        import os, sys
        s = {str(sentinel)!r}
        if not os.path.exists(s):
            open(s, "w").write("x")
            sys.exit(1)   # first attempt fails
        print("second attempt ok")
    """)
    rc = launch(["--nproc_per_node", "1", "--max_restarts", "1",
                 "--log_dir", str(tmp_path / "log"), script])
    assert rc == 0
    assert "second attempt ok" in (tmp_path / "log" / "workerlog.0").read_text()


def test_elastic_manager_membership():
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.launch import ElasticManager
    s = TCPStore(is_master=True, world_size=2)
    try:
        m0 = ElasticManager(s, node_rank=0, ttl=5.0)
        m1 = ElasticManager(s, node_rank=1, ttl=5.0)
        m0.heartbeat()
        assert m0.alive_nodes(2) == [0]
        assert m0.membership_changed(expected=2)
        m1.heartbeat()
        assert m0.alive_nodes(2) == [0, 1]
        assert not m0.membership_changed(expected=2)
    finally:
        s.close()


def test_fault_injection_sigkill_worker_recovers(tmp_path):
    """Kill-a-worker fault injection (SURVEY §5.3): rank 1 SIGKILLs itself
    mid-run on the first attempt; the watch loop must tear the pod down and
    relaunch it, and the retry completes on all ranks."""
    sentinel = tmp_path / "already_died"
    done = tmp_path / "done"
    done.mkdir()
    script = _write_script(tmp_path, f"""
        import os, signal, time
        rank = os.environ["PADDLE_TRAINER_ID"]
        s = {str(sentinel)!r}
        if rank == "1" and not os.path.exists(s):
            open(s, "w").write("x")
            os.kill(os.getpid(), signal.SIGKILL)  # simulated host failure
        if rank == "0" and not os.path.exists(s):
            time.sleep(30)  # would hang forever if the pod were not torn down
        open(os.path.join({str(done)!r}, rank), "w").write("ok")
        print("rank", rank, "finished")
    """)
    import time
    t0 = time.time()
    rc = launch(["--nproc_per_node", "2", "--max_restarts", "1",
                 "--log_dir", str(tmp_path / "log"), script])
    assert rc == 0
    # rank 0's first attempt was killed by the controller (not after 30s)
    assert time.time() - t0 < 25
    assert "rank 0 finished" in (tmp_path / "log" / "workerlog.0").read_text()
    assert "rank 1 finished" in (tmp_path / "log" / "workerlog.1").read_text()
    # both ranks completed the retry attempt
    assert (done / "0").exists() and (done / "1").exists()
