"""Continuous-batching serving subsystem.

Three modules over the Pallas paged-decode kernel
(`ops/pallas_paged.py` via `ops.paged_attention`):

  - `block_allocator`: fixed pool of page_size-token KV blocks with
    refcounts, per-sequence page tables, copy-on-write prefix sharing,
    and utilization/fragmentation gauges;
  - `scheduler`: FCFS in-flight request scheduler — requests join
    mid-decode, leave instantly on EOS/max-tokens, with admission
    backpressure (`inference.Config.set_admission`) and per-request
    deadlines (`set_deadline` → falsy TimeoutResult partials);
  - `engine`: `ServingEngine.add_request/step/collect`, a fixed-shape
    jitted decode step (one compile per model/slot-count) plus chunked
    prefill, for the llama/moe, gpt and mla families.

See docs/SERVING.md ("Continuous batching") for sizing and usage.
"""

from typing import Any, Dict

from .. import observability as _obs
from .block_allocator import PageBlockAllocator
from .engine import ServingEngine
from .scheduler import Request, Scheduler

__all__ = ["ServingEngine", "Request", "Scheduler", "PageBlockAllocator",
           "metrics"]


def metrics() -> Dict[str, Any]:
    """The serving.engine.* slice of the registry snapshot."""
    return {k: v for k, v in _obs.registry().snapshot().items()
            if k.startswith("serving.engine.")}
