"""Tensor/sequence-parallel layers (ref: python/paddle/distributed/fleet/
meta_parallel/parallel_layers/mp_layers.py — SURVEY §2.3 P4/P5).

TPU-native mechanism: the layers ARE plain Linear/Embedding math; parallelism
comes from (a) a sharding spec attached to each weight (materialized by
fleet.distributed_model / shard_layer), and (b) sharding constraints on
activations. GSPMD then inserts exactly the collectives the reference codes
by hand (column: no comm fwd, allreduce bwd; row: allreduce fwd; vocab
embedding: masked lookup + allreduce; vocab-parallel CE: sharded logsumexp).
Layers degrade gracefully to single-device when no mesh is active.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from .mesh import get_mesh
from .auto_parallel import mark_sharding

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "annotate_sequence_parallel", "MP_AXIS"]

MP_AXIS = "mp"


def _mesh_has(axis: str) -> bool:
    m = get_mesh()
    return m is not None and axis in m.axis_names and m.shape[axis] > 1


class ColumnParallelLinear(nn.Layer):
    """Weight [in, out] sharded along out (columns) on the mp axis.
    gather_output=True adds a constraint forcing replicated output (GSPMD
    all-gathers); False leaves the activation sharded on its last dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(None, MP_AXIS)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_spec = P(MP_AXIS)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if _mesh_has(MP_AXIS):
            if self.gather_output:
                out = mark_sharding(out, *([None] * out.ndim))
            else:
                out = mark_sharding(out, *([None] * (out.ndim - 1) + [MP_AXIS]))
        return out


class RowParallelLinear(nn.Layer):
    """Weight [in, out] sharded along in (rows); input expected sharded on
    its last dim (input_is_parallel) — GSPMD inserts the fwd allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight._sharding_spec = P(MP_AXIS, None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_spec = P()  # replicated (added post-reduce)
        else:
            self.bias = None

    def forward(self, x):
        if _mesh_has(MP_AXIS) and not self.input_is_parallel:
            x = mark_sharding(x, *([None] * (x.ndim - 1) + [MP_AXIS]))
        out = F.linear(x, self.weight, self.bias)
        if _mesh_has(MP_AXIS):
            out = mark_sharding(out, *([None] * out.ndim))
        return out


class VocabParallelEmbedding(nn.Layer):
    """Embedding table sharded along vocab (dim 0) on mp (ref: range mask +
    allreduce in mp_layers.py; GSPMD derives the same from a gather on a
    sharded-operand)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight._sharding_spec = P(MP_AXIS, None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if _mesh_has(MP_AXIS):
            out = mark_sharding(out, *([None] * out.ndim))
        return out


class ParallelCrossEntropy(nn.Layer):
    """Vocab-sharded softmax cross-entropy (ref:
    c_softmax_with_cross_entropy_op.cu — the TP-CE that never materializes
    replicated logits). Keeping the logits' vocab dim sharded through
    logsumexp lets GSPMD reduce over the mp axis in f32."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        from ..core.dispatch import apply
        lab = label._data if isinstance(label, Tensor) else jnp.asarray(label)
        mp_on = _mesh_has(MP_AXIS)
        mesh = get_mesh()

        def impl(lg):
            lg32 = lg.astype(jnp.float32)
            if mp_on:
                lg32 = jax.lax.with_sharding_constraint(
                    lg32, NamedSharding(mesh, P(*([None] * (lg.ndim - 1)
                                                  + [MP_AXIS]))))
            lse = jax.scipy.special.logsumexp(lg32, axis=-1)
            lab2 = lab[..., 0] if lab.ndim == lg.ndim else lab
            picked = jnp.take_along_axis(
                lg32, lab2[..., None].astype(jnp.int32), axis=-1)[..., 0]
            loss = lse - picked
            mask = lab2 != self.ignore_index
            return jnp.where(mask, loss, jnp.zeros((), loss.dtype))[..., None]
        return apply("parallel_cross_entropy", impl, [logits])


import threading as _threading

_sp_state = _threading.local()


class suppress_sequence_parallel_annotations:
    """Trace-time switch: inside the timetable pipeline executor
    (distributed.pp_exec), per-block seq-dim resharding hints sit inside
    lax.switch branches, where the reshard can lower to a full-mesh
    collective-permute — a collective only some devices reach, i.e. a
    deadlock (the branch-collective rule). The executor suppresses the
    hints during its trace; GSPMD sharding propagation covers the region
    instead. Thread-local so concurrent traces don't leak suppression."""

    def __enter__(self):
        self._prev = getattr(_sp_state, "off", False)
        _sp_state.off = True
        return self

    def __exit__(self, *exc):
        _sp_state.off = self._prev
        return False


def annotate_sequence_parallel(x: Tensor, axis: str = MP_AXIS) -> Tensor:
    """Megatron-SP parity (ref: sequence_parallel_utils.py ScatterOp/
    GatherOp): shard the sequence dim (dim 1 of [B,S,H]) on the mp axis
    between blocks. One annotation replaces the allreduce→rs/ag rewrite."""
    if getattr(_sp_state, "off", False) or not _mesh_has(axis):
        return x
    spec = [None] * x.ndim
    spec[1] = axis
    return mark_sharding(x, *spec)
