"""ERNIE model family (SURVEY §2.4 config 3: ERNIE-3.0 encoder /
ERNIE-4.5-style MoE decoder).

Reference capability: PaddleNLP paddlenlp/transformers/ernie/ — a BERT-style
encoder with task-type embeddings (the ERNIE 3.0 distinguishing input), and
the ERNIE 4.5 generation = MoE decoder (built here as a config preset of
paddle_tpu.models.moe_llm)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from .bert import BertConfig, BertLayer
from .moe_llm import MoEConfig, MoEForCausalLM

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForMaskedLM", "ernie30_tiny_config", "ernie45_moe_config",
           "Ernie45MoEForCausalLM"]


class ErnieConfig(BertConfig):
    """BertConfig + task_type_vocab_size (ERNIE task embeddings) +
    use_task_id switch."""

    def __init__(self, task_type_vocab_size=3, use_task_id=True, **kw):
        super().__init__(**kw)
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id


def ernie30_tiny_config(**kw) -> ErnieConfig:
    base = dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=128,
                max_position_embeddings=128)
    base.update(kw)
    return ErnieConfig(**base)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, c: ErnieConfig):
        super().__init__()
        init = I.Normal(0.0, c.initializer_range)
        self.word_embeddings = nn.Embedding(c.vocab_size, c.hidden_size,
                                            padding_idx=c.pad_token_id)
        self.word_embeddings.weight._data = init(
            [c.vocab_size, c.hidden_size], "float32")
        self.position_embeddings = nn.Embedding(c.max_position_embeddings,
                                                c.hidden_size)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size,
                                                  c.hidden_size)
        if c.use_task_id:
            self.task_type_embeddings = nn.Embedding(c.task_type_vocab_size,
                                                     c.hidden_size)
        else:
            self.task_type_embeddings = None
        self.layer_norm = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros_like(input_ids._data))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.task_type_embeddings is not None:
            if task_type_ids is None:
                task_type_ids = Tensor(jnp.zeros_like(input_ids._data))
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask, task_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return loss, logits
        return logits


class ErnieForMaskedLM(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None, masked_positions=None):
        seq, _ = self.ernie(input_ids, token_type_ids,
                            attention_mask=attention_mask)
        h = self.layer_norm(F.gelu(self.transform(seq)))
        logits = self.decoder(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                labels.reshape([-1]), ignore_index=-100)
            return loss, logits
        return logits


def ernie45_moe_config(**kw) -> MoEConfig:
    """ERNIE 4.5-style MoE decoder preset (shared expert + fine-grained
    routed experts, aux-loss routing)."""
    base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, num_experts=8, top_k=2,
                moe_intermediate_size=64, shared_expert_intermediate_size=64,
                first_k_dense_replace=1)
    base.update(kw)
    return MoEConfig(**base)


class Ernie45MoEForCausalLM(MoEForCausalLM):
    """Alias class so checkpoints/configs can name the family explicitly."""
