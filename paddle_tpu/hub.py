"""paddle.hub parity (ref: python/paddle/hapi/hub.py — load models from
a hubconf.py). Local directories work fully; remote github/gitee sources
are refused (zero-egress environment)."""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir: str, source: str):
    if source != "local":
        raise NotImplementedError(
            f"hub source {source!r} needs network access (none in this "
            f"environment); clone the repo and use source='local'")
    return _load_hubconf(repo_dir)


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:
    mod = _resolve(repo_dir, source)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:
    mod = _resolve(repo_dir, source)
    entry = getattr(mod, model, None)
    if entry is None:
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}")
    return entry.__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    mod = _resolve(repo_dir, source)
    entry = getattr(mod, model, None)
    if entry is None:
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}")
    return entry(**kwargs)
