"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py).

Pickle-protocol state dicts with tensors converted to numpy on save and
restored as device tensors on load; nested containers and >4GB tensors are
handled by pickle protocol 4. Sharding-aware distributed checkpointing lives
in paddle_tpu.distributed.checkpoint (orbax/tensorstore-backed).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


class _TensorPayload:
    """Tag wrapper so load() knows which ndarrays were Tensors."""

    __slots__ = ("array", "stop_gradient")

    def __init__(self, array: np.ndarray, stop_gradient: bool):
        self.array = array
        self.stop_gradient = stop_gradient


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        a = np.asarray(obj._data)
        # bfloat16 has no numpy pickle support everywhere; view as uint16
        if obj._data.dtype == jnp.bfloat16:
            return _TensorPayload(a.view(np.uint16), obj.stop_gradient), "bf16"
        return _TensorPayload(a, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any) -> Any:
    if isinstance(obj, tuple) and len(obj) == 2 and isinstance(obj[0], _TensorPayload) \
            and obj[1] == "bf16":
        payload = obj[0]
        return Tensor(jnp.asarray(payload.array).view(jnp.bfloat16),
                      stop_gradient=payload.stop_gradient)
    if isinstance(obj, _TensorPayload):
        return Tensor(jnp.asarray(obj.array), stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    out = _unpack(obj)
    if return_numpy:
        def to_np(o):
            if isinstance(o, Tensor):
                return o.numpy()
            if isinstance(o, dict):
                return {k: to_np(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return type(o)(to_np(v) for v in o)
            return o
        return to_np(out)
    return out
