"""nn.Layer machinery, layers, functional, losses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(8, 2)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def test_layer_params_and_state_dict():
    paddle.seed(0)
    m = MLP()
    names = [n for n, _ in m.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    sd = m.state_dict()
    m2 = MLP()
    missing, unexpected = m2.set_state_dict(sd)
    assert not missing and not unexpected
    np.testing.assert_allclose(m2.fc1.weight.numpy(), m.fc1.weight.numpy())


def test_layer_forward_backward():
    paddle.seed(1)
    m = MLP()
    x = paddle.rand([3, 4])
    y = m(x)
    assert y.shape == [3, 2]
    loss = y.sum()
    loss.backward()
    for p in m.parameters():
        assert p.grad is not None, p.name


def test_train_eval_mode_dropout():
    m = nn.Dropout(0.5)
    x = paddle.ones([100])
    m.eval()
    np.testing.assert_allclose(m(x).numpy(), x.numpy())
    m.train()
    out = m(x)
    assert (out.numpy() == 0).any()


def test_sequential_and_layerlist():
    m = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    x = paddle.rand([2, 4])
    assert m(x).shape == [2, 2]
    ll = nn.LayerList([nn.Linear(3, 3) for _ in range(4)])
    assert len(list(ll.parameters())) == 8


def test_layer_hooks():
    m = nn.Linear(2, 2)
    calls = []
    h = m.register_forward_post_hook(lambda l, i, o: calls.append(1))
    m(paddle.rand([1, 2]))
    assert calls
    h.remove()


def test_layer_to_dtype():
    m = MLP()
    m.to(dtype="bfloat16")
    assert str(m.fc1.weight.dtype) == "bfloat16"
    m.float()
    assert m.fc1.weight.dtype == np.float32


def test_layernorm_matches_reference():
    x = paddle.rand([4, 10])
    ln = nn.LayerNorm(10)
    out = ln(x).numpy()
    a = x.numpy()
    ref = (a - a.mean(-1, keepdims=True)) / np.sqrt(a.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_rms_norm():
    x = paddle.rand([2, 8])
    rn = nn.RMSNorm(8)
    a = x.numpy()
    ref = a / np.sqrt((a ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(rn(x).numpy(), ref, rtol=1e-4, atol=1e-5)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.rand([4, 3, 5, 5]) * 2 + 1
    y = bn(x)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), 0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == y.shape


def test_conv2d_matches_manual():
    paddle.seed(3)
    conv = nn.Conv2D(2, 4, 3, padding=1)
    x = paddle.rand([1, 2, 8, 8])
    out = conv(x)
    assert out.shape == [1, 4, 8, 8]
    # compare against jax.lax reference directly
    ref = jax.lax.conv_general_dilated(
        x._data, conv.weight._data, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ref = ref + conv.bias._data.reshape(1, 4, 1, 1)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_pooling():
    x = paddle.arange(16, dtype="float32").reshape([1, 1, 4, 4])
    mp = nn.MaxPool2D(2, 2)
    np.testing.assert_allclose(mp(x).numpy().reshape(2, 2),
                               [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(2, 2)
    np.testing.assert_allclose(ap(x).numpy().reshape(2, 2),
                               [[2.5, 4.5], [10.5, 12.5]])


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor([[0, 1], [2, 0]], dtype="int32")
    out = emb(idx)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], np.zeros(4))


def test_cross_entropy_matches_jax():
    logits_np = np.random.RandomState(0).randn(6, 5).astype(np.float32)
    labels_np = np.array([0, 1, 2, 3, 4, 0])
    x = paddle.to_tensor(logits_np, stop_gradient=False)
    loss = F.cross_entropy(x, paddle.to_tensor(labels_np))
    lp = jax.nn.log_softmax(logits_np)
    ref = -lp[np.arange(6), labels_np].mean()
    assert loss.item() == pytest.approx(float(ref), rel=1e-5)
    loss.backward()
    assert x.grad is not None


def test_cross_entropy_ignore_index():
    logits = paddle.rand([4, 5])
    labels = paddle.to_tensor([0, -100, 2, -100])
    loss = F.cross_entropy(logits, labels)
    l0 = F.cross_entropy(logits[0:1], paddle.to_tensor([0]))
    l2 = F.cross_entropy(logits[2:3], paddle.to_tensor([2]))
    assert loss.item() == pytest.approx((l0.item() + l2.item()) / 2, rel=1e-5)


def test_bce_with_logits_stable():
    z = paddle.to_tensor([100.0, -100.0], stop_gradient=False)
    lab = paddle.to_tensor([1.0, 0.0])
    loss = F.binary_cross_entropy_with_logits(z, lab)
    assert np.isfinite(loss.item())
    assert loss.item() == pytest.approx(0.0, abs=1e-6)


def test_multihead_attention():
    paddle.seed(5)
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.rand([2, 6, 16])
    out = mha(x)
    assert out.shape == [2, 6, 16]
    out.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder():
    enc = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(d_model=16, nhead=2, dim_feedforward=32),
        num_layers=2)
    enc.eval()
    x = paddle.rand([2, 5, 16])
    assert enc(x).shape == [2, 5, 16]


def test_sdpa_causal():
    q = paddle.rand([1, 4, 2, 8])
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    assert out.shape == [1, 4, 2, 8]
    # first position attends only to itself → equals v[0]
    np.testing.assert_allclose(out.numpy()[0, 0], q.numpy()[0, 0],
                               rtol=1e-4, atol=1e-5)


def test_ctc_loss_simple():
    # T=4, B=1, C=3 (blank=0); label "12"
    logits = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 1, 3).astype(np.float32),
        stop_gradient=False)
    labels = paddle.to_tensor(np.array([[1, 2]], np.int32))
    loss = F.ctc_loss(logits, labels, paddle.to_tensor([4]),
                      paddle.to_tensor([2]))
    assert np.isfinite(loss.item()) and loss.item() > 0
    loss.backward()
    assert np.isfinite(logits.grad.numpy()).all()


def test_ctc_loss_vs_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    T, B, C, L = 8, 3, 5, 3
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, L)).astype(np.int32)
    in_len = np.array([8, 7, 6])
    lab_len = np.array([3, 2, 3])

    ours = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                      reduction="none")

    t_logp = torch.log_softmax(torch.tensor(logits), dim=-1)
    ref = torch.nn.functional.ctc_loss(
        t_logp, torch.tensor(labels.astype(np.int64)),
        torch.tensor(in_len), torch.tensor(lab_len),
        blank=0, reduction="none")
    np.testing.assert_allclose(ours.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)
