"""Dtype table and default-dtype state.

Parity surface: paddle.dtype names (ref: paddle/phi/common/data_type.h upstream
layout; python surface paddle.set_default_dtype). bfloat16 is first-class on TPU.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp
import numpy as np

__all__ = [
    "bool_", "uint8", "int8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64", "complex64", "complex128",
    "float8_e4m3fn", "float8_e5m2",
    "convert_dtype", "set_default_dtype", "get_default_dtype",
    "is_floating_dtype",
]

bool_ = jnp.bool_
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}


# 64-bit dtypes demote to 32-bit unless jax x64 is enabled — the TPU-native
# policy (matches jax; avoids per-call truncation warnings while keeping the
# reference's "int64"/"float64" dtype names accepted everywhere)
_DEMOTE_64 = {
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def long_dtype() -> np.dtype:
    """The index/long dtype actually in effect (int32 on TPU by default)."""
    return np.dtype(np.int64) if _x64_enabled() else np.dtype(np.int32)


def convert_dtype(dtype) -> np.dtype:
    """Normalize a dtype spec (string, np/jnp dtype, python type) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise TypeError(f"unsupported dtype name: {dtype!r}")
        d = np.dtype(_NAME_TO_DTYPE[dtype])
    elif dtype is bool:
        d = np.dtype(np.bool_)
    elif dtype is int:
        d = np.dtype(np.int64)
    elif dtype is float:
        d = np.dtype(_state.default)
    else:
        d = np.dtype(dtype)
    if d in _DEMOTE_64 and not _x64_enabled():
        d = _DEMOTE_64[d]
    return d


class _State(threading.local):
    def __init__(self):
        self.default = np.dtype(np.float32)


_state = _State()


def set_default_dtype(dtype) -> None:
    d = convert_dtype(dtype)
    if d not in (np.dtype(np.float16), np.dtype(jnp.bfloat16),
                 np.dtype(np.float32), np.dtype(np.float64)):
        raise TypeError(f"default dtype must be a float dtype, got {d}")
    _state.default = d


def get_default_dtype() -> np.dtype:
    return _state.default


def is_floating_dtype(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.floating) or \
        np.dtype(dtype) == np.dtype(jnp.bfloat16)
