"""Random sampling ops over the global stateful generator
(ref surface: python/paddle/tensor/random.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import convert_dtype, get_default_dtype, long_dtype
from ..core.tensor import Tensor
from ..framework.random import next_key

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "gaussian", "bernoulli", "multinomial", "randperm",
    "poisson", "exponential_", "uniform_", "normal_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def _dt(dtype):
    d = convert_dtype(dtype)
    return d if d is not None else get_default_dtype()


def rand(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dt(dtype)))


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None) -> Tensor:
    k = jax.random.key(seed) if seed else next_key()
    return Tensor(mean + std * jax.random.normal(k, _shape(shape), _dt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    dt = convert_dtype(dtype) if dtype is not None else x.dtype
    return Tensor(jax.random.randint(next_key(), tuple(x.shape), low, high,
                                     dt if np.issubdtype(dt, np.integer) else long_dtype()
                                     ).astype(dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    k = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(k, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = np.broadcast_shapes(np.shape(m), np.shape(s))
        return Tensor(m + s * jax.random.normal(next_key(), shp,
                                                get_default_dtype()))
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape),
                                                 get_default_dtype()))


def bernoulli(x, name=None) -> Tensor:
    return Tensor(jax.random.bernoulli(next_key(), x._data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    def draw(p):
        logits = jnp.log(jnp.clip(p, 1e-30, None))
        if replacement:
            return jax.random.categorical(next_key(), logits,
                                          shape=(num_samples,) + logits.shape[:-1]
                                          ).swapaxes(0, -1) if logits.ndim > 1 else \
                jax.random.categorical(next_key(), logits, shape=(num_samples,))
        g = jax.random.gumbel(next_key(), logits.shape) + logits
        _, idx = jax.lax.top_k(g, num_samples)
        return idx
    return Tensor(draw(x._data).astype(long_dtype()))


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(next_key(), n).astype(convert_dtype(dtype)))


def poisson(x, name=None) -> Tensor:
    return Tensor(jax.random.poisson(next_key(), x._data).astype(x.dtype))


# inplace random fills (paddle Tensor methods)
def uniform_(x, min=-1.0, max=1.0, name=None) -> Tensor:
    x._data = jax.random.uniform(next_key(), tuple(x.shape), x.dtype,
                                 minval=min, maxval=max)
    x._node = None
    return x


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    x._data = (mean + std * jax.random.normal(next_key(), tuple(x.shape))
               ).astype(x.dtype)
    x._node = None
    return x


def exponential_(x, lam=1.0, name=None) -> Tensor:
    x._data = (jax.random.exponential(next_key(), tuple(x.shape)) / lam
               ).astype(x.dtype)
    x._node = None
    return x
