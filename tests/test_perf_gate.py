"""tools/perf_gate.py: band derivation from the committed BENCH /
SERVING_BENCH artifacts, pass on current values, fail on a synthetically
regressed candidate row, and the non-fatal no-artifact path the verify
wiring relies on."""

import json
import os
import shutil
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perf_gate  # noqa: E402


@pytest.fixture()
def mini_repo(tmp_path):
    """A scratch repo with one pretrain round + repeats + one serving
    row, so band math is assertable exactly."""
    (tmp_path / "docs").mkdir()
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"parsed": {"metric": "pretrain_tps", "value": 1000.0}},
                  f)
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump({"parsed": {"metric": "pretrain_tps", "value": 1010.0}},
                  f)
    with open(tmp_path / "docs" / "BENCH_REPEATS_r2.json", "w") as f:
        json.dump({"metric": "pretrain_tps",
                   "runs": [995.0, 1005.0, 1015.0],
                   "r1_band": [990.0, 1020.0]}, f)
    with open(tmp_path / "docs" / "SERVING_BENCH.json", "w") as f:
        json.dump({"decode": {"decode_tokens_per_s_per_chip": 200.0},
                   "note": "not a row"}, f)
    return str(tmp_path)


class TestBands:
    def test_pretrain_band_is_union_of_runs_and_bands(self, mini_repo):
        rows = perf_gate.pretrain_rows(mini_repo, margin=0.0)
        assert len(rows) == 1
        r = rows[0]
        assert r["key"] == "pretrain.pretrain_tps"
        assert r["value"] == 1010.0          # latest round wins
        assert r["band"] == [990.0, 1020.0]  # union(runs, r1_band)
        assert r["ok"]

    def test_margin_widens_band(self, mini_repo):
        r = perf_gate.pretrain_rows(mini_repo, margin=0.01)[0]
        assert r["band"][0] == pytest.approx(990.0 * 0.99)
        assert r["band"][1] == pytest.approx(1020.0 * 1.01)

    def test_serving_rows_banded_by_noise(self, mini_repo):
        rows = perf_gate.serving_rows(mini_repo, noise=0.10)
        assert len(rows) == 1
        r = rows[0]
        assert r["key"] == "serving.decode.decode_tokens_per_s_per_chip"
        assert r["band"] == [pytest.approx(180.0), pytest.approx(220.0)]
        assert r["ok"]

    def test_no_repeats_falls_back_to_round_spread(self, mini_repo):
        os.unlink(os.path.join(mini_repo, "docs",
                               "BENCH_REPEATS_r2.json"))
        r = perf_gate.pretrain_rows(mini_repo, margin=0.0)[0]
        assert r["band"] == [1000.0, 1010.0]


class TestCheck:
    def test_regressed_candidate_fails(self, mini_repo, tmp_path):
        cand = tmp_path / "cand.json"
        with open(cand, "w") as f:
            json.dump({"pretrain.pretrain_tps": 900.0}, f)
        rc = perf_gate.main(["--repo", mini_repo, "--check", str(cand)])
        assert rc == 1

    def test_inband_candidate_passes(self, mini_repo, tmp_path):
        cand = tmp_path / "cand.json"
        with open(cand, "w") as f:
            json.dump({"pretrain.pretrain_tps": 1012.0,
                       "serving.decode.decode_tokens_per_s_per_chip":
                           190.0}, f)
        rc = perf_gate.main(["--repo", mini_repo, "--check", str(cand)])
        assert rc == 0

    def test_above_band_is_rerate_not_failure(self, mini_repo):
        rows = perf_gate.gate_rows(mini_repo, margin=0.0)
        out = perf_gate.check_candidate(
            {"pretrain.pretrain_tps": 5000.0}, rows)
        assert out[0]["ok"]   # higher-is-better: exceeding band passes

    def test_unknown_key_fails_loudly(self, mini_repo):
        rows = perf_gate.gate_rows(mini_repo)
        out = perf_gate.check_candidate({"pretrain.typo_tps": 1.0}, rows)
        assert not out[0]["ok"]
        assert out[0]["why"] == "unknown metric key"


class TestCli:
    def test_no_artifacts_exit_zero(self, tmp_path):
        rc = perf_gate.main(["--repo", str(tmp_path)])
        assert rc == 0

    def test_self_check_on_committed_artifacts(self, capsys):
        # the real repo's own artifacts must gate green (the acceptance
        # criterion + the verify-skill wiring)
        rc = perf_gate.main(["--repo", REPO])
        out = capsys.readouterr().out
        assert rc == 0
        assert "pretrain." in out and "serving." in out

    def test_synthetic_regression_on_committed_artifacts(self, tmp_path):
        # copy the real artifacts, regress the pretrain row 20%, expect 1
        shutil.copytree(os.path.join(REPO, "docs"),
                        str(tmp_path / "docs"),
                        ignore=shutil.ignore_patterns("*.md"))
        import glob
        for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
            shutil.copy(p, str(tmp_path))
        latest = sorted(glob.glob(str(tmp_path / "BENCH_r*.json")))[-1]
        with open(latest) as f:
            d = json.load(f)
        d["parsed"]["value"] *= 0.8
        with open(latest, "w") as f:
            json.dump(d, f)
        rc = perf_gate.main(["--repo", str(tmp_path)])
        assert rc == 1

    def test_json_mode(self, mini_repo, capsys):
        rc = perf_gate.main(["--repo", mini_repo, "--json"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["failed"] == 0
        assert {r["key"] for r in rep["rows"]} == {
            "pretrain.pretrain_tps",
            "serving.decode.decode_tokens_per_s_per_chip"}
