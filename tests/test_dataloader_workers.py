"""Multiprocess DataLoader workers (ref: python/paddle/io/dataloader/
worker.py — VERDICT r1 item 9): order/content parity with the serial
path, per-worker seeding + worker_init_fn, error propagation, and a
parallelizable-transform speedup."""

import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset, get_worker_info


class SquareDataset(Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i, i * i], np.int64)


class SleepDataset(SquareDataset):
    def __getitem__(self, i):
        time.sleep(0.05)
        return super().__getitem__(i)


class FailingDataset(SquareDataset):
    def __getitem__(self, i):
        if i == 7:
            raise RuntimeError("boom at 7")
        return super().__getitem__(i)


class WorkerInfoDataset(SquareDataset):
    def __getitem__(self, i):
        info = get_worker_info()
        assert info is not None and info.num_workers == 2
        return np.asarray([i, info.id], np.int64)


def _collect(loader):
    return [np.asarray(b._data) if hasattr(b, "_data") else np.asarray(b)
            for b in loader]


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


class TestProcessWorkers:
    def test_matches_serial_order_and_content(self):
        ds = SquareDataset(33)
        serial = _collect(DataLoader(ds, batch_size=4, num_workers=0))
        proc = _collect(DataLoader(ds, batch_size=4, num_workers=3,
                                   worker_mode="process"))
        assert len(serial) == len(proc)
        for a, b in zip(serial, proc):
            np.testing.assert_array_equal(a, b)

    def test_worker_info_and_init_fn(self, tmp_path):
        marker = tmp_path / "init"

        def init(worker_id):
            (marker.parent / f"init{worker_id}").write_text(str(worker_id))

        out = _collect(DataLoader(WorkerInfoDataset(8), batch_size=2,
                                  num_workers=2, worker_mode="process",
                                  worker_init_fn=init))
        ids = np.concatenate([o[:, 1] for o in out])
        assert set(ids.tolist()) == {0, 1}
        assert (tmp_path / "init0").exists()
        assert (tmp_path / "init1").exists()

    def test_error_propagates(self):
        dl = DataLoader(FailingDataset(16), batch_size=4, num_workers=2,
                        worker_mode="process")
        with pytest.raises(RuntimeError, match="boom at 7"):
            _collect(dl)

    def test_parallel_transform_speedup(self):
        # sleep-based transform: parallel across processes even on a
        # single-core host (the CPU-bound-python case needs >1 core, but
        # the mechanism under test — concurrent workers — is the same)
        ds = SleepDataset(80)
        t0 = time.perf_counter()
        _collect(DataLoader(ds, batch_size=4, num_workers=0))
        serial = time.perf_counter() - t0
        # best of 2 parallel runs: fork startup of a jax-heavy parent is
        # load-sensitive (~0.3s idle, seconds on a busy CI host) and is
        # not the mechanism under test — concurrent workers are
        par = min(
            _timed(lambda: _collect(DataLoader(
                ds, batch_size=4, num_workers=4, worker_mode="process")))
            for _ in range(2))
        # 4 workers on a 4s-of-sleep pipeline: well under serial
        assert par < serial * 0.7, (serial, par)

    def test_iterable_rejected(self):
        from paddle_tpu.io import IterableDataset

        class It(IterableDataset):
            def __iter__(self):
                yield from range(4)
        with pytest.raises(NotImplementedError):
            DataLoader(It(), num_workers=2, worker_mode="process")

    def test_custom_collate_runs_in_worker(self):
        def collate(batch):
            return np.stack(batch).sum(0)
        out = list(DataLoader(SquareDataset(8), batch_size=4,
                              num_workers=2, worker_mode="process",
                              collate_fn=collate))
        ref = list(DataLoader(SquareDataset(8), batch_size=4,
                              num_workers=0, collate_fn=collate))
        for a, b in zip(out, ref):
            np.testing.assert_array_equal(a, b)


class TestSharedMemoryTransport:
    def test_shm_matches_pickle(self):
        ds = SquareDataset(24)
        shm = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                  worker_mode="process",
                                  use_shared_memory=True))
        pkl = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                  worker_mode="process",
                                  use_shared_memory=False))
        assert len(shm) == len(pkl) == 6
        for a, b in zip(shm, pkl):
            np.testing.assert_array_equal(a, b)

    def test_shm_dict_batches(self):
        from paddle_tpu.io import Dataset

        class DictDS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"x": np.full((3,), i, np.float32), "tag": str(i)}

        out = list(DataLoader(DictDS(), batch_size=4, num_workers=2,
                              worker_mode="process",
                              use_shared_memory=True))
        assert len(out) == 2
        np.testing.assert_allclose(np.asarray(out[0]["x"]._data)[:, 0],
                                   [0, 1, 2, 3])
        assert out[0]["tag"] == ["0", "1", "2", "3"]

    def test_no_leaked_segments(self):
        # scope to this loader's attributable names: global /dev/shm
        # diffs flake against unrelated concurrent processes
        import glob
        _collect(DataLoader(SquareDataset(16), batch_size=4,
                            num_workers=2, worker_mode="process",
                            use_shared_memory=True))
        assert glob.glob("/dev/shm/ppio*") == []

    def test_early_break_cleans_up(self):
        import glob
        dl = DataLoader(SquareDataset(32), batch_size=4, num_workers=2,
                        worker_mode="process", use_shared_memory=True)
        it = iter(dl)
        next(it)
        it.close()  # early break — pending batches must be unlinked
        time.sleep(0.3)
        leaked = glob.glob("/dev/shm/ppio*")
        assert leaked == [], leaked

    def test_object_dtype_stays_on_pickle_path(self):
        from paddle_tpu.io import Dataset

        class ObjDS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return {"x": np.full((2,), i, np.float32),
                        "meta": np.array([{"id": i}], object)}

        def collate(batch):
            return {"x": np.stack([b["x"] for b in batch]),
                    "meta": np.concatenate([b["meta"] for b in batch])}
        out = list(DataLoader(ObjDS(), batch_size=4, num_workers=2,
                              worker_mode="process",
                              use_shared_memory=True,
                              collate_fn=collate))
        assert out[0]["meta"][0]["id"] == 0
        np.testing.assert_allclose(out[1]["x"][:, 0], [4, 5, 6, 7])

    def test_early_break_pickle_mode_does_not_hang(self):
        ds = SquareDataset(32)
        dl = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_mode="process", use_shared_memory=False)
        it = iter(dl)
        next(it)
        t0 = time.perf_counter()
        it.close()
        assert time.perf_counter() - t0 < 10
