"""paddle.vision.ops parity: detection operators (ref: python/paddle/
vision/ops.py over CUDA kernels roi_align/nms/deform_conv — SURVEY §2.2
vision row "GPU-accelerated ops").

TPU-native mechanism notes:
- roi_align / roi_pool: bilinear/max sampling expressed as dense gathers —
  XLA lowers to vectorized dynamic-slices; no atomics needed (the CUDA
  kernels' main complication).
- nms: O(N²) IoU matrix + a greedy suppression sweep under lax.fori_loop —
  compiler-friendly fixed-shape loop; the final index extraction is
  data-dependent and therefore eager-only (like every NMS).
- deform_conv2d: offset-shifted bilinear sampling (gather) followed by ONE
  im2col-style matmul on the MXU — the idiomatic TPU shape for DCN.

Layouts follow paddle: images NCHW, boxes [N, 4] as (x1, y1, x2, y2).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["nms", "roi_align", "roi_pool", "deform_conv2d", "DeformConv2D"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------
def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """ref: paddle.vision.ops.nms. Greedy suppression in score order;
    category-aware when category_idxs is given (boxes of different
    categories never suppress each other). Returns kept indices (Tensor,
    int64-ordered by score) — data-dependent size, eager-only."""
    b = _arr(boxes).astype(jnp.float32)
    n = b.shape[0]
    s = jnp.arange(n, 0, -1, jnp.float32) if scores is None \
        else _arr(scores).astype(jnp.float32)
    iou = _iou_matrix(b)
    if category_idxs is not None:
        cat = _arr(category_idxs)
        same = cat[:, None] == cat[None, :]
        iou = jnp.where(same, iou, 0.0)
    order = jnp.argsort(-s)

    def body(i, keep):
        bi = order[i]
        # suppressed iff a higher-scoring KEPT box overlaps > threshold
        higher = jnp.arange(n) < i
        sup = jnp.any(higher & keep[order] & (iou[bi, order] > iou_threshold))
        return keep.at[bi].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
    kept_sorted = order[keep[order]]  # score order, eager extraction
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    return Tensor(kept_sorted.astype(jnp.int64))


# ---------------------------------------------------------------------------
# RoI align / pool
# ---------------------------------------------------------------------------
def _bilinear(feat, y, x):
    """feat [C, H, W]; y/x sample grids of any shape → [C, *grid]."""
    H, W = feat.shape[-2:]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = y - y0
    wx = x - x0
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def _bilinear_zero(feat, y, x):
    """Bilinear sampling with ZERO padding outside the image (the
    deform-conv reference semantics; `_bilinear` edge-clamps instead,
    which is what roi_align wants)."""
    H, W = feat.shape[-2:]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    wy = y - y0
    wx = x - x0
    out = 0.0
    for yc, ww_y in ((y0, 1 - wy), (y0 + 1, wy)):
        for xc, ww_x in ((x0, 1 - wx), (x0 + 1, wx)):
            valid = (yc >= 0) & (yc < H) & (xc >= 0) & (xc < W)
            v = feat[:, jnp.clip(yc, 0, H - 1), jnp.clip(xc, 0, W - 1)]
            out = out + v * (ww_y * ww_x * valid)
    return out


def _roi_grid(box, pooled: Tuple[int, int], spatial_scale, sr_h, sr_w,
              aligned):
    ph, pw = pooled
    off = 0.5 if aligned else 0.0
    x1 = box[0] * spatial_scale - off
    y1 = box[1] * spatial_scale - off
    x2 = box[2] * spatial_scale - off
    y2 = box[3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw
    iy = (jnp.arange(sr_h) + 0.5) / sr_h
    ix = (jnp.arange(sr_w) + 0.5) / sr_w
    ys = y1 + (jnp.arange(ph)[:, None] + iy[None, :]) * bin_h  # [ph, sr_h]
    xs = x1 + (jnp.arange(pw)[:, None] + ix[None, :]) * bin_w  # [pw, sr_w]
    return ys, xs


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ref: paddle.vision.ops.roi_align. boxes [R,4] concatenated over the
    batch, boxes_num [N] giving the per-image count. sampling_ratio<=0
    means reference-adaptive: ceil(roi_size/bin_count) samples per bin,
    computed per ROI (host-side — boxes are data, so eager-only)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    import numpy as np
    xb = _arr(x)
    bx = _arr(boxes).astype(jnp.float32)
    bn = [int(v) for v in jnp.asarray(_arr(boxes_num))]
    img_idx = [i for i, c in enumerate(bn) for _ in range(c)]
    ph, pw = output_size
    bx_np = np.asarray(bx)
    srs = []
    for r in range(bx_np.shape[0]):
        if sampling_ratio > 0:
            srs.append((sampling_ratio, sampling_ratio))
        else:
            rh = (bx_np[r, 3] - bx_np[r, 1]) * spatial_scale
            rw = (bx_np[r, 2] - bx_np[r, 0]) * spatial_scale
            srs.append((max(int(math.ceil(rh / ph)), 1),
                        max(int(math.ceil(rw / pw)), 1)))

    def impl(feat_all):
        outs = []
        for r in range(bx_np.shape[0]):
            feat = feat_all[img_idx[r]]
            sr_h, sr_w = srs[r]
            ys, xs = _roi_grid(bx[r], (ph, pw), spatial_scale, sr_h, sr_w,
                               aligned)
            Y, X = jnp.meshgrid(ys.reshape(-1), xs.reshape(-1),
                                indexing="ij")
            # samples past the [-1, size] band contribute zero (the
            # reference clamps only within that band; beyond it the
            # sample is dropped, not edge-clamped)
            H_, W_ = feat.shape[-2:]
            valid = ((Y >= -1.0) & (Y <= H_) & (X >= -1.0) & (X <= W_))
            vals = _bilinear(feat, Y, X) * valid.astype(feat.dtype)
            C = feat.shape[0]
            vals = vals.reshape(C, ph, sr_h, pw, sr_w)
            outs.append(vals.mean(axis=(2, 4)))
        return jnp.stack(outs)

    return apply("roi_align", impl, [x if isinstance(x, Tensor)
                                     else Tensor(xb)])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """ref: paddle.vision.ops.roi_pool (max pooling over quantized bins)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    xb = _arr(x)
    bx = _arr(boxes).astype(jnp.float32)
    bn = [int(v) for v in jnp.asarray(_arr(boxes_num))]
    img_idx = jnp.asarray(
        sum(([i] * c for i, c in enumerate(bn)), []), jnp.int32)
    ph, pw = output_size
    H, W = xb.shape[-2:]

    def impl(feat_all):
        def one(box, img):
            feat = feat_all[img]
            x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            # dense mask-max over the full feature map per bin (TPU-style:
            # trade FLOPs for gather-free regular compute)
            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]
            b_y0 = y1 + (jnp.arange(ph)[:, None] * rh) // ph
            b_y1 = y1 + ((jnp.arange(ph)[:, None] + 1) * rh + ph - 1) // ph
            b_x0 = x1 + (jnp.arange(pw)[:, None] * rw) // pw
            b_x1 = x1 + ((jnp.arange(pw)[:, None] + 1) * rw + pw - 1) // pw
            my = (ys >= b_y0) & (ys < jnp.maximum(b_y1, b_y0 + 1))  # [ph,H]
            mx = (xs >= b_x0) & (xs < jnp.maximum(b_x1, b_x0 + 1))  # [pw,W]
            m = my[:, None, :, None] & mx[None, :, None, :]  # [ph,pw,H,W]
            neg = jnp.asarray(-3.4e38, feat.dtype)
            v = jnp.where(m[None], feat[:, None, None, :, :], neg)
            mx = v.max(axis=(-1, -2))
            # empty bin (box off the feature map / degenerate) → 0, the
            # reference's convention — never the -3.4e38 sentinel
            return jnp.where(m.any(axis=(-1, -2))[None], mx, 0.0)
        return jax.vmap(one)(bx, img_idx)

    return apply("roi_pool", impl, [x if isinstance(x, Tensor)
                                    else Tensor(xb)])


# ---------------------------------------------------------------------------
# Deformable convolution (DCNv1/v2)
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """ref: paddle.vision.ops.deform_conv2d. x NCHW, offset
    [N, 2·dg·kh·kw, Ho, Wo] ((dy, dx) interleaved per kernel point), mask
    [N, dg·kh·kw, Ho, Wo] for DCNv2. groups/deformable_groups=1 supported.
    """
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1")
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph_, pw_ = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    wshape = (_arr(weight)).shape
    oc, ic, kh, kw = wshape
    xb = _arr(x)
    N, C, H, W = xb.shape
    Ho = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1

    base_y = jnp.arange(Ho) * sh - ph_
    base_x = jnp.arange(Wo) * sw - pw_
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw

    def impl(xa, off, w, *rest):
        i = 0
        m = None
        if mask is not None:
            m = rest[0].reshape(N, kh, kw, Ho, Wo)
            i = 1
        b = rest[i] if bias is not None else None
        offr = off.reshape(N, kh, kw, 2, Ho, Wo)
        dy = offr[:, :, :, 0]
        dx = offr[:, :, :, 1]
        # sample positions [N, kh, kw, Ho, Wo]
        yy = (base_y[None, None, None, :, None]
              + ky[None, :, None, None, None] + dy)
        xx = (base_x[None, None, None, None, :]
              + kx[None, None, :, None, None] + dx)
        vals = jax.vmap(_bilinear_zero)(xa, yy, xx)  # [N,C,kh,kw,Ho,Wo]
        if m is not None:
            vals = vals * m[:, None]
        # im2col contraction: one MXU einsum over (c, kh, kw)
        out = jnp.einsum("ncijhw,ocij->nohw", vals, w)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    inputs = [x if isinstance(x, Tensor) else Tensor(xb),
              offset if isinstance(offset, Tensor) else Tensor(_arr(offset)),
              weight if isinstance(weight, Tensor) else Tensor(_arr(weight))]
    if mask is not None:
        inputs.append(mask if isinstance(mask, Tensor)
                      else Tensor(_arr(mask)))
    if bias is not None:
        inputs.append(bias if isinstance(bias, Tensor)
                      else Tensor(_arr(bias)))
    return apply("deform_conv2d", impl, inputs)


from ..nn import Layer as _Layer  # noqa: E402
from ..nn import initializer as _I  # noqa: E402


class DeformConv2D(_Layer):
    """ref: paddle.vision.ops.DeformConv2D. A real nn.Layer so enclosing
    models pick up weight/bias in parameters() and state_dict."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        fan_in = in_channels * ks[0] * ks[1]
        std = math.sqrt(2.0 / fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels, ks[0], ks[1]],
            default_initializer=_I.Normal(0.0, std))
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self.stride, padding=self.padding,
                             dilation=self.dilation,
                             deformable_groups=self.deformable_groups,
                             groups=self.groups, mask=mask)


# ---------------------------------------------------------------------------
# SSD / YOLO box utilities (ref: python/paddle/vision/ops.py prior_box,
# box_coder, yolo_box, matrix_nms)
# ---------------------------------------------------------------------------
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes for one feature map. input NCHW feature,
    image NCHW original image. Returns (boxes [H, W, A, 4] in normalized
    (x1, y1, x2, y2), variances broadcast to the same shape)."""
    fh, fw = _arr(input).shape[-2:]
    ih, iw = _arr(image).shape[-2:]
    # reference ExpandAspectRatios: 1.0 is always implicitly first, then
    # each new ratio (+ reciprocal when flip), deduplicated
    ars = [1.0]
    for ar in aspect_ratios:
        ar = float(ar)
        for cand in ([ar, 1.0 / ar] if flip else [ar]):
            if all(abs(cand - e) > 1e-6 for e in ars):
                ars.append(cand)
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    widths, heights = [], []
    for mi, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            widths.append(ms); heights.append(ms)
            if max_sizes:
                s = math.sqrt(ms * max_sizes[mi])
                widths.append(s); heights.append(s)
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                widths.append(ms * math.sqrt(ar))
                heights.append(ms / math.sqrt(ar))
        else:
            for ar in ars:
                widths.append(ms * math.sqrt(ar))
                heights.append(ms / math.sqrt(ar))
            if max_sizes:
                s = math.sqrt(ms * max_sizes[mi])
                widths.append(s); heights.append(s)
    A = len(widths)
    w = jnp.asarray(widths, jnp.float32) / iw
    h = jnp.asarray(heights, jnp.float32) / ih
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w / iw
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h / ih
    CX = cx[None, :, None]
    CY = cy[:, None, None]
    boxes = jnp.stack([
        jnp.broadcast_to(CX - w / 2, (fh, fw, A)),
        jnp.broadcast_to(CY - h / 2, (fh, fw, A)),
        jnp.broadcast_to(CX + w / 2, (fh, fw, A)),
        jnp.broadcast_to(CY + h / 2, (fh, fw, A))], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return Tensor(boxes), Tensor(var)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """SSD box encode/decode (ref: paddle.vision.ops.box_coder).
    encode: target corner boxes [N,4] vs priors [M,4] → offsets [N,M,4].
    decode: offsets [N,M,4]-compatible vs priors → corner boxes."""
    pb = _arr(prior_box).astype(jnp.float32)
    tb = _arr(target_box).astype(jnp.float32)
    pbv = None if prior_box_var is None else \
        _arr(prior_box_var).astype(jnp.float32)
    if pbv is not None and pbv.ndim == 1:  # 4-float list form (API parity)
        pbv = jnp.broadcast_to(pbv, pb.shape)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], -1)
        if pbv is not None:
            out = out / pbv[None, :, :]
        return Tensor(out)
    if code_type == "decode_center_size":
        # tb: [N, M, 4] offsets (or broadcastable); priors along `axis`
        if tb.ndim == 2:
            tb = tb[:, None, :]
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (v[None, :] for v in (pw, ph, pcx, pcy))
            pbv_ = None if pbv is None else pbv[None, :, :]
        else:
            pw_, ph_, pcx_, pcy_ = (v[:, None] for v in (pw, ph, pcx, pcy))
            pbv_ = None if pbv is None else pbv[:, None, :]
        off = tb * pbv_ if pbv_ is not None else tb
        cx = off[..., 0] * pw_ + pcx_
        cy = off[..., 1] * ph_ + pcy_
        w = jnp.exp(off[..., 2]) * pw_
        h = jnp.exp(off[..., 3]) * ph_
        return Tensor(jnp.stack([cx - w / 2, cy - h / 2,
                                 cx + w / 2 - norm, cy + h / 2 - norm], -1))
    raise ValueError(f"unknown code_type {code_type!r}")


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0, name=None):
    """Decode a YOLOv3 head (ref: paddle.vision.ops.yolo_box). x is
    [N, A*(5+C), H, W]; returns (boxes [N, A*H*W, 4] xyxy in image pixels,
    scores [N, A*H*W, C]) with anchor-major rows r = a*H*W + h*W + w;
    low-confidence boxes are zeroed."""
    xb = _arr(x).astype(jnp.float32)
    N, _, H, W = xb.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    xb = xb.reshape(N, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    sig = lambda v: 1.0 / (1.0 + jnp.exp(-v))
    bx = (sig(xb[:, :, 0]) * alpha + beta + gx) / W
    by = (sig(xb[:, :, 1]) * alpha + beta + gy) / H
    in_w = downsample_ratio * W
    in_h = downsample_ratio * H
    bw = jnp.exp(xb[:, :, 2]) * an[None, :, 0, None, None] / in_w
    bh = jnp.exp(xb[:, :, 3]) * an[None, :, 1, None, None] / in_h
    conf = sig(xb[:, :, 4])
    probs = sig(xb[:, :, 5:]) * conf[:, :, None]
    img = jnp.asarray(_arr(img_size), jnp.float32).reshape(N, 2)
    ih = img[:, 0][:, None, None, None]
    iw = img[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * iw
    y1 = (by - bh / 2) * ih
    x2 = (bx + bw / 2) * iw
    y2 = (by + bh / 2) * ih
    if clip_bbox:
        x1 = jnp.clip(x1, 0, iw - 1)
        y1 = jnp.clip(y1, 0, ih - 1)
        x2 = jnp.clip(x2, 0, iw - 1)
        y2 = jnp.clip(y2, 0, ih - 1)
    keep = (conf > conf_thresh).astype(jnp.float32)
    boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
    scores = probs * keep[:, :, None]
    # reference kernel writes anchor-major rows: r = a*H*W + h*W + w
    boxes = boxes.reshape(N, A * H * W, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, A * H * W,
                                                     class_num)
    return Tensor(boxes), Tensor(scores)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): fully-vectorized soft suppression — no
    sequential loop, a natural TPU fit (ref: paddle.vision.ops.matrix_nms).
    bboxes [N, M, 4], scores [N, C, M]. Returns [R, 6] rows of
    (class, decayed_score, x1, y1, x2, y2) per image, concatenated."""
    import numpy as _np
    bb = _np.asarray(_arr(bboxes), _np.float32)  # one transfer, then host
    sc = _np.asarray(_arr(scores), _np.float32)
    N, C, M = sc.shape
    outs, idxs, nums = [], [], []
    for n in range(N):
        cls_all, box_all = _np.nonzero(sc[n] > score_threshold)
        if background_label >= 0:
            keep_c = cls_all != background_label
            cls_all, box_all = cls_all[keep_c], box_all[keep_c]
        s_all = sc[n, cls_all, box_all]
        order0 = _np.argsort(-s_all)[:nms_top_k]
        flat = [(float(s_all[i]), int(cls_all[i]), int(box_all[i]))
                for i in order0]
        if not flat:
            outs.append(_np.zeros((0, 6), _np.float32))
            idxs.append(_np.zeros((0,), _np.int64))
            nums.append(0)
            continue
        # whole decay computation on host: the candidate set is small
        # (<= nms_top_k) and this op is eager-only — no device round-trips
        ss = _np.asarray([f[0] for f in flat], _np.float32)
        cs = _np.asarray([f[1] for f in flat])
        bs_np = bb[n, [f[2] for f in flat]]
        k = len(flat)
        iou = _np_iou_matrix(bs_np)
        same_cls = cs[:, None] == cs[None, :]
        # rows sorted by score desc: pair (i, j) active iff j outranks i
        higher = _np.arange(k)[None, :] < _np.arange(k)[:, None]
        iou_h = _np.where(higher & same_cls, iou, 0.0)
        # compensation: each suppressor j's own max overlap with ITS
        # higher-ranked peers (the SOLOv2 matrix-NMS formula)
        comp = _np.max(iou_h, axis=1)
        if use_gaussian:
            # reference formula: exp(-σ·(iou² − comp²)) — σ MULTIPLIES
            decay_mat = _np.exp(-gaussian_sigma
                                * (iou_h ** 2 - comp[None, :] ** 2))
        else:
            # comp→1 (duplicate suppressor) would be 0/0: guard the
            # denominator so the duplicate decays to 0, not nan
            decay_mat = (1.0 - iou_h) / _np.maximum(1.0 - comp[None, :],
                                                    1e-10)
        decay_mat = _np.where(higher & same_cls, decay_mat, 1.0)
        dec_np = ss * _np.min(decay_mat, axis=1)
        keep_np = dec_np >= post_threshold if post_threshold > 0 else \
            _np.ones_like(dec_np, bool)
        order = _np.argsort(-dec_np)
        order = order[keep_np[order]][:keep_top_k]
        rows = _np.concatenate(
            [cs[order, None].astype(_np.float32),
             dec_np[order, None], bs_np[order]], 1) if len(order) else \
            _np.zeros((0, 6), _np.float32)
        outs.append(rows)
        idxs.append(_np.asarray([flat[i][2] for i in order], _np.int64))
        nums.append(len(order))
    out = Tensor(jnp.asarray(_np.concatenate(outs, 0)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(_np.concatenate(idxs, 0))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(_np.asarray(nums, _np.int32))))
    return tuple(res) if len(res) > 1 else out


class RoIAlign:
    """ref: paddle.vision.ops.RoIAlign layer wrapper."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    """ref: paddle.vision.ops.RoIPool layer wrapper."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


__all__ += ["prior_box", "box_coder", "yolo_box", "matrix_nms",
            "RoIAlign", "RoIPool"]


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (R-FCN; ref:
    paddle.vision.ops.psroi_pool). Input channels must be
    C_out * ph * pw; bin (i, j) of an ROI average-pools channel group
    (i*pw + j) over that bin's spatial extent."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xb = _arr(x)
    Cin, H, W = xb.shape[-3:]
    if Cin % (ph * pw) != 0:
        raise ValueError(f"input channels {Cin} not divisible by "
                         f"{ph}*{pw} bins")
    Cout = Cin // (ph * pw)
    bx = _arr(boxes).astype(jnp.float32)
    bn = [int(v) for v in jnp.asarray(_arr(boxes_num))]
    img_idx = [i for i, c in enumerate(bn) for _ in range(c)]

    def impl(feat_all):
        outs = []
        for r in range(bx.shape[0]):
            # R-FCN layout: channel (k, i, j) = k·ph·pw + i·pw + j
            feat = feat_all[img_idx[r]].reshape(Cout, ph, pw, H, W)
            x1 = bx[r, 0] * spatial_scale
            y1 = bx[r, 1] * spatial_scale
            x2 = bx[r, 2] * spatial_scale
            y2 = bx[r, 3] * spatial_scale
            bh = jnp.maximum(y2 - y1, 0.1) / ph
            bw = jnp.maximum(x2 - x1, 0.1) / pw
            ys = jnp.arange(H, dtype=jnp.float32)[None, :]
            xs = jnp.arange(W, dtype=jnp.float32)[None, :]
            y0 = y1 + jnp.arange(ph, dtype=jnp.float32)[:, None] * bh
            x0 = x1 + jnp.arange(pw, dtype=jnp.float32)[:, None] * bw
            my = (ys >= jnp.floor(y0)) & (ys < jnp.ceil(y0 + bh))  # [ph,H]
            mx = (xs >= jnp.floor(x0)) & (xs < jnp.ceil(x0 + bw))  # [pw,W]
            m = (my[:, None, :, None] & mx[None, :, None, :])  # [ph,pw,H,W]
            cnt = jnp.maximum(m.sum(axis=(-1, -2)), 1)         # [ph,pw]
            v = jnp.where(m[None], feat, 0.0)
            pooled = v.sum(axis=(-1, -2)) / cnt[None]          # [Cout,ph,pw]
            outs.append(pooled)
        return jnp.stack(outs)

    return apply("psroi_pool", impl, [x if isinstance(x, Tensor)
                                      else Tensor(xb)])


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (ref:
    paddle.vision.ops.distribute_fpn_proposals):
    level = floor(refer_level + log2(sqrt(area)/refer_scale)), clipped.
    Returns (rois-per-level list, restore_index, rois_num-per-level)."""
    import numpy as np
    rois = np.asarray(_arr(fpn_rois), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-12))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    # roi → owning image (for per-image per-level counts)
    if rois_num is not None:
        rn = np.asarray(_arr(rois_num)).astype(np.int64)
        img_of = np.repeat(np.arange(len(rn)), rn)
    else:
        rn = None
        img_of = np.zeros(len(rois), np.int64)
    multi_rois, per_level_nums = [], []
    order = []
    for L in range(min_level, max_level + 1):
        ids = np.nonzero(lvl == L)[0]
        order.extend(ids.tolist())
        multi_rois.append(Tensor(jnp.asarray(rois[ids])))
        if rn is not None:
            per_level_nums.append(Tensor(jnp.asarray(np.bincount(
                img_of[ids], minlength=len(rn)).astype(np.int32))))
        else:
            per_level_nums.append(len(ids))
    restore = np.empty(len(rois), np.int64)
    restore[np.asarray(order, np.int64)] = np.arange(len(rois))
    if rn is None:
        per_level_nums = Tensor(jnp.asarray(
            np.asarray(per_level_nums, np.int32)))
    return multi_rois, Tensor(jnp.asarray(restore)), per_level_nums


def _np_iou_matrix(boxes):
    import numpy as np
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def _np_greedy_nms(props, thresh, eta=1.0):
    """Greedy NMS on score-sorted boxes with Paddle's adaptive-threshold
    option: after each kept box, thresh *= eta while thresh > 0.5."""
    import numpy as np
    iou = _np_iou_matrix(props)
    kept = []
    adaptive = float(thresh)
    for i in range(len(props)):
        # each candidate tests against the CURRENT (decayed) threshold —
        # the reference NMSFast order of operations
        if kept and float(iou[i, kept].max()) > adaptive:
            continue
        kept.append(i)
        if eta < 1.0 and adaptive > 0.5:
            adaptive *= eta
    return np.asarray(kept, np.int64)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation (ref: paddle.vision.ops.generate_proposals):
    decode anchor deltas → clip to image → filter small → top-k → NMS.
    scores [N, A, H, W], bbox_deltas [N, 4A, H, W], anchors/variances
    [H, W, A, 4] (prior_box layout)."""
    import numpy as np
    sc = np.asarray(_arr(scores), np.float32)
    bd = np.asarray(_arr(bbox_deltas), np.float32)
    an = np.asarray(_arr(anchors), np.float32).reshape(-1, 4)
    va = np.asarray(_arr(variances), np.float32).reshape(-1, 4)
    imgs = np.asarray(_arr(img_size), np.float32)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_nums = [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # HWA order
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw * 0.5
        acy = an[:, 1] + ah * 0.5
        cx = va[:, 0] * d[:, 0] * aw + acx
        cy = va[:, 1] * d[:, 1] * ah + acy
        wd = np.exp(np.minimum(va[:, 2] * d[:, 2], 10.0)) * aw
        hg = np.exp(np.minimum(va[:, 3] * d[:, 3], 10.0)) * ah
        props = np.stack([cx - wd / 2, cy - hg / 2,
                          cx + wd / 2 - off, cy + hg / 2 - off], 1)
        ih, iw = imgs[n, 0], imgs[n, 1]
        props[:, 0] = np.clip(props[:, 0], 0, iw - off)
        props[:, 1] = np.clip(props[:, 1], 0, ih - off)
        props[:, 2] = np.clip(props[:, 2], 0, iw - off)
        props[:, 3] = np.clip(props[:, 3], 0, ih - off)
        keep = ((props[:, 2] - props[:, 0] + off >= min_size)
                & (props[:, 3] - props[:, 1] + off >= min_size))
        props, s = props[keep], s[keep]
        order = np.argsort(-s)[:pre_nms_top_n]
        props, s = props[order], s[order]
        if len(props):
            kept = _np_greedy_nms(props, nms_thresh, eta)
            kept = kept[:post_nms_top_n]
            props, s = props[kept], s[kept]
        all_rois.append(np.concatenate([props, s[:, None]], 1))
        all_nums.append(len(props))
    rois = np.concatenate(all_rois, 0) if all_rois else \
        np.zeros((0, 5), np.float32)
    out = (Tensor(jnp.asarray(rois[:, :4])), Tensor(jnp.asarray(rois[:, 4])))
    if return_rois_num:
        return out + (Tensor(jnp.asarray(np.asarray(all_nums, np.int32))),)
    return out


__all__ += ["psroi_pool", "distribute_fpn_proposals", "generate_proposals"]
