"""N-gram self-drafting speculative decoding for the serving engine.

Prompt-lookup drafting (no draft model): the longest suffix n-gram of a
sequence's context that occurred earlier in that same context proposes
the k tokens that followed its most recent occurrence. The engine
verifies all k drafts in ONE ragged unified step — a decode slot simply
contributes `1 + k` rows instead of 1 to the flat token buffer, and the
ragged kernel's per-row causality (`row t attends KV positions
0 .. kv_len - num_tokens + t`) already gives each draft position
exactly the prefix it would see in plain decode.

Greedy accept/rollback keeps engine output EXACTLY equal to plain
decode: with greedy sampling, position j's argmax depends only on the
accepted prefix, so accepting drafts while they match the verifier's
argmax chain and rolling the KV length back past the first mismatch
(`allocator.shrink`; rejected KV rows are never readable and are
rewritten later) reproduces the token-at-a-time output bit-for-bit.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .. import observability as _obs

__all__ = ["ngram_draft", "accept_length", "record_verify"]

_DRAFTED = _obs.registry().counter(
    "serving.spec_decode.draft_tokens", "tokens proposed by the drafter")
_ACCEPTED = _obs.registry().counter(
    "serving.spec_decode.accepted_tokens",
    "drafted tokens accepted by batched greedy verification")
_REJECTED = _obs.registry().counter(
    "serving.spec_decode.rejected_tokens",
    "drafted tokens rolled back after verification")
_STEPS = _obs.registry().counter(
    "serving.spec_decode.verify_steps",
    "engine steps that verified >= 1 drafted token")


def ngram_draft(context: Sequence[int], k: int, max_ngram: int = 3,
                min_ngram: int = 1) -> List[int]:
    """Draft up to `k` next tokens for `context` (prompt + generated so
    far) by prompt lookup: for n from `max_ngram` down to `min_ngram`,
    find the most recent earlier occurrence of the length-n context
    suffix and propose the tokens that followed it. Returns [] when no
    n-gram recurs — the engine then runs a plain 1-token row.

    The copy is self-referential (LZ77 style): when the match sits close
    to the end of the context, drafted tokens feed back into the copy
    source, so a periodic tail (e.g. a constant run) drafts the full k
    tokens instead of truncating at the context boundary."""
    ctx = np.asarray(context, dtype=np.int64).ravel()
    size = int(ctx.size)
    if k <= 0 or size < min_ngram + 1:
        return []
    for n in range(min(max_ngram, size - 1), min_ngram - 1, -1):
        tail = ctx[size - n:]
        for i in range(size - n - 1, -1, -1):
            if np.array_equal(ctx[i:i + n], tail):
                seq = [int(t) for t in ctx]
                out: List[int] = []
                pos = i + n
                for _ in range(k):
                    nxt = seq[pos]
                    out.append(nxt)
                    seq.append(nxt)
                    pos += 1
                return out
    return []


def accept_length(drafts: Sequence[int], greedy: Sequence[int]) -> int:
    """Length of the accepted prefix: drafted token j survives iff it
    equals the verifier's greedy argmax at position j (which was
    computed with drafts[:j] in context)."""
    m = 0
    for d, g in zip(drafts, greedy):
        if int(d) != int(g):
            break
        m += 1
    return m


def record_verify(drafted: int, accepted: int) -> None:
    """Publish one verify step's draft/accept counts."""
    if not _obs.enabled() or drafted <= 0:
        return
    _DRAFTED.inc(drafted)
    _ACCEPTED.inc(accepted)
    _REJECTED.inc(drafted - accepted)
    _STEPS.inc()
