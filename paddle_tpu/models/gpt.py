"""GPT model family (ref capability: PaddleNLP
paddlenlp/transformers/gpt/modeling.py — GPTModel / GPTForCausalLM, the
GPT-3 pretrain recipe that predates the Llama baseline).

Same TPU-first conventions as models/llama.py: weights carry Megatron
sharding specs (qkv/fc-in: column on mp; proj/fc-out: row on mp; embeddings
vocab-sharded), attention routes through scaled_dot_product_attention
(flash-kernel routable), and the vocab-parallel CE loss comes from
ParallelCrossEntropy. Architectural differences from Llama kept faithful to
GPT-2/3: learned absolute position embeddings (no rope), pre-LN blocks with
bias-ful linears, gelu 4x MLP, final LayerNorm, tied LM head by default.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.parallel_layers import MP_AXIS

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt2_small_config",
           "gpt3_6_7b_config", "gpt_tiny_config"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=None, max_position_embeddings=1024,
                 hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                 initializer_range=0.02, layer_norm_eps=1e-5,
                 tie_word_embeddings=True, recompute=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.tie_word_embeddings = tie_word_embeddings
        self.recompute = recompute
        self.head_dim = hidden_size // num_attention_heads


def gpt2_small_config(**kw) -> GPTConfig:
    return GPTConfig(**kw)


def gpt3_6_7b_config(**kw) -> GPTConfig:
    base = dict(hidden_size=4096, num_hidden_layers=32,
                num_attention_heads=32, max_position_embeddings=2048)
    base.update(kw)
    return GPTConfig(**base)


def gpt_tiny_config(**kw) -> GPTConfig:
    base = dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, max_position_embeddings=64)
    base.update(kw)
    return GPTConfig(**base)


def _mp_linear(in_f, out_f, spec):
    l = nn.Linear(in_f, out_f)
    l.weight._sharding_spec = spec
    if spec == P(None, MP_AXIS):          # column-parallel: bias sharded too
        l.bias._sharding_spec = P(MP_AXIS)
    return l


class GPTAttention(nn.Layer):
    def __init__(self, c: GPTConfig):
        super().__init__()
        self.c = c
        H = c.hidden_size
        self.qkv = _mp_linear(H, 3 * H, P(None, MP_AXIS))
        self.proj = _mp_linear(H, H, P(MP_AXIS, None))

    def forward(self, x, attn_mask=None):
        B, S, H = x.shape
        nh, hd = self.c.num_attention_heads, self.c.head_dim
        qkv = self.qkv(x)
        q, k, v = (t.reshape([B, S, nh, hd])
                   for t in qkv.chunk(3, axis=-1))
        # always causal; a user mask (e.g. padding) composes with it rather
        # than replacing it (PaddleNLP builds the causal mask internally)
        o = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=True,
            dropout_p=self.c.attention_probs_dropout_prob
            if self.training else 0.0)
        return self.proj(o.reshape([B, S, H]))


class GPTMLP(nn.Layer):
    def __init__(self, c: GPTConfig):
        super().__init__()
        self.fc_in = _mp_linear(c.hidden_size, c.intermediate_size,
                                P(None, MP_AXIS))
        self.fc_out = _mp_linear(c.intermediate_size, c.hidden_size,
                                 P(MP_AXIS, None))

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTBlock(nn.Layer):
    def __init__(self, c: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.attn = GPTAttention(c)
        self.ln_2 = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.mlp = GPTMLP(c)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = x + self.dropout(self.attn(self.ln_1(x), attn_mask))
        return x + self.dropout(self.mlp(self.ln_2(x)))


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size)
        self.embed_tokens.weight._data = init(
            [config.vocab_size, config.hidden_size], "float32")
        self.embed_tokens.weight._sharding_spec = P(MP_AXIS, None)
        self.embed_positions = nn.Embedding(config.max_position_embeddings,
                                            config.hidden_size)
        self.embed_positions.weight._data = init(
            [config.max_position_embeddings, config.hidden_size], "float32")
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTBlock(config)
                               for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None):
        S = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :])
        x = self.embed_tokens(input_ids) + self.embed_positions(position_ids)
        x = self.dropout(x)
        for block in self.h:
            if self.config.recompute and self.training:
                from ..distributed.recompute import recompute
                x = recompute(block, x, attn_mask)
            else:
                x = block(x, attn_mask)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
            self.lm_head.weight._sharding_spec = P(None, MP_AXIS)

    def forward(self, input_ids, labels=None, position_ids=None,
                attn_mask=None):
        h = self.gpt(input_ids, position_ids, attn_mask)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = F.linear(h, self.gpt.embed_tokens.weight.T)
        if labels is not None:
            from ..distributed.parallel_layers import ParallelCrossEntropy
            tok_loss = ParallelCrossEntropy()(logits, labels)
            return tok_loss.mean(), logits
        return logits
