"""paddle.static parity (ref: python/paddle/static/ — SURVEY §2.2 static
API row).

TPU-native rework (SURVEY §7.0): the reference's static graph is a
ProgramDesc executed by StandaloneExecutor; here a `Program` CAPTURES a
traced jax function (the jaxpr/StableHLO IS the program — SURVEY §1 "static
= traced program under jit"). The user-facing workflow keeps parity:

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 8], "float32")
        y = paddle.nn.Linear(8, 2)(x)        # traced lazily at run()
    exe = static.Executor()
    out, = exe.run(main, feed={"x": arr}, fetch_list=[y])

Ops execute eagerly during `with program_guard` (define-by-run), and the
Program records the (fn, feeds, fetches) closure; Executor.run re-traces
under jax.jit keyed by feed shapes — the compiled executable is cached the
way _ExecutorCache caches StandaloneExecutor instances (§3.3).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import autograd as _ag

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "InputSpec",
           "cpu_places", "cuda_places", "device_guard", "name_scope",
           "save_inference_model", "load_inference_model", "nn"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else s for s in shape)
        self.dtype = dtype
        self.name = name


class _Placeholder(Tensor):
    """A feedable variable: created by static.data; holds zeros until fed."""

    def __init__(self, name, shape, dtype):
        concrete = tuple(1 if (s is None or s < 0) else s for s in shape)
        super().__init__(jnp.zeros(concrete, dtype))
        self._feed_name = name
        self._declared_shape = tuple(
            -1 if (s is None or s < 0) else s for s in shape)


class _OpRecord:
    __slots__ = ("name", "fn", "in_ids", "in_refs", "in_consts", "out_ids")

    def __init__(self, name, fn, in_ids, in_refs, in_consts, out_ids):
        self.name = name
        self.fn = fn
        self.in_ids = in_ids        # per input: id(Tensor) or None
        self.in_refs = in_refs      # weakrefs to live input Tensors (params!)
        self.in_consts = in_consts  # per input: captured array (fallback)
        self.out_ids = out_ids


class Program:
    """Placeholders + the recorded op list built under its guard (the
    Instruction-list analog of §3.3; replay = ProgramInterpreter)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.placeholders: Dict[str, _Placeholder] = {}
        self.ops: List[_OpRecord] = []
        self.random_seed = 0

    # dispatch hook target
    def _record(self, name, fn, tlist, arrs, results):
        import weakref
        in_ids = [id(t) if t is not None else None for t in tlist]
        in_refs = [weakref.ref(t) if t is not None else None for t in tlist]
        self.ops.append(_OpRecord(
            name, fn, in_ids, in_refs, list(arrs), [id(r) for r in results]))

    def replay(self, feed: Dict[str, object]):
        """Re-execute the op list with placeholder values swapped in.
        Returns env mapping recorded-tensor id -> new array."""
        env: Dict[int, object] = {}
        for nm, ph in self.placeholders.items():
            if nm in feed:
                env[id(ph)] = jnp.asarray(np.asarray(feed[nm]))
        for op in self.ops:
            ins = []
            for tid, ref, const in zip(op.in_ids, op.in_refs, op.in_consts):
                if tid is not None and tid in env:
                    ins.append(env[tid])
                elif ref is not None and ref() is not None:
                    ins.append(ref()._data)  # live tensor (e.g. a parameter)
                else:
                    ins.append(const)
            out = op.fn(*ins)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for oid, o in zip(op.out_ids, outs):
                env[oid] = o
        return env

    def clone(self, for_test: bool = False) -> "Program":
        return self

    def __repr__(self):
        return (f"Program(id={self.id}, feeds={list(self.placeholders)}, "
                f"ops={len(self.ops)})")


_tls = threading.local()


def _current_program() -> Optional[Program]:
    return getattr(_tls, "program", None)


class program_guard:
    def __init__(self, main_program: Program, startup_program: Program = None):
        self.main = main_program

    def __enter__(self):
        from ..core import dispatch as _dispatch
        self._prev = _current_program()
        _tls.program = self.main
        self._prev_rec = _dispatch._static_recorder
        _dispatch.set_static_recorder(self.main._record)
        return self.main

    def __exit__(self, *exc):
        from ..core import dispatch as _dispatch
        _tls.program = self._prev
        _dispatch.set_static_recorder(self._prev_rec)
        return False


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return _current_program() or _default_main


def default_startup_program() -> Program:
    return _default_startup


def data(name: str, shape, dtype="float32", lod_level=0) -> _Placeholder:
    """ref: paddle.static.data — declares a feedable graph input."""
    ph = _Placeholder(name, shape, dtype)
    prog = default_main_program()
    prog.placeholders[name] = ph
    return ph


class Executor:
    """ref: paddle.static.Executor — run(program, feed, fetch_list).

    The first run() with a given feed-shape signature traces the fetch
    graph; repeats hit the jit cache (parity: _ExecutorCache →
    StandaloneExecutor build-once)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed: Dict = None,
            fetch_list: Sequence = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        env = program.replay(feed)
        outs = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                a = env.get(id(f), f._data)
            else:
                a = jnp.asarray(f)
            outs.append(np.asarray(a) if return_numpy else a)
        return outs


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    import jax as _j
    return [str(d) for d in _j.devices()]


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program=None):
    """ref: paddle.static.save_inference_model — delegates to the traced
    export (paddle_tpu.jit.save semantics: StableHLO program on disk)."""
    raise NotImplementedError(
        "static-graph export is unified with paddle_tpu.jit.save (the traced "
        "StableHLO program is the deployment format; SURVEY §7.0 inference "
        "row)")


def load_inference_model(path_prefix: str, executor):
    raise NotImplementedError(
        "use paddle_tpu.jit.load (TranslatedLayer over the saved trace)")


class _StaticNN:
    """paddle.static.nn.* façade: the layer zoo doubles as the static op
    set (define-by-run capture)."""

    def __getattr__(self, name):
        from .. import nn as _nn
        fnmap = {"fc": self._fc, "conv2d": self._conv2d,
                 "batch_norm": self._batch_norm}
        if name in fnmap:
            return fnmap[name]
        raise AttributeError(name)

    @staticmethod
    def _fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F
        l = _nn.Linear(int(x.shape[-1]), size)
        out = l(x)
        if activation == "relu":
            out = F.relu(out)
        elif activation == "softmax":
            out = F.softmax(out)
        return out

    @staticmethod
    def _conv2d(input, num_filters, filter_size, stride=1, padding=0,
                act=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F
        l = _nn.Conv2D(int(input.shape[1]), num_filters, filter_size,
                       stride=stride, padding=padding)
        out = l(input)
        if act == "relu":
            out = F.relu(out)
        return out

    @staticmethod
    def _batch_norm(input, act=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F
        l = _nn.BatchNorm2D(int(input.shape[1]))
        out = l(input)
        if act == "relu":
            out = F.relu(out)
        return out


nn = _StaticNN()
