"""Generation tests (ref capability: PaddleNLP GenerationMixin /
model.generate — paddlenlp/generation/utils.py)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.generation import generate
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config


def _prompt(B, S, V, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randint(0, V, (B, S)).astype(np.int32))


def test_greedy_matches_manual_argmax_loop():
    paddle.seed(0)
    c = gpt_tiny_config(num_hidden_layers=1)
    model = GPTForCausalLM(c)
    model.eval()
    ids = _prompt(2, 5, c.vocab_size)
    gen, scores = generate(model, ids, max_new_tokens=4,
                           decode_strategy="greedy_search")
    assert gen.shape == [2, 4]
    # manual loop: grow the sequence, argmax the last position each time
    cur = ids.numpy()
    for step in range(4):
        logits = model(paddle.to_tensor(cur)).numpy()
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        np.testing.assert_array_equal(gen.numpy()[:, step], nxt)
        cur = np.concatenate([cur, nxt[:, None]], 1)
    # scores are the chosen tokens' log-probs (finite, <= 0)
    s = scores.numpy()
    assert np.all(np.isfinite(s)) and np.all(s <= 1e-6)


def test_sampling_reproducible_and_valid():
    paddle.seed(0)
    c = llama_tiny_config(num_hidden_layers=1)
    model = LlamaForCausalLM(c)
    model.eval()
    ids = _prompt(2, 4, c.vocab_size, seed=1)
    paddle.seed(123)
    g1, _ = generate(model, ids, max_new_tokens=6, decode_strategy="sampling",
                     top_k=8, temperature=0.9)
    paddle.seed(123)
    g2, _ = generate(model, ids, max_new_tokens=6, decode_strategy="sampling",
                     top_k=8, temperature=0.9)
    np.testing.assert_array_equal(g1.numpy(), g2.numpy())
    assert g1.numpy().min() >= 0 and g1.numpy().max() < c.vocab_size


def test_top_k_1_equals_greedy():
    paddle.seed(0)
    c = gpt_tiny_config(num_hidden_layers=1)
    model = GPTForCausalLM(c)
    model.eval()
    ids = _prompt(1, 4, c.vocab_size, seed=2)
    greedy, _ = generate(model, ids, max_new_tokens=5,
                         decode_strategy="greedy_search")
    paddle.seed(7)
    topk1, _ = generate(model, ids, max_new_tokens=5,
                        decode_strategy="sampling", top_k=1)
    np.testing.assert_array_equal(greedy.numpy(), topk1.numpy())


def test_top_p_filters_tail():
    """top_p≈0 keeps only the argmax token → equals greedy."""
    paddle.seed(0)
    c = gpt_tiny_config(num_hidden_layers=1)
    model = GPTForCausalLM(c)
    model.eval()
    ids = _prompt(1, 4, c.vocab_size, seed=3)
    greedy, _ = generate(model, ids, max_new_tokens=4,
                         decode_strategy="greedy_search")
    paddle.seed(11)
    nucleus, _ = generate(model, ids, max_new_tokens=4,
                          decode_strategy="sampling", top_p=1e-6)
    np.testing.assert_array_equal(greedy.numpy(), nucleus.numpy())


def test_eos_stops_and_pads():
    paddle.seed(0)
    c = gpt_tiny_config(num_hidden_layers=1)
    model = GPTForCausalLM(c)
    model.eval()
    ids = _prompt(1, 4, c.vocab_size, seed=4)
    # force eos = the greedy first token → generation ends immediately
    first, _ = generate(model, ids, max_new_tokens=1,
                        decode_strategy="greedy_search")
    eos = int(first.numpy()[0, 0])
    gen, scores = generate(model, ids, max_new_tokens=5,
                           decode_strategy="greedy_search", eos_token_id=eos,
                           pad_token_id=0)
    g = gen.numpy()[0]
    assert g.shape == (5,)
    assert g[0] == eos
    np.testing.assert_array_equal(g[1:], 0)
    np.testing.assert_array_equal(scores.numpy()[0, 1:], 0.0)


def test_cached_generation_matches_padded_buffer():
    """KV-cache decode (generate_cached) must produce exactly the greedy
    tokens of the padded-buffer path."""
    from paddle_tpu.generation import generate_cached
    paddle.seed(0)
    c = llama_tiny_config(num_hidden_layers=2)
    model = LlamaForCausalLM(c)
    model.eval()
    ids = _prompt(2, 6, c.vocab_size, seed=7)
    ref, ref_scores = generate(model, ids, max_new_tokens=6,
                               decode_strategy="greedy_search")
    got, got_scores = generate_cached(model, ids, max_new_tokens=6,
                                      decode_strategy="greedy_search")
    np.testing.assert_array_equal(ref.numpy(), got.numpy())
    np.testing.assert_allclose(ref_scores.numpy(), got_scores.numpy(),
                               rtol=1e-3, atol=1e-4)


def test_cached_generation_eos_and_limits():
    from paddle_tpu.generation import generate_cached
    paddle.seed(0)
    c = llama_tiny_config(num_hidden_layers=1)
    model = LlamaForCausalLM(c)
    model.eval()
    ids = _prompt(1, 4, c.vocab_size, seed=8)
    first, _ = generate_cached(model, ids, max_new_tokens=1,
                               decode_strategy="greedy_search")
    eos = int(first.numpy()[0, 0])
    gen, _ = generate_cached(model, ids, max_new_tokens=5,
                             decode_strategy="greedy_search",
                             eos_token_id=eos)
    g = gen.numpy()[0]
    assert g[0] == eos
    np.testing.assert_array_equal(g[1:], 0)
    import pytest
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate_cached(model, ids,
                        max_new_tokens=c.max_position_embeddings)


def test_compiled_decode_loop_matches_cached():
    """The one-XLA-program decode loop (generate_compiled) must produce
    exactly generate_cached's greedy tokens, and respect eos padding."""
    from paddle_tpu.generation import generate_cached, generate_compiled
    paddle.seed(0)
    c = llama_tiny_config(num_hidden_layers=2)
    model = LlamaForCausalLM(c)
    model.eval()
    ids = _prompt(2, 6, c.vocab_size, seed=11)
    ref, ref_scores = generate_cached(model, ids, max_new_tokens=6,
                                      decode_strategy="greedy_search")
    got, got_scores = generate_compiled(model, ids, max_new_tokens=6,
                                        decode_strategy="greedy_search")
    np.testing.assert_array_equal(ref.numpy(), got.numpy())
    np.testing.assert_allclose(ref_scores.numpy(), got_scores.numpy(),
                               rtol=1e-3, atol=1e-4)
    # eos: once a row finishes it emits pad (fixed trip count, no early exit)
    eos = int(ref.numpy()[0, 0])
    gen, _ = generate_compiled(model, ids[:1], max_new_tokens=5,
                               decode_strategy="greedy_search",
                               eos_token_id=eos)
    g = gen.numpy()[0]
    assert g[0] == eos
    np.testing.assert_array_equal(g[1:], 0)


def test_compiled_decode_sampling_valid():
    from paddle_tpu.generation import generate_compiled
    paddle.seed(3)
    c = llama_tiny_config(num_hidden_layers=1)
    model = LlamaForCausalLM(c)
    model.eval()
    ids = _prompt(2, 4, c.vocab_size, seed=12)
    gen, scores = generate_compiled(model, ids, max_new_tokens=4,
                                    decode_strategy="sampling",
                                    top_k=8, temperature=0.9)
    g = gen.numpy()
    assert g.shape == (2, 4) and (g >= 0).all() and (g < c.vocab_size).all()
    s = scores.numpy()
    assert np.all(np.isfinite(s)) and np.all(s <= 1e-6)


def test_qwen2_cached_and_compiled_decode():
    """The cached/compiled decode family covers Qwen2 (qkv biases, tied
    head) — tokens must match the padded-buffer path exactly."""
    from paddle_tpu.models.qwen2 import Qwen2ForCausalLM, qwen2_tiny_config
    from paddle_tpu.generation import generate_cached, generate_compiled
    paddle.seed(0)
    c = qwen2_tiny_config(num_hidden_layers=2)
    model = Qwen2ForCausalLM(c)
    model.eval()
    ids = _prompt(2, 5, c.vocab_size, seed=21)
    ref, _ = generate(model, ids, max_new_tokens=5,
                      decode_strategy="greedy_search")
    got_c, _ = generate_cached(model, ids, max_new_tokens=5,
                               decode_strategy="greedy_search")
    got_k, _ = generate_compiled(model, ids, max_new_tokens=5,
                                 decode_strategy="greedy_search")
    np.testing.assert_array_equal(ref.numpy(), got_c.numpy())
    np.testing.assert_array_equal(ref.numpy(), got_k.numpy())
