"""Common layers (ref: python/paddle/nn/layer/common.py, conv.py, pooling.py)."""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ...core.dtypes import convert_dtype
from .. import functional as F
from .. import initializer as I
from .layers import Layer, Parameter

__all__ = ["Linear", "Embedding", "Dropout", "Dropout2D", "AlphaDropout",
           "Flatten", "Pad1D", "Pad2D", "Pad3D", "Upsample", "UpsamplingNearest2D",
           "UpsamplingBilinear2D", "Identity", "Conv1D", "Conv2D", "Conv3D",
           "Conv2DTranspose", "MaxPool1D", "MaxPool2D", "AvgPool1D",
           "AvgPool2D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "PixelShuffle", "Bilinear", "CosineSimilarity"]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """paddle weight layout [in_features, out_features] (ref: nn/layer/common.py)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        bound = 1.0 / math.sqrt(in_features)
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value,
                         "NCW" if data_format == "NCL" else "NWC")


class Pad2D(_PadND):
    pass


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, data_format)


class _ConvND(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * nd
        self._nd = nd
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        fan_in = in_channels // groups * int(np.prod(ks))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups] + list(ks), attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in,
                                                 nonlinearity="leaky_relu",
                                                 negative_slope=math.sqrt(5)))
        if bias_attr is not False:
            bound = 1.0 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr,
                default_initializer=I.Uniform(-bound, bound))
        else:
            self.bias = None


class Conv1D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * 2
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups] + list(ks), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        k, s, p, c = self.args
        return F.max_pool1d(x, k, s, p, c)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        k, s, p, c, df = self.args
        return F.max_pool2d(x, k, s, p, c, data_format=df)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        k, s, p, e, c = self.args
        return F.avg_pool1d(x, k, s, p, e, c)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive, data_format)

    def forward(self, x):
        k, s, p, c, e, df = self.args
        return F.avg_pool2d(x, k, s, p, c, e, data_format=df)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)
