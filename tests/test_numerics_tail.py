"""Long-tail numerics: distribution, sparse, fft/signal, geometric, audio,
quantization, profiler (SURVEY §2.2 misc numerics + §5.1)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class TestDistribution:
    def test_normal_sample_logprob_kl(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        paddle.seed(0)
        d = Normal(0.0, 1.0)
        s = d.sample((5000,))
        assert abs(float(s.mean())) < 0.1
        lp = d.log_prob(Tensor(jnp.zeros(())))
        np.testing.assert_allclose(float(lp), -0.5 * np.log(2 * np.pi),
                                   rtol=1e-5)
        kl = kl_divergence(Normal(0.0, 1.0), Normal(1.0, 1.0))
        np.testing.assert_allclose(float(kl), 0.5, rtol=1e-5)

    def test_categorical_and_bernoulli(self):
        from paddle_tpu.distribution import Bernoulli, Categorical
        paddle.seed(1)
        c = Categorical(probs=jnp.asarray([0.2, 0.8]))
        s = np.asarray(c.sample((2000,))._data)
        assert abs(s.mean() - 0.8) < 0.05
        b = Bernoulli(probs=0.3)
        assert abs(float(b.mean) - 0.3) < 1e-6
        assert float(b.entropy()) > 0

    @pytest.mark.parametrize("name", ["Exponential", "Laplace", "Gamma",
                                      "Beta", "Poisson", "Geometric"])
    def test_moment_sanity(self, name):
        import paddle_tpu.distribution as D
        paddle.seed(2)
        args = {"Exponential": (2.0,), "Laplace": (0.0, 1.0),
                "Gamma": (2.0, 3.0), "Beta": (2.0, 2.0), "Poisson": (3.0,),
                "Geometric": (0.4,)}[name]
        d = getattr(D, name)(*map(jnp.asarray, args))
        s = np.asarray(d.sample((4000,))._data)
        assert abs(s.mean() - float(d.mean)) < 4 * np.sqrt(
            float(d.variance) / 4000) + 0.05

    def test_dirichlet_multinomial(self):
        from paddle_tpu.distribution import Dirichlet, Multinomial
        paddle.seed(3)
        d = Dirichlet(jnp.asarray([2.0, 3.0, 5.0]))
        s = np.asarray(d.sample((500,))._data)
        np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
        m = Multinomial(10, jnp.asarray([0.3, 0.7]))
        sm = np.asarray(m.sample((100,))._data)
        np.testing.assert_allclose(sm.sum(-1), 10.0)


class TestSparse:
    def test_coo_roundtrip_and_matmul(self):
        import paddle_tpu.sparse as sp
        idx = jnp.asarray([[0, 1, 2], [1, 0, 2]])   # [ndim, nnz]
        vals = jnp.asarray([1.0, 2.0, 3.0])
        s = sp.sparse_coo_tensor(idx, vals, shape=(3, 3))
        dense = np.asarray(s.to_dense()._data)
        expect = np.zeros((3, 3), np.float32)
        expect[0, 1], expect[1, 0], expect[2, 2] = 1, 2, 3
        np.testing.assert_allclose(dense, expect)
        y = np.asarray(sp.matmul(s, jnp.eye(3))._data)
        np.testing.assert_allclose(y, expect)

    def test_csr_and_ops(self):
        import paddle_tpu.sparse as sp
        s = sp.sparse_csr_tensor([0, 1, 3], [1, 0, 2], [-1.0, 2.0, -3.0],
                                 (2, 3))
        dense = np.asarray(s.to_dense()._data)
        expect = np.array([[0, -1, 0], [2, 0, -3]], np.float32)
        np.testing.assert_allclose(dense, expect)
        r = sp.relu(s.to_coo())
        np.testing.assert_allclose(np.asarray(r.to_dense()._data),
                                   np.maximum(expect, 0))


class TestFFTSignal:
    def test_fft_roundtrip(self):
        import paddle_tpu.fft as fft
        x = Tensor(jnp.asarray(np.random.RandomState(0).randn(16)
                               .astype(np.float32)))
        X = fft.fft(x)
        back = fft.ifft(X)
        np.testing.assert_allclose(np.asarray(back._data).real,
                                   np.asarray(x._data), atol=1e-5)

    def test_rfft_matches_numpy(self):
        import paddle_tpu.fft as fft
        x = np.random.RandomState(1).randn(32).astype(np.float32)
        X = fft.rfft(Tensor(jnp.asarray(x)))
        np.testing.assert_allclose(np.asarray(X._data), np.fft.rfft(x),
                                   rtol=1e-4, atol=1e-4)

    def test_stft_istft_roundtrip(self):
        from paddle_tpu.signal import istft, stft
        x = np.random.RandomState(2).randn(1, 256).astype(np.float32)
        S = stft(Tensor(jnp.asarray(x)), n_fft=64, hop_length=16)
        assert S._data.shape == (1, 33, 256 // 16 + 1)
        back = istft(S, n_fft=64, hop_length=16, length=256)
        np.testing.assert_allclose(np.asarray(back._data), x, atol=1e-4)


class TestGeometric:
    def test_segment_ops(self):
        from paddle_tpu.geometric import segment_max, segment_mean, \
            segment_sum
        x = Tensor(jnp.asarray([[1.0], [2.0], [3.0], [4.0]]))
        ids = jnp.asarray([0, 0, 1, 1])
        np.testing.assert_allclose(
            np.asarray(segment_sum(x, ids)._data), [[3.0], [7.0]])
        np.testing.assert_allclose(
            np.asarray(segment_mean(x, ids)._data), [[1.5], [3.5]])
        np.testing.assert_allclose(
            np.asarray(segment_max(x, ids)._data), [[2.0], [4.0]])

    def test_empty_segments_fill_zero(self):
        # reference fills skipped segment ids with 0, not ±inf
        from paddle_tpu.geometric import (segment_max, segment_min,
                                          send_u_recv)
        x = Tensor(jnp.asarray([[1.0], [-2.0], [3.0]]))
        ids = jnp.asarray([0, 0, 2])  # segment 1 is empty
        np.testing.assert_allclose(
            np.asarray(segment_max(x, ids)._data), [[1.0], [0.0], [3.0]])
        np.testing.assert_allclose(
            np.asarray(segment_min(x, ids)._data), [[-2.0], [0.0], [3.0]])
        out = send_u_recv(x, jnp.asarray([0, 1]), jnp.asarray([0, 0]),
                          "max")
        np.testing.assert_allclose(np.asarray(out._data),
                                   [[1.0], [0.0], [0.0]])

    def test_send_u_recv(self):
        from paddle_tpu.geometric import send_u_recv, send_ue_recv
        x = Tensor(jnp.asarray([[1.0, 0.0], [0.0, 1.0], [2.0, 2.0]]))
        src = jnp.asarray([0, 1, 2])
        dst = jnp.asarray([1, 2, 1])
        out = np.asarray(send_u_recv(x, src, dst, "sum")._data)
        np.testing.assert_allclose(out, [[0, 0], [3, 2], [0, 1]])
        e = Tensor(jnp.asarray([[1.0, 1.0], [1.0, 1.0], [1.0, 1.0]]))
        m = np.asarray(send_ue_recv(x, e, src, dst, "add", "mean")._data)
        np.testing.assert_allclose(m, [[0, 0], [2.5, 2], [1, 2]])


class TestAudio:
    def test_melspectrogram_and_mfcc_shapes(self):
        from paddle_tpu.audio import LogMelSpectrogram, MFCC
        x = Tensor(jnp.asarray(np.random.RandomState(3).randn(1, 2048)
                               .astype(np.float32)))
        lm = LogMelSpectrogram(sr=16000, n_fft=256, n_mels=32)(x)
        assert lm._data.shape[1] == 32
        mf = MFCC(sr=16000, n_mfcc=13, n_mels=32, n_fft=256)(x)
        assert mf._data.shape[1] == 13
        assert np.isfinite(np.asarray(mf._data)).all()


class TestQuantization:
    def test_qat_fake_quant_trains(self):
        from paddle_tpu import nn, optimizer as opt
        from paddle_tpu.quantization import (FakeQuanterWithAbsMax, QAT,
                                             QuantConfig)
        np.random.seed(4)
        model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
        cfg = QuantConfig(activation=FakeQuanterWithAbsMax,
                          weight=FakeQuanterWithAbsMax)
        model = QAT(cfg).quantize(model)
        x = Tensor(jnp.asarray(np.random.randn(4, 8).astype(np.float32)))
        out = model(x)
        loss = (out * out).mean()
        loss.backward()
        params = model.parameters()
        assert any(p.grad is not None for p in params)

    def test_ptq_convert_runs_close(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import PTQ
        np.random.seed(5)
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                              nn.Linear(16, 4))
        x = Tensor(jnp.asarray(np.random.randn(4, 16).astype(np.float32)))
        ref = np.asarray(model(x)._data)
        ptq = PTQ()
        model = ptq.quantize(model)
        model(x)  # calibration
        model = ptq.convert(model)
        out = np.asarray(model(x)._data)
        assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6) < 0.05


class TestProfiler:
    def test_profiler_records_and_summarizes(self, tmp_path, capsys):
        from paddle_tpu.profiler import (Profiler, ProfilerTarget,
                                         RecordEvent, export_chrome_tracing)
        p = Profiler(targets=[ProfilerTarget.CPU],
                     on_trace_ready=export_chrome_tracing(str(tmp_path)))
        with p:
            with RecordEvent("myop"):
                sum(range(10000))
            stats = p.summary()
        assert p.last_export_path is not None
        import os
        assert os.path.exists(p.last_export_path)
        assert "myop" in stats

    def test_scheduler_windows(self):
        from paddle_tpu.profiler import Profiler, make_scheduler, prof_clear
        sched = make_scheduler(closed=1, ready=0, record=2, repeat=1)
        p = Profiler(scheduler=sched)
        p.start()
        states = []
        for i in range(4):
            p.step()
            states.append(p._recording)
        p.stop()
        assert True in states and False in states


def test_longtail_distributions():
    """Gumbel/Cauchy/StudentT/Chi2/Binomial/MVN/Independent — log_prob vs
    scipy, sample moments sanity."""
    from scipy import stats as ss
    import paddle_tpu.distribution as D

    x = np.linspace(-2, 2, 7).astype(np.float32)
    np.testing.assert_allclose(
        D.Gumbel(0.5, 1.5).log_prob(paddle.to_tensor(x)).numpy(),
        ss.gumbel_r.logpdf(x, 0.5, 1.5), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        D.Cauchy(0.0, 2.0).log_prob(paddle.to_tensor(x)).numpy(),
        ss.cauchy.logpdf(x, 0, 2), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        D.StudentT(4.0).log_prob(paddle.to_tensor(x)).numpy(),
        ss.t.logpdf(x, 4), rtol=1e-5, atol=1e-5)
    xc = np.array([0.5, 1.5, 3.0], np.float32)
    np.testing.assert_allclose(
        D.Chi2(3.0).log_prob(paddle.to_tensor(xc)).numpy(),
        ss.chi2.logpdf(xc, 3), rtol=1e-4, atol=1e-5)
    k = np.array([0., 2., 5.], np.float32)
    np.testing.assert_allclose(
        D.Binomial(10.0, 0.3).log_prob(paddle.to_tensor(k)).numpy(),
        ss.binom.logpmf(k, 10, 0.3), rtol=1e-4, atol=1e-5)

    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    mvn = D.MultivariateNormal(np.zeros(2, np.float32),
                               covariance_matrix=cov)
    v = np.array([0.3, -0.7], np.float32)
    np.testing.assert_allclose(
        mvn.log_prob(paddle.to_tensor(v)).numpy(),
        ss.multivariate_normal.logpdf(v, np.zeros(2), cov), rtol=1e-4)
    assert mvn.sample([5]).shape == [5, 2]

    ind = D.Independent(D.Normal(np.zeros(3, np.float32),
                                 np.ones(3, np.float32)), 1)
    lp = ind.log_prob(paddle.to_tensor(np.zeros(3, np.float32)))
    np.testing.assert_allclose(float(lp.numpy()),
                               3 * ss.norm.logpdf(0.0), rtol=1e-5)

    # Gumbel KL: zero for identical, positive otherwise
    kl0 = D.kl_divergence(D.Gumbel(0.0, 1.0), D.Gumbel(0.0, 1.0))
    assert abs(float(kl0.numpy())) < 1e-5
    kl1 = D.kl_divergence(D.Gumbel(0.0, 1.0), D.Gumbel(1.0, 2.0))
    assert float(kl1.numpy()) > 0


def test_distribution_review_regressions():
    import paddle_tpu.distribution as D
    from scipy import stats as ss
    # batched log_prob against a single MVN
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    mvn = D.MultivariateNormal(np.zeros(2, np.float32),
                               covariance_matrix=cov)
    vals = np.random.RandomState(0).randn(4, 2).astype(np.float32)
    np.testing.assert_allclose(
        mvn.log_prob(paddle.to_tensor(vals)).numpy(),
        ss.multivariate_normal.logpdf(vals, np.zeros(2), cov), rtol=1e-4)
    # batched covariance
    covs = np.stack([np.eye(2), 2 * np.eye(2), 3 * np.eye(2)]).astype(
        np.float32)
    mvb = D.MultivariateNormal(np.zeros(2, np.float32),
                               covariance_matrix=covs)
    assert mvb.batch_shape == (3,)
    assert mvb.sample([5]).shape == [5, 3, 2]
    # degenerate binomial params stay finite
    assert np.isfinite(float(
        D.Binomial(10.0, 1.0).log_prob(paddle.to_tensor(10.0)).numpy()))
    assert np.isfinite(float(
        D.Binomial(10.0, 0.0).log_prob(paddle.to_tensor(0.0)).numpy()))
    # continuous bernoulli closed-form variance
    v = float(D.ContinuousBernoulli(np.float32(0.3)).variance.numpy())
    assert abs(v - 0.0804) < 5e-3, v
    # Independent rank validation
    import pytest
    with pytest.raises(ValueError):
        D.Independent(D.Normal(np.zeros(3, np.float32),
                               np.ones(3, np.float32)), 2)
