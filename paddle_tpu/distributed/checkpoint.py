"""Sharding-aware distributed checkpoint with cross-topology reload.

Reference capability (SURVEY §5.4): paddle.distributed.checkpoint
(python/paddle/distributed/checkpoint/save_state_dict.py /
load_state_dict.py) — every rank writes its local shards plus a metadata
file mapping global tensor -> (shard offsets, files); load reshards across a
DIFFERENT parallel topology by intersecting saved slices with target slices
(the read-overlap plan). PaddleNLP "unified checkpoint" adds async save.

TPU-native rework (tensorstore/Orbax pattern, self-contained):
- save: walk `jax.Array.addressable_shards`, write one .npy per unique
  shard index-domain + a global-view metadata.json (shape/dtype/offsets).
  Replicated tensors write a single shard. `async_save=True` snapshots to
  host then writes on a background thread (PaddleNLP async-save parity).
- load: for each target tensor we build its target shards' index domains,
  intersect with saved domains, and read ONLY the overlapping slices
  (np.load mmap) — cross-topology reload is exactly this intersection, so a
  checkpoint from a (dp=2, mp=4) run loads into (dp=8) unchanged.
"""

from __future__ import annotations

import io as _io
import json
import os
import re
import threading
import warnings
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import resilience as _res
from ..core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_async_saves",
           "verify_checkpoint"]

_META = "metadata.json"
_pending: list = []  # (thread, error_box) pairs


def _safe(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


def _index_to_offsets(index, shape):
    """index: tuple of slices from shard.index -> (offsets, extents)."""
    offs, exts = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offs.append(start)
        exts.append(stop - start)
    return offs, exts


def _arr_of(v):
    if isinstance(v, Tensor):
        return v._data
    return v


def _npy_bytes(data: np.ndarray) -> bytes:
    """Serialized .npy payload for a shard — one buffer so the checksum
    covers exactly what lands on disk."""
    if data.dtype == jnp.bfloat16:
        # .npy has no native bf16; store lossless as f32, the metadata
        # dtype restores the logical type on load
        data = data.astype(np.float32)
    buf = _io.BytesIO()
    np.save(buf, data)
    return buf.getvalue()


def save_state_dict(state_dict: Dict[str, object], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False) -> None:
    """Write every tensor's addressable shards + global metadata under
    ``path`` (a directory)."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = {"tensors": {}, "world_size": jax.process_count()}

    jobs = []  # (filename, serialized .npy bytes), written now or async
    for key, v in state_dict.items():
        arr = _arr_of(v)
        if arr is None:
            continue
        arr = jnp.asarray(arr) if not isinstance(arr, jax.Array) else arr
        entry = {"shape": list(arr.shape),
                 "dtype": str(arr.dtype.name
                              if hasattr(arr.dtype, "name") else arr.dtype),
                 "shards": []}
        seen = set()
        shards = getattr(arr, "addressable_shards", None)
        if not shards:
            fname = f"{_safe(key)}.r{rank}.s0.npy"
            raw = _npy_bytes(np.asarray(arr))
            entry["shards"].append(
                {"offsets": [0] * arr.ndim, "shape": list(arr.shape),
                 "file": fname, "crc32": _res.crc32_bytes(raw)})
            jobs.append((fname, raw))
        else:
            for i, sh in enumerate(shards):
                offs, exts = _index_to_offsets(sh.index, arr.shape)
                domkey = tuple(offs + exts)
                if domkey in seen:  # replicated shard already captured
                    continue
                seen.add(domkey)
                fname = f"{_safe(key)}.r{rank}.s{i}.npy"
                raw = _npy_bytes(np.asarray(sh.data))
                entry["shards"].append(
                    {"offsets": offs, "shape": exts, "file": fname,
                     "crc32": _res.crc32_bytes(raw)})
                jobs.append((fname, raw))
        meta["tensors"][key] = entry

    def write_all():
        # per-shard atomic write (temp + os.replace) under the bounded
        # retry budget; the injection hook exercises exactly this path
        for fname, raw in jobs:
            def _attempt(fname=fname, raw=raw):
                rule = _res.inject("ckpt_write_fail", file=fname)
                if rule is not None:
                    raise _res.InjectedFault(
                        f"ckpt_write_fail injected for shard {fname}", rule)
                _res.atomic_write(os.path.join(path, fname), raw)
            _res.retry_io(_attempt, what=f"shard write {fname}")
        # EVERY rank records its own shard map: a multi-process save has
        # shards only THIS process can see, so a single coordinator meta
        # would silently omit every other rank's files and a later load
        # would zero-fill their regions. load_state_dict unions exactly
        # world_size per-rank metas (as recorded by rank 0's meta), so a
        # stale meta.r{k} from an earlier larger-topology save into the
        # same directory is ignored. The legacy single metadata.json is
        # written ONLY single-process — multi-process it would list just
        # this rank's shards, a silent-corruption trap for any consumer
        # reading it directly.
        meta_name = f"{_META}.r{rank}" if jax.process_count() > 1 else _META
        _res.retry_io(
            lambda: _res.atomic_write(os.path.join(path, meta_name),
                                      json.dumps(meta).encode()),
            what=f"metadata write {meta_name}")

    if async_save:
        # errors on the background thread surface at wait_async_saves();
        # a daemon thread swallowing a failed write would report a save
        # that never durably happened
        box: list = []

        def run():
            try:
                write_all()
            except BaseException as e:  # noqa: BLE001 — re-raised at wait
                box.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        _pending.append((t, box))
    else:
        write_all()


def wait_async_saves() -> None:
    """Join outstanding async saves; re-raises the first write error."""
    errors: list = []
    while _pending:
        t, box = _pending.pop()
        t.join()
        errors.extend(box)
    if errors:
        raise errors[0]


def _read_overlap(saved_shards, path, t_offs, t_exts, dtype):
    """Assemble one target shard by intersecting with saved index domains,
    reading only overlapping slices (mmap)."""
    out = np.zeros(t_exts, dtype=dtype)
    for s in saved_shards:
        s_offs, s_exts = s["offsets"], s["shape"]
        lo = [max(a, b) for a, b in zip(t_offs, s_offs)]
        hi = [min(a + ea, b + eb)
              for a, ea, b, eb in zip(t_offs, t_exts, s_offs, s_exts)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        src = np.load(os.path.join(path, s["file"]), mmap_mode="r")
        src_sel = tuple(slice(l - o, h - o)
                        for l, h, o in zip(lo, hi, s_offs))
        dst_sel = tuple(slice(l - o, h - o)
                        for l, h, o in zip(lo, hi, t_offs))
        out[dst_sel] = src[src_sel]
    return out


def _load_meta(path: str) -> dict:
    """Union the per-rank shard maps when present (multi-process saves);
    fall back to the legacy single metadata.json. Rank 0's meta records
    the save's world_size, and exactly ranks [0, world_size) are unioned
    — a stale meta.r{k} left behind by an earlier LARGER-topology save
    into the same directory must not leak old shard data into the load."""
    r0 = os.path.join(path, f"{_META}.r0")
    if not os.path.exists(r0):
        with open(os.path.join(path, _META)) as f:
            return json.load(f)
    with open(r0) as f:
        meta = json.load(f)
    for rank in range(1, int(meta.get("world_size", 1))):
        with open(os.path.join(path, f"{_META}.r{rank}")) as f:
            m = json.load(f)
        for key, entry in m["tensors"].items():
            tgt = meta["tensors"].setdefault(key, {**entry, "shards": []})
            seen = {tuple(s["offsets"]) + tuple(s["shape"])
                    for s in tgt["shards"]}
            for s in entry["shards"]:
                if tuple(s["offsets"]) + tuple(s["shape"]) not in seen:
                    tgt["shards"].append(s)
    return meta


def _verify_shard_files(meta: dict, path: str, keys) -> None:
    """Integrity pre-pass: every shard file a load will touch is checked
    against its recorded crc32 BEFORE any tensor is assigned, so a
    corrupt checkpoint never leaves the target state_dict half-filled.
    Legacy metas without crc32 fields verify vacuously."""
    checked: Dict[str, bool] = {}
    for key in keys:
        entry = meta["tensors"].get(key)
        if entry is None:
            continue
        for s in entry["shards"]:
            fname = s["file"]
            if fname in checked:
                continue
            checked[fname] = True
            full = os.path.join(path, fname)
            if not os.path.exists(full):
                raise _res.CheckpointCorrupt(
                    f"{path}: shard file {fname} (tensor {key!r}) missing")
            want = s.get("crc32")
            if want is None:
                continue
            injected = _res.inject("ckpt_read_corrupt",
                                   file=fname) is not None
            if injected or _res.crc32_file(full) != int(want):
                raise _res.CheckpointCorrupt(
                    f"{path}: shard {fname} (tensor {key!r}) checksum "
                    f"mismatch" + (" (injected)" if injected else ""))


def verify_checkpoint(path: str) -> bool:
    """True when every shard recorded in the checkpoint's metadata exists
    and matches its crc32 (vacuous for legacy checksum-less metas)."""
    try:
        meta = _load_meta(path)
        _verify_shard_files(meta, path, list(meta["tensors"]))
        return True
    except (_res.CheckpointCorrupt, OSError, KeyError, ValueError):
        return False


def load_state_dict(state_dict: Dict[str, object], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    fallback_paths: Sequence[str] = ()) -> None:
    """In-place load (paddle signature): each tensor in ``state_dict`` is
    filled from the checkpoint, resharded to ITS OWN current sharding —
    regardless of the topology that wrote the checkpoint (including a
    different PROCESS topology: per-rank shard maps are unioned).

    ``fallback_paths``: previous known-good checkpoints to fall back to
    (in order) when this one has a corrupt/missing shard; each fallback
    taken bumps ``resilience.ckpt_fallbacks``."""
    try:
        meta = _load_meta(path)
        _verify_shard_files(meta, path, list(state_dict))
    except (_res.CheckpointCorrupt, OSError) as e:
        if not fallback_paths:
            raise
        _res._count_fallback()
        warnings.warn(
            f"checkpoint {path} failed integrity verification ({e}); "
            f"falling back to {fallback_paths[0]}", RuntimeWarning)
        return load_state_dict(state_dict, fallback_paths[0],
                               process_group, coordinator_rank,
                               fallback_paths=fallback_paths[1:])

    for key, v in state_dict.items():
        if key not in meta["tensors"]:
            raise KeyError(f"checkpoint missing tensor: {key}")
        entry = meta["tensors"][key]
        arr = _arr_of(v)
        gshape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"]) if entry["dtype"] != "bfloat16" \
            else jnp.bfloat16
        if arr is not None and tuple(arr.shape) != gshape:
            raise ValueError(
                f"{key}: target shape {tuple(arr.shape)} != saved {gshape}")

        sharding = getattr(arr, "sharding", None) if arr is not None else None
        if sharding is not None and not isinstance(
                sharding, jax.sharding.SingleDeviceSharding):
            # per-device assembly via the read-overlap plan
            dev_map = sharding.devices_indices_map(gshape)
            pieces, devs = [], []
            for dev, index in dev_map.items():
                if dev.process_index != jax.process_index():
                    continue
                offs, exts = _index_to_offsets(index, gshape)
                block = _read_overlap(entry["shards"], path, offs, exts,
                                      np.float32 if dtype == jnp.bfloat16
                                      else dtype)
                pieces.append(jax.device_put(
                    jnp.asarray(block, dtype=dtype), dev))
                devs.append(dev)
            new = jax.make_array_from_single_device_arrays(
                gshape, sharding, pieces)
        else:
            full = _read_overlap(entry["shards"], path, [0] * len(gshape),
                                 list(gshape),
                                 np.float32 if dtype == jnp.bfloat16
                                 else dtype)
            new = jnp.asarray(full, dtype=dtype)

        if isinstance(v, Tensor):
            v._data = new
        else:
            state_dict[key] = new
