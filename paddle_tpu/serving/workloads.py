"""Seeded, replayable hostile-traffic workloads for a serving fleet.

ISSUE 16 tentpole (3): the scenario suite that makes "millions of
users" testable in CI. Each generator produces a deterministic arrival
plan from a seed — what a hostile slice of production traffic looks
like, shrunk to tiny models so tier-1 (CPU) replays it exactly:

  - ``burst``        — thundering-herd arrivals: whole waves land on the
                       same step, far beyond slot capacity, so admission
                       queueing and handoff brokering are saturated.
  - ``agentic``      — multi-turn agent chains: every turn's prompt is
                       the previous turn's prompt + output + a new tail,
                       building deep shared prefixes the radix trie
                       should turn into prefill skips.
  - ``mixed``        — long-context analysis jobs interleaved with
                       short chats: the classic head-of-line blocking
                       mix for chunked prefill + paged decode.
  - ``thrash``       — an adversarial tenant streaming never-repeating
                       prompts through a deliberately small page pool,
                       trying to evict a well-behaved tenant's shared
                       prefix out of the trie.
  - ``replica_kill`` — chaos: a decode replica is drained mid-burst
                       (the `CollectiveTimeout` path) and later
                       re-admitted; the scenario asserts zero request
                       loss and exact greedy outputs anyway.

`run_scenario` drives a fresh two/three-replica fleet through a plan
and emits one flat SERVING_BENCH-style row: fleet tokens/s, TTFT/e2e
percentiles (from the before/after delta of the router-measured
``serving.fleet.*`` histograms, so concurrent scenarios sharing one
process registry stay self-contained), prefill-skip rate, handoff
count/latency, a zero-request-loss flag, and an output-token checksum —
the deterministic fields are what `tools/perf_gate.py` locks with exact
bands and `tools/fleetboard.py --selftest` replays.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import observability as _obs
from .. import resilience as _res
from ..observability import fleet as _fleet
from ..observability import tracing as _tracing
from .controller import FleetController, SLOTargets
from .engine import ServingEngine
from .router import FleetRouter

__all__ = ["SCENARIOS", "Arrival", "Chaos", "Plan", "make_plan",
           "build_fleet", "run_scenario", "run_all", "ROW_DETERMINISTIC",
           "ROW_TIMING"]

#: the five hostile-traffic scenarios, in canonical order
SCENARIOS: Tuple[str, ...] = ("burst", "agentic", "mixed", "thrash",
                              "replica_kill")

#: row fields that replay bit-exactly from the seed (perf_gate locks
#: these with exact [v, v] bands; fleetboard --selftest re-checks them)
ROW_DETERMINISTIC: Tuple[str, ...] = (
    "requests", "completed", "zero_loss", "output_checksum", "handoffs",
    "shed", "ttft_p90_steps", "e2e_p90_steps")
#: machine-dependent row fields (noise-banded, regenerated on-machine)
ROW_TIMING: Tuple[str, ...] = (
    "fleet_tokens_per_s", "ttft_p50_ms", "ttft_p90_ms", "ttft_p99_ms",
    "e2e_p50_ms", "e2e_p90_ms", "e2e_p99_ms", "handoff_latency_ms",
    "wall_s")


@dataclass
class Arrival:
    """One planned request. `after` chains multi-turn agents: the
    arrival is held until the named parent's result lands, then its
    prompt becomes parent_prompt + parent_output + `prompt` (the new
    user turn) — the deep-shared-prefix shape agentic traffic has."""
    request_id: str
    prompt: np.ndarray
    max_new: int
    at_step: int = 0
    tenant: Optional[str] = None
    priority: int = 0
    after: Optional[str] = None


@dataclass
class Chaos:
    """Kill `replica` (router.drain — the CollectiveTimeout path) once
    `at_step` is reached, re-admitting it `readmit_after` steps later."""
    replica: str
    at_step: int
    readmit_after: int = 4


@dataclass
class Plan:
    name: str
    seed: int
    arrivals: List[Arrival]
    #: replica name -> role, in construction order
    roles: Dict[str, str]
    chaos: Optional[Chaos] = None
    #: engine kwargs applied to every replica
    engine_kw: Dict[str, Any] = field(default_factory=dict)
    #: per-replica overrides (thrash squeezes only the prefill pool —
    #: a starved decode pool would just park handoffs forever)
    replica_kw: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    #: replica_kill compares every output against solo greedy decode
    check_exact: bool = False
    #: declared SLO targets — what "holding the SLO" means for this
    #: traffic shape; recorded in the emitted row, actuated by the
    #: autopilot when `run_scenario(autopilot=True)`
    slo: Optional[SLOTargets] = None


def _prompt(rng: np.random.Generator, vocab: int, n: int) -> np.ndarray:
    return rng.integers(1, vocab, size=n).astype(np.int32)


def make_plan(name: str, seed: int = 0, vocab: int = 128) -> Plan:
    """Build the named scenario's deterministic arrival plan. `vocab`
    must not exceed the serving model's vocab size."""
    rng = np.random.default_rng([seed, SCENARIOS.index(name)])
    two = {"pf0": "prefill", "dec0": "decode"}
    arr: List[Arrival] = []
    if name == "burst":
        # three waves of 4, each wave landing on one step
        for wave, step in enumerate((0, 2, 4)):
            for i in range(4):
                arr.append(Arrival(f"burst-{wave}-{i}",
                                   _prompt(rng, vocab, int(rng.integers(5, 9))),
                                   int(rng.integers(3, 6)), at_step=step,
                                   tenant="burst"))
        return Plan(name, seed, arr, two,
                    slo=SLOTargets(ttft_p90_ms=500.0, e2e_p90_ms=2000.0,
                                   ttft_p90_steps=12, e2e_p90_steps=18,
                                   queue_depth=4))
    if name == "agentic":
        # 3 agents x 3 turns; turns 2..3 extend the previous turn
        for a in range(3):
            root = _prompt(rng, vocab, int(rng.integers(6, 10)))
            arr.append(Arrival(f"agent{a}-t0", root, 3, at_step=a,
                               tenant=f"agent{a}"))
            for t in (1, 2):
                arr.append(Arrival(
                    f"agent{a}-t{t}", _prompt(rng, vocab, 2), 3,
                    tenant=f"agent{a}", after=f"agent{a}-t{t - 1}"))
        return Plan(name, seed, arr, two,
                    slo=SLOTargets(ttft_p90_ms=500.0, e2e_p90_ms=3000.0,
                                   ttft_p90_steps=8, e2e_p90_steps=10,
                                   queue_depth=4))
    if name == "mixed":
        # two long-context jobs up front, six short chats trickling in
        for i in range(2):
            arr.append(Arrival(f"long{i}", _prompt(rng, vocab, 24), 4,
                               at_step=0, tenant="analyst"))
        for i in range(6):
            arr.append(Arrival(f"chat{i}",
                               _prompt(rng, vocab, int(rng.integers(4, 7))),
                               int(rng.integers(2, 5)), at_step=i,
                               tenant="chat"))
        return Plan(name, seed, arr, two,
                    slo=SLOTargets(ttft_p90_ms=800.0, e2e_p90_ms=3000.0,
                                   ttft_p90_steps=13, e2e_p90_steps=15,
                                   queue_depth=4))
    if name == "thrash":
        # a good tenant re-using one prefix vs an adversary streaming
        # unique prompts through a small pool (num_pages squeezed)
        shared = _prompt(rng, vocab, 8)
        for i in range(4):
            arr.append(Arrival(
                f"good{i}",
                np.concatenate([shared, _prompt(rng, vocab, 2)]),
                3, at_step=2 * i, tenant="good"))
        for i in range(6):
            arr.append(Arrival(f"evil{i}", _prompt(rng, vocab, 12), 2,
                               at_step=i, tenant="adversary",
                               priority=0))
        return Plan(name, seed, arr, two,
                    replica_kw={"pf0": {"num_pages": 24}},
                    slo=SLOTargets(ttft_p90_ms=800.0, e2e_p90_ms=3000.0,
                                   ttft_p90_steps=15, e2e_p90_steps=16,
                                   queue_depth=3, pool_high=0.7,
                                   pool_low=0.4))
    if name == "replica_kill":
        roles = {"pf0": "prefill", "dec0": "decode", "dec1": "decode"}
        for i in range(8):
            arr.append(Arrival(f"kill{i}",
                               _prompt(rng, vocab, int(rng.integers(5, 9))),
                               int(rng.integers(3, 6)),
                               at_step=i // 2, tenant="burst"))
        return Plan(name, seed, arr, roles,
                    chaos=Chaos("dec0", at_step=6, readmit_after=4),
                    check_exact=True,
                    slo=SLOTargets(ttft_p90_ms=800.0, e2e_p90_ms=4000.0,
                                   ttft_p90_steps=10, e2e_p90_steps=14,
                                   queue_depth=4))
    raise ValueError(f"unknown scenario {name!r} (one of {SCENARIOS})")


def build_fleet(model, roles: Dict[str, str],
                replica_kw: Optional[Dict[str, Dict[str, Any]]] = None,
                **engine_kw) -> FleetRouter:
    """Fresh fleet of tiny replicas sharing `model` weights (page_size 4
    / 2 slots / prefill_chunk 4 unless overridden; `replica_kw` layers
    per-replica overrides on top)."""
    replicas = {}
    for name, role in roles.items():
        kw = {"max_slots": 2, "page_size": 4, "prefill_chunk": 4}
        kw.update(engine_kw)
        kw.update((replica_kw or {}).get(name, {}))
        replicas[name] = ServingEngine(model, role=role, replica=name,
                                       **kw)
    return FleetRouter(replicas)


_SHARED_TOKENS = "serving.prefix_cache.shared_tokens"


def _fleet_hist_snapshot() -> Dict[str, Any]:
    snap = _obs.snapshot()
    keep = _fleet.FLEET_SLO_METRICS + (_SHARED_TOKENS,)
    return {n: snap[n] for n in keep if n in snap}


def _counter_value(snap: Dict[str, Any], name: str) -> float:
    e = snap.get(name)
    if not e or not e["series"]:
        return 0.0
    return float(e["series"][0]["value"])


def _delta_pXX(before: Dict[str, Any], after: Dict[str, Any],
               name: str, q: float) -> Optional[float]:
    """Percentile of ONLY this scenario's observations: the bucket-count
    delta between the before/after snapshots of one fleet histogram
    (scenarios share the process-wide default registry)."""
    b, a = before.get(name), after.get(name)
    if a is None:
        return None
    sa = a["series"][0]
    counts = list(sa["counts"])
    total = sa["count"]
    if b is not None:
        sb = b["series"][0]
        counts = [x - y for x, y in zip(counts, sb["counts"])]
        total -= sb["count"]
    if total <= 0:
        return None
    series = {"counts": counts, "sum": 0.0, "count": total}
    return _tracing.percentile(series, q, buckets=a["buckets"])


def run_scenario(name: str, model, seed: int = 0,
                 vocab: Optional[int] = None,
                 max_steps: int = 100000,
                 autopilot: bool = False) -> Dict[str, Any]:
    """Replay one scenario against a fresh fleet; return its
    SERVING_BENCH row (see module docstring for the field split).

    With `autopilot=True` the SAME traffic replays with the ISSUE-18
    feedback controllers closed around the declared `Plan.slo` targets:
    every replica gets an `EngineController` (via the engine's
    `slo_targets` kwarg) and the router a `FleetController`. All
    controller sensors are deterministic, so the autopilot rows replay
    bit-exactly too — fleetboard commits them side by side with the
    static rows."""
    if vocab is None:
        vocab = int(getattr(model.config, "vocab_size", 128))
    plan = make_plan(name, seed=seed, vocab=min(vocab, 128))
    engine_kw = dict(plan.engine_kw)
    if autopilot:
        engine_kw["slo_targets"] = plan.slo
    router = build_fleet(model, plan.roles, replica_kw=plan.replica_kw,
                         **engine_kw)
    if autopilot:
        FleetController(router, plan.slo)
    before = _fleet_hist_snapshot()
    pending = list(plan.arrivals)
    held = {a.request_id: a for a in pending if a.after}
    ready = [a for a in pending if not a.after]
    prompts: Dict[str, np.ndarray] = {}
    results: Dict[str, np.ndarray] = {}
    submitted: List[str] = []
    shed: List[str] = []
    chaos_done = readmit_at = None
    t0 = time.perf_counter()
    step = 0
    while ready or held or router.has_work():
        if step >= max_steps:
            raise RuntimeError(f"scenario {name} did not drain "
                               f"({router.stats()})")
        for a in [a for a in ready if a.at_step <= step]:
            ready.remove(a)
            try:
                router.submit(a.prompt, a.max_new,
                              request_id=a.request_id,
                              priority=a.priority, tenant=a.tenant)
            except _res.Shed:
                # the controller refused it at the door: a deliberate,
                # traced outcome — NOT a lost request
                shed.append(a.request_id)
                continue
            except _res.Overloaded:
                # admission backpressure: retry the arrival next step
                a.at_step = step + 1
                ready.append(a)
                continue
            prompts[a.request_id] = a.prompt
            submitted.append(a.request_id)
        if plan.chaos is not None and chaos_done is None \
                and step >= plan.chaos.at_step:
            router.drain(plan.chaos.replica)
            chaos_done = step
            readmit_at = step + plan.chaos.readmit_after
        if readmit_at is not None and step >= readmit_at:
            router.readmit(plan.chaos.replica)
            readmit_at = None
        router.step()
        for rid, res in router.collect().items():
            assert isinstance(res, np.ndarray), \
                f"scenario {name}: request {rid} lost -> {res!r}"
            results[rid] = res
            # release any turn chained on this result: its prompt is
            # the full conversation so far plus the new user tail
            for child in [c for c in held.values() if c.after == rid]:
                del held[child.request_id]
                child.prompt = np.concatenate(
                    [prompts[rid], res.astype(np.int32), child.prompt])
                child.after = None
                child.at_step = step + 1
                ready.append(child)
        step += 1
    wall = time.perf_counter() - t0
    after = _fleet_hist_snapshot()
    zero_loss = int(set(submitted) == set(results)
                    and all(isinstance(r, np.ndarray)
                            for r in results.values()))
    if plan.check_exact:
        from ..generation import generate_cached
        import paddle_tpu as paddle
        for rid in submitted:
            want, _ = generate_cached(
                model, paddle.to_tensor(prompts[rid][None]),
                max_new_tokens=len(results[rid]),
                decode_strategy="greedy_search")
            got = results[rid]
            if not np.array_equal(want.numpy()[0], got):
                raise AssertionError(
                    f"scenario {name}: request {rid} diverged from "
                    f"solo greedy decode after chaos")
    new_tokens = int(sum(r.size for r in results.values()))
    prompt_tokens = int(sum(p.size for p in prompts.values()))
    steps_slo = router.step_slo_summary()
    row: Dict[str, Any] = {
        "scenario": name + ("_autopilot" if autopilot else ""),
        "seed": seed, "autopilot": int(autopilot),
        "requests": len(submitted), "completed": len(results),
        "zero_loss": zero_loss,
        "shed": len(shed),
        # step-indexed fleet latencies: deterministic on a seeded
        # replay, so they live in ROW_DETERMINISTIC and pin the
        # autopilot's latency win with exact perf_gate bands
        "ttft_p90_steps": steps_slo["ttft_p90_steps"],
        "e2e_p90_steps": steps_slo["e2e_p90_steps"],
        "ttft_p50_steps": steps_slo["ttft_p50_steps"],
        "e2e_p50_steps": steps_slo["e2e_p50_steps"],
        # what "holding the SLO" meant for this traffic shape
        "slo": plan.slo.as_row() if plan.slo is not None else {},
        "output_checksum": int(sum(int(t) for r in results.values()
                                   for t in r.tolist()) % 1_000_000_007),
        "handoffs": router.handoff_count,
        # prompt tokens whose prefill the fleet skipped via the trie,
        # scenario-scoped through the before/after counter delta
        "prefill_skip_rate": (
            (_counter_value(after, _SHARED_TOKENS)
             - _counter_value(before, _SHARED_TOKENS)) / prompt_tokens
            if prompt_tokens else 0.0),
        "fleet_tokens_per_s": new_tokens / wall if wall > 0 else 0.0,
        "handoff_latency_ms": router.stats()["handoff_latency_s"] * 1e3,
        "wall_s": wall,
        "steps": step,
    }
    for metric, key in (("serving.fleet.ttft_seconds", "ttft"),
                        ("serving.fleet.e2e_seconds", "e2e")):
        for q in (50, 90, 99):
            v = _delta_pXX(before, after, metric, q)
            row[f"{key}_p{q}_ms"] = (v * 1e3) if v is not None else None
    return row


def run_all(model, seed: int = 0,
            autopilot: bool = False) -> Dict[str, Dict[str, Any]]:
    """All five scenarios, canonical order: {scenario: row}. With
    `autopilot=True` the rows are keyed ``<scenario>_autopilot``."""
    suffix = "_autopilot" if autopilot else ""
    return {name + suffix: run_scenario(name, model, seed=seed,
                                        autopilot=autopilot)
            for name in SCENARIOS}
