"""Core data model for paddlelint (docs/ANALYSIS.md).

A :class:`Finding` is one reported hazard: rule id, severity, location,
the enclosing function's qualname, a human message and a fix hint, plus a
``detail`` token — a short, line-number-free signature of the offending
construct so baseline entries survive unrelated edits to the file
(:attr:`Finding.baseline_key` is ``rule|path|qualname|detail``).

Suppressions are source comments, matched against the finding's line:

    x = float(t)          # paddlelint: disable=PT001
    # paddlelint: disable-file=PT003   (anywhere in the file: whole file)

Severity ladder: ``error`` (will break or silently mis-trace at runtime),
``warning`` (perf/correctness hazard worth an explicit decision), ``info``
(patterns that are often deliberate — reported only under ``--strict``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Set

SEVERITIES = ("info", "warning", "error")

#: rule id -> one-line description (filled by the rule modules at import)
RULES: Dict[str, str] = {}
#: rule id -> nominal severity (a rule may still emit individual findings
#: at a lower severity, e.g. PK102's lane-alignment advisories)
RULE_SEVERITIES: Dict[str, str] = {}
#: rule id -> implementing module. The family prefix groups rules
#: conceptually; the registry records where each one actually lives, so
#: cross-filed rules (PC201 is numbered in the collective family but
#: guards a kernel-adjacent hazard and lives in rules_collective.py) are
#: documented here instead of by filename convention.
RULE_MODULES: Dict[str, str] = {}

#: family prefix -> one-line description (``--list-rules`` group headers)
FAMILIES: Dict[str, str] = {
    "PT": "python-tracing hygiene (host leaks, retrace churn, RNG/thread "
          "discipline)",
    "PK": "pallas kernel structure (grids, BlockSpecs, refs, aliases, "
          "accumulators)",
    "PC": "collectives (axis names, branch-guarded issue order)",
    "PS": "sharding/mesh (PartitionSpec vs mesh axes, donation, "
          "resharding)",
    "PF": "kernel memory lane (VMEM budgets, donation dataflow, dtype "
          "chains, fusion advisories, cost-model drift)",
    "PE": "grid memory-effects lane (write-write races, donated-read "
          "ordering, accumulator guards, scatter disjointness, fusion "
          "legality, write-side cost drift)",
}


def register_rule(rule_id: str, description: str,
                  severity: str = "warning", module: str = "") -> None:
    RULES[rule_id] = description
    RULE_SEVERITIES[rule_id] = severity
    RULE_MODULES[rule_id] = module


def rule_family(rule_id: str) -> str:
    """'PK101' -> 'PK': the alphabetic prefix groups rules into families
    (see :data:`FAMILIES`)."""
    return rule_id.rstrip("0123456789") or rule_id


@dataclasses.dataclass
class Finding:
    rule: str                 # "PT001" .. "PT006"
    severity: str             # "error" | "warning" | "info"
    path: str                 # repo-relative posix path
    line: int
    col: int
    qualname: str             # enclosing function ("<module>" at top level)
    message: str
    hint: str = ""
    detail: str = ""          # stable construct signature for baselining

    @property
    def baseline_key(self) -> str:
        return f"{self.rule}|{self.path}|{self.qualname}|{self.detail}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "qualname": self.qualname, "message": self.message,
                "hint": self.hint, "detail": self.detail,
                "baseline_key": self.baseline_key}

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        hint = f"\n      hint: {self.hint}" if self.hint else ""
        return (f"{loc}: {self.rule} [{self.severity}] "
                f"({self.qualname}) {self.message}{hint}")


@dataclasses.dataclass
class Config:
    """Analyzer knobs. ``hot_entry_patterns`` are regexes matched against
    ``module:qualname`` (module relative to the package root) — the PT003
    reachability roots."""
    rules: Optional[Set[str]] = None     # None = all registered
    strict: bool = False                 # include info-severity findings
    hot_entry_patterns: List[str] = dataclasses.field(default_factory=lambda: [
        r"(^|[.:])training_step$",
        r"(^|[.:])_run_loop$",
        r"_step_body$",
        r"(^|[.:])generate_cached$",
        r"(^|[.:])generate_compiled$",
        r"Predictor\.run$",
    ])

    def wants(self, rule_id: str) -> bool:
        return self.rules is None or rule_id in self.rules


_SUPPRESS_RE = re.compile(
    r"#\s*paddlelint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_,\s]+?)\s*(?:#|$)")


def collect_suppressions(source: str):
    """-> (line_no -> set(rule_ids or {'all'}), file-wide set)."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip().upper() if r.strip().lower() != "all" else "all"
                 for r in m.group(2).split(",") if r.strip()}
        if m.group(1) == "disable-file":
            file_wide |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
    return per_line, file_wide


def is_suppressed(f: Finding, per_line, file_wide) -> bool:
    if "all" in file_wide or f.rule in file_wide:
        return True
    rules = per_line.get(f.line, ())
    return "all" in rules or f.rule in rules
