"""Text generation (ref capability: PaddleNLP GenerationMixin —
model.generate with greedy_search / sampling decode strategies,
paddlenlp/generation/utils.py).

TPU-first mechanism: autoregressive decoding runs the model on a FIXED
[B, prompt+max_new_tokens] buffer every step and reads the logits at the
current position. Causal attention makes positions > t irrelevant to the
step-t logits, so the pad tail is harmless — and the constant shape means
ONE compiled executable serves every step (no per-length recompiles, the
XLA analog of the reference's static decode graph). The serving-grade
O(1)-per-step path is the paged/masked decode attention kernel set
(ops/paged_attention.py, incubate.nn.functional.masked_multihead_attention)
used by the inference Predictor; this module is the framework-level
`generate()` every CausalLM model family shares.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import observability as _obs
from . import resilience as _res
from .core.tensor import Tensor
from .core import autograd as ag
from .framework.random import next_key

__all__ = ["generate"]


def _finalize_tokens(out_tokens, out_scores, B, max_new_tokens,
                     pad_token_id):
    """Stack + right-pad the per-step token/score lists to the full
    [B, max_new_tokens] width (early eos or deadline expiry leaves the
    lists short; an expiry before the first token leaves them empty)."""
    if out_tokens:
        gen = jnp.stack(out_tokens, 1)
        sc = jnp.stack(out_scores, 1)
    else:
        gen = jnp.zeros((B, 0), jnp.int32)
        sc = jnp.zeros((B, 0), jnp.float32)
    if gen.shape[1] < max_new_tokens:
        padw = max_new_tokens - gen.shape[1]
        gen = jnp.concatenate(
            [gen, jnp.full((B, padw), pad_token_id, jnp.int32)], 1)
        sc = jnp.concatenate([sc, jnp.zeros((B, padw), sc.dtype)], 1)
    return Tensor(gen), Tensor(sc)


def _timeout_result(kind, dl, completed, partial):
    """Typed deadline-expiry return (resilience.TimeoutResult): counts
    the miss and carries whatever tokens were produced in time."""
    _res.deadline_miss()
    return _res.TimeoutResult(kind=kind, budget_s=dl.budget_s,
                              elapsed_s=dl.elapsed_s,
                              completed=completed, partial=partial)

# serving metrics (ISSUE 1): prefill vs decode token throughput, request
# batch sizes, and decode-loop program-cache hit rate. Durations are host
# wall-clock around the dispatching section; PJRT dispatch is async, so a
# section's time includes device wait only where the code forces a fetch
# (documented in docs/OBSERVABILITY.md).
_SRV_REQS = _obs.registry().counter(
    "pt_serving_requests_total", "generate-family calls", labels=("path",))
_SRV_PREFILL_TOK = _obs.registry().counter(
    "pt_serving_prefill_tokens_total", "prompt tokens prefilled")
_SRV_DECODE_TOK = _obs.registry().counter(
    "pt_serving_decode_tokens_total", "tokens produced by decode steps")
_SRV_PREFILL_S = _obs.registry().histogram(
    "pt_serving_prefill_seconds", "prefill section wall time",
    labels=("path",))
_SRV_DECODE_S = _obs.registry().histogram(
    "pt_serving_decode_seconds", "decode section wall time",
    labels=("path",))
_SRV_BATCH = _obs.registry().histogram(
    "pt_serving_batch_size", "request batch size",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
_JIT_CACHE = _obs.registry().counter(
    "pt_jit_cache_events_total", "compiled-program cache lookups",
    labels=("cache", "event"))


def _logits_fn(model, ids_arr):
    """One forward on the padded buffer → [B, S, V] raw logits array."""
    out = model(Tensor(ids_arr))
    if isinstance(out, tuple):
        out = out[-1]
    return out._data


def _sample_token(logits, strategy, top_k, top_p, temperature):
    """logits [B, V] → token ids [B]."""
    if strategy == "greedy_search" or (temperature is not None
                                       and temperature <= 0.0):
        # temperature 0 degenerates to greedy (the usual convention),
        # never a silent fall-through to temperature-1 sampling
        return jnp.argmax(logits, -1).astype(jnp.int32)
    return jax.random.categorical(
        next_key(), _filter_logits(logits, top_k, top_p, temperature),
        -1).astype(jnp.int32)


def _filter_logits(logits, top_k, top_p, temperature):
    """The temperature/top-k/top-p part of _sample_token, key-free (shared
    by the host-loop and compiled samplers); keeps the smallest prefix with
    cumulative prob >= top_p."""
    if temperature is not None and temperature != 1.0:
        logits = logits / temperature
    if top_k:
        kth = jnp.sort(logits, -1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, -1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, -1)
        cum = jnp.cumsum(probs, -1)
        cutoff_idx = jnp.sum(cum < top_p, -1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], -1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(model, input_ids, max_new_tokens: int = 20,
             decode_strategy: str = "sampling", top_k: Optional[int] = None,
             top_p: Optional[float] = None, temperature: float = 1.0,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             deadline_s: Optional[float] = None):
    """ref: PaddleNLP model.generate(...). Returns (generated_ids, scores):
    generated_ids [B, max_new_tokens] holds ONLY the new tokens (prompt
    excluded, PaddleNLP convention), padded with pad_token_id after eos;
    scores [B, max_new_tokens] are the chosen tokens' log-probs.

    ``deadline_s`` bounds the request wall-clock: the decode loop stops
    at the first step past the budget and the call returns a falsy
    resilience.TimeoutResult whose .partial carries the (padded) tokens
    produced in time — a typed outcome, never an unbounded hang.
    """
    if decode_strategy not in ("greedy_search", "sampling"):
        raise ValueError(f"decode_strategy {decode_strategy!r}: expected "
                         "'greedy_search' or 'sampling'")
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, S0 = ids.shape
    total = S0 + max_new_tokens
    buf = jnp.concatenate(
        [ids, jnp.full((B, max_new_tokens), pad_token_id, jnp.int32)], 1)
    finished = jnp.zeros((B,), bool)
    out_tokens = []
    out_scores = []
    dl = _res.Deadline(deadline_s) if deadline_s else None
    timed_out = False
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        with ag.no_grad():
            for t in range(S0 - 1, total - 1):
                if dl is not None and dl.expired():
                    timed_out = True
                    break
                logits = _logits_fn(model, buf)[:, t]
                tok = _sample_token(logits, decode_strategy, top_k, top_p,
                                    temperature)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                score = jnp.take_along_axis(logp, tok[:, None], -1)[:, 0]
                if eos_token_id is not None:
                    tok = jnp.where(finished, pad_token_id, tok)
                    score = jnp.where(finished, 0.0, score)
                    finished = finished | (tok == eos_token_id)
                buf = buf.at[:, t + 1].set(tok)
                out_tokens.append(tok)
                out_scores.append(score)
                if eos_token_id is not None and bool(jnp.all(finished)):
                    break
    finally:
        if was_training and hasattr(model, "train"):
            model.train()
    partial = _finalize_tokens(out_tokens, out_scores, B, max_new_tokens,
                               pad_token_id)
    if timed_out:
        return _timeout_result("generate", dl, len(out_tokens), partial)
    return partial


# ---------------------------------------------------------------------------
# KV-cache decoding (serving-grade O(1)-per-step path; ref capability:
# PaddleNLP use_cache generation over the masked/block decode attention
# kernels — paddle/phi/kernels/fusion/gpu/masked_multihead_attention)
# ---------------------------------------------------------------------------
def _llama_decode_params(model, weight_only_int8: bool = False,
                         weight_only_quant=None):
    """Extract the cached-decode weight tree from a Llama-family CausalLM
    (LlamaForCausalLM, Qwen2ForCausalLM — same GQA backbone; Qwen2 adds
    q/k/v biases, carried as optional leaves).

    ``weight_only_int8``: store every 2-D matmul weight as (int8 values,
    per-output-channel f32 scale) — ops/quant.weight_quantize — halving
    the HBM weight reads that bound decode; the body dequantizes in VMEM
    (ref: paddle/nn/quant weight-only deploy path).
    ``weight_only_quant``: 'int8' (same as the bool) or 'int4' (packed
    nibbles, quarter the weight reads; decode contracts even/odd rows so
    the unpack fuses — see _int4_halves)."""
    algo, enabled = _woq_algo(weight_only_int8, weight_only_quant)
    cfg = model.config
    inner = getattr(model, "llama", None)
    if inner is None:
        inner = getattr(model, "qwen2", None)
    if inner is None:
        raise NotImplementedError(
            "KV-cache generation: expected a Llama-family model "
            "(model.llama / model.qwen2)")
    if getattr(cfg, "fuse_attention_qkv", False) or \
            getattr(cfg, "fuse_attention_ffn", False):
        raise NotImplementedError(
            "use_cache generation supports the unfused Llama layout; the "
            "fused qkv/ffn packs are pretrain perf knobs")
    layers = []
    for lyr in inner.layers:
        a, m = lyr.self_attn, lyr.mlp
        d = dict(
            ln1=lyr.input_layernorm.weight._data,
            wq=a.q_proj.weight._data, wk=a.k_proj.weight._data,
            wv=a.v_proj.weight._data, wo=a.o_proj.weight._data,
            ln2=lyr.post_attention_layernorm.weight._data,
            wg=m.gate_proj.weight._data, wu=m.up_proj.weight._data,
            wd=m.down_proj.weight._data)
        if getattr(a.q_proj, "bias", None) is not None:
            d["bq"] = a.q_proj.bias._data
            d["bk"] = a.k_proj.bias._data
            d["bv"] = a.v_proj.bias._data
        for k in ("wq", "wk", "wv", "wo", "wg", "wu", "wd"):
            _q8(d, k, enabled, algo)
        layers.append(d)
    head = model.lm_head.weight._data if model.lm_head is not None else None
    p = dict(cfg=cfg, family="llama",
             embed=inner.embed_tokens.weight._data,
             layers=layers, norm=inner.norm.weight._data, head=head,
             cos=inner.rope_cos._data, sin=inner.rope_sin._data)
    if enabled and head is not None:
        _q8(p, "head", True, algo)
        p["head"] = None
    return p


def _gpt_decode_params(model):
    """GPT family: fused qkv (+bias), LayerNorms with biases, GELU MLP,
    learned positions, no rope."""
    gpt = model.gpt
    layers = []
    for blk in gpt.h:
        layers.append(dict(
            ln1w=blk.ln_1.weight._data, ln1b=blk.ln_1.bias._data,
            wqkv=blk.attn.qkv.weight._data, bqkv=blk.attn.qkv.bias._data,
            wo=blk.attn.proj.weight._data, bo=blk.attn.proj.bias._data,
            ln2w=blk.ln_2.weight._data, ln2b=blk.ln_2.bias._data,
            wi=blk.mlp.fc_in.weight._data, bi=blk.mlp.fc_in.bias._data,
            wf=blk.mlp.fc_out.weight._data, bf=blk.mlp.fc_out.bias._data))
    head = model.lm_head.weight._data if model.lm_head is not None else None
    return dict(cfg=model.config, family="gpt",
                embed=gpt.embed_tokens.weight._data,
                pos=gpt.embed_positions.weight._data,
                layers=layers, normw=gpt.ln_f.weight._data,
                normb=gpt.ln_f.bias._data, head=head)


def _woq_algo(weight_only_int8, weight_only_quant):
    """Normalize the two public quant knobs to (algo, enabled)."""
    if weight_only_quant not in (None, "int8", "int4"):
        raise ValueError(
            f"weight_only_quant {weight_only_quant!r}: expected "
            "'int8' or 'int4'")
    if weight_only_quant:
        if weight_only_int8 and weight_only_quant != "int8":
            raise ValueError(
                "conflicting quant knobs: weight_only_int8=True with "
                f"weight_only_quant={weight_only_quant!r} — drop the "
                "bool or make them agree")
        return "weight_only_" + weight_only_quant, True
    return "weight_only_int8", bool(weight_only_int8)


def _q8(d, key, enabled: bool = True, algo: str = "weight_only_int8"):
    """Quantize d[key] in place to (int8 or packed-int4 values,
    per-out-channel f32 scale) — the weight-only deploy transform shared
    by every decode family. int8 stores key_q [K, N]; int4 stores key_q4
    [K/2, N] (two nibbles per byte — consumers split the contraction
    into even/odd rows so the unpack stays an elementwise chain XLA
    fuses into the dot operand loads, never a materialized bf16 weight).
    3-D expert stacks [E, K, N] quantize per expert (vmapped absmax)
    with scales [E, N]; None entries and disabled calls are no-ops."""
    if not enabled or d.get(key) is None:
        return
    from .ops.quant import weight_quantize
    import functools
    w = d.pop(key)
    qfn = functools.partial(weight_quantize, algo=algo)
    if w.ndim == 3:
        qw, sc = jax.vmap(qfn)(w)
    else:
        qw, sc = qfn(w)
    d[key + ("_q4" if algo == "weight_only_int4" else "_q")] = qw
    d[key + "_s"] = sc.astype(jnp.float32)


def _mlp_params(lyr, weight_only_int8: bool = False,
                algo: str = "weight_only_int8"):
    """Per-layer FFN weights: (weight dict, static routing knobs or None).
    Dense SwiGLU (llama layout) or routed MoE (dropless per-token routing —
    serving never drops tokens; the capacity factor is a training
    regularizer, ref fused MoE serving kernels). Static knobs must stay out
    of the weight tree: it rides through jit as arguments.

    ``weight_only_int8`` quantizes the dense ffn, the per-expert stacks
    (per-expert out-channel scales) and the shared expert with ``algo``
    ('weight_only_int8' or 'weight_only_int4' — the 3-D expert stacks
    pack per expert via the vmapped weight_quantize and read back
    through _dq's plane-interleave); the ROUTER gate stays fp — it is
    tiny and routing decisions are precision-sensitive (a flipped top-k
    is a different program, not a rounding error)."""
    m = lyr.mlp
    from .incubate.moe import MoELayer
    if isinstance(m, MoELayer):
        if m.activation != "swiglu":
            raise NotImplementedError(
                "cached MoE decode supports swiglu experts (the LM configs)")
        if not m.dropless:
            import warnings
            warnings.warn(
                "cached/compiled MoE decode always routes DROPLESS (no "
                "capacity drops — serving never discards tokens); this "
                "model trains in capacity mode, so cached decode can "
                "diverge from generate() near capacity overflow. Exactness "
                "vs the buffer path holds for moe_dropless=True models.",
                stacklevel=3)
        mo = dict(gate=m.gate_weight._data,
                  wge=m.w_gate._data if m.w_gate is not None else None,
                  wup=m.w_up._data, wdn=m.w_down._data)
        for k in ("wge", "wup", "wdn"):
            _q8(mo, k, weight_only_int8, algo)
        if m.shared_up is not None:
            sh = dict(sg=m.shared_gate.weight._data,
                      su=m.shared_up.weight._data,
                      sd=m.shared_down.weight._data)
            for k in ("sg", "su", "sd"):
                _q8(sh, k, weight_only_int8, algo)
            mo["shared"] = sh
        return dict(moe=mo), dict(top_k=m.top_k, renorm=m.renormalize)
    d = dict(wg=m.gate_proj.weight._data, wu=m.up_proj.weight._data,
             wd=m.down_proj.weight._data)
    for k in ("wg", "wu", "wd"):
        _q8(d, k, weight_only_int8, algo)
    return d, None


def _moe_decode_params(model, weight_only_int8: bool = False,
                       algo: str = "weight_only_int8"):
    """MoEForCausalLM (Qwen2-MoE/DeepSeekMoE pattern): llama attention
    backbone, per-layer dense-or-routed FFN. ``weight_only_int8`` cuts
    the HBM weight reads (the expert stacks are the bulk of them) with
    ``algo`` — 'weight_only_int4' packs the 3-D expert stacks two
    nibbles per byte for quarter-width reads — see _llama_decode_params."""
    inner = model.model
    cfg = model.config
    layers = []
    moe_static = []
    for lyr in inner.layers:
        a = lyr.self_attn
        d = dict(
            ln1=lyr.input_layernorm.weight._data,
            wq=a.q_proj.weight._data, wk=a.k_proj.weight._data,
            wv=a.v_proj.weight._data, wo=a.o_proj.weight._data,
            ln2=lyr.post_attention_layernorm.weight._data)
        for k in ("wq", "wk", "wv", "wo"):
            _q8(d, k, weight_only_int8, algo)
        mlp_w, mlp_st = _mlp_params(lyr, weight_only_int8, algo)
        d.update(mlp_w)
        layers.append(d)
        moe_static.append(mlp_st)
    head = model.lm_head.weight._data if model.lm_head is not None else None
    p = dict(cfg=cfg, family="moe",
             embed=inner.embed_tokens.weight._data,
             layers=layers, norm=inner.norm.weight._data, head=head,
             cos=inner.rope_cos._data, sin=inner.rope_sin._data,
             moe_static=tuple(moe_static))
    if weight_only_int8 and head is not None:
        _q8(p, "head", True, algo)
        p["head"] = None
    return p


def _mla_decode_params(model, weight_only_int8: bool = False,
                       algo: str = "weight_only_int8"):
    """DeepSeekV2ForCausalLM: multi-head latent attention with the
    ABSORBED decode formulation — the KV cache stores only the normalized
    latent [r] + shared rope key [dr] per token, and kv_b is folded into
    the query/output projections (DeepSeek-V2 matrix absorption; ref
    capability: PaddleNLP deepseek_v2 fused MLA decode).

    ``algo`` applies to every quantized leaf: 'weight_only_int4' packs
    the attention projections (kv_b reads whole through
    ops.quant.int4_dequantize; the rest through _mm_w's split
    contraction) AND the FFN/expert stacks — 3-D packed stacks read
    whole through _dq's plane-interleave dequant (density win: the
    stored stack is quarter-width)."""
    inner = model.model
    cfg = model.config
    layers = []
    moe_static = []
    for lyr in inner.layers:
        a = lyr.self_attn
        d = dict(
            ln1=lyr.input_layernorm.weight._data,
            wkva=a.kv_a_proj_with_mqa.weight._data,
            gkv=a.kv_a_layernorm.weight._data,
            wkvb=a.kv_b_proj.weight._data,
            wo=a.o_proj.weight._data,
            ln2=lyr.post_attention_layernorm.weight._data)
        if cfg.q_lora_rank:
            d["wqa"] = a.q_a_proj.weight._data
            d["gq"] = a.q_a_layernorm.weight._data
            d["wqb"] = a.q_b_proj.weight._data
        else:
            d["wq"] = a.q_proj.weight._data
        for k in ("wkva", "wkvb", "wo", "wqa", "wqb", "wq"):
            if k in d:
                _q8(d, k, weight_only_int8, algo)
        mlp_w, mlp_st = _mlp_params(lyr, weight_only_int8, algo)
        d.update(mlp_w)
        layers.append(d)
        moe_static.append(mlp_st)
    head = model.lm_head.weight._data if model.lm_head is not None else None
    p = dict(cfg=cfg, family="mla",
             embed=inner.embed_tokens.weight._data,
             layers=layers, norm=inner.norm.weight._data, head=head,
             cos=inner.rope_cos._data, sin=inner.rope_sin._data,
             moe_static=tuple(moe_static))
    if weight_only_int8 and head is not None:
        _q8(p, "head", True, algo)
        p["head"] = None
    return p


def _decode_params(model, weight_only_int8: bool = False,
                   weight_only_quant=None):
    """Family dispatch for the cached/compiled decode paths. int4 covers
    the llama, MoE and MLA families end-to-end: 2-D projections contract
    through _mm_w's even/odd split (or read whole through
    int4_dequantize — the MLA kv_b), and the 3-D MoE expert stacks pack
    per expert and read back through _dq's plane-interleave. The GPT
    family stays fp (its fused-qkv + bias layout is not wired through
    the quant matmul helper)."""
    algo, enabled = _woq_algo(weight_only_int8, weight_only_quant)
    if getattr(model, "gpt", None) is not None:
        if enabled:
            raise NotImplementedError(
                "weight_only_int8 decode covers the llama/MoE/MLA "
                "families; the GPT family is fp (its fused-qkv + bias "
                "layout is not wired through the quant matmul helper)")
        return _gpt_decode_params(model)
    inner = getattr(model, "model", None)
    if inner is not None:
        from .models.deepseek import DeepSeekV2Model
        from .models.moe_llm import MoEModel
        if isinstance(inner, DeepSeekV2Model):
            return _mla_decode_params(model, enabled, algo)
        if isinstance(inner, MoEModel):
            return _moe_decode_params(model, enabled, algo)
    return _llama_decode_params(model, weight_only_int8,
                                weight_only_quant)


def _llama_weights(p):
    """The traced-argument slice of _llama_decode_params: weights enter
    jit as ARGUMENTS, never as closures — a closed-over device array is
    embedded in the lowered module as a literal constant, and at 8B-shard
    scale (~0.5 GB) that makes XLA chew through the weights at compile
    time (~5 s/MB measured on the axon remote-compile path)."""
    return {k: v for k, v in p.items()
            if k not in ("cfg", "family", "moe_static")}


def _dq(d, key, dtype):
    """Read an optionally-quantized weight entry WHOLE (for consumers
    that reshape/slice it, e.g. the MLA kv_b or 3-D expert stacks, where
    _mm_w's fused matmul shape doesn't apply): int8 layouts dequantize
    in VMEM — the HBM read stays int8 and XLA fuses the scale multiply
    into the consuming einsum. 3-D stacks carry per-(expert, out-channel)
    scales [E, N]. 2-D int4 (_q4) entries unpack through the
    ops.quant.int4_dequantize Pallas kernel (the HBM read stays packed;
    the MLA absorbed kv_b rides this); 3-D packed stacks [E, K/2, N]
    interleave their sign-extended nibble planes back to source-row
    order (the same row order weight_dequantize writes) and scale per
    (expert, out-channel) — int4's recorded win here is DENSITY (the
    stored stack is quarter-width), not speed: the per-expert einsum
    consumers materialize the planes either way."""
    if key + "_q4" in d:
        q4, s = d[key + "_q4"], d[key + "_s"]
        if q4.ndim == 3:
            from .ops.quant import int4_planes
            lo, hi = int4_planes(q4)                    # [E, K/2, N]
            E, K2, N = q4.shape
            w = jnp.stack([lo, hi], axis=2).reshape(E, K2 * 2, N)
            return (w.astype(jnp.float32)
                    * s[:, None, :].astype(jnp.float32)).astype(dtype)
        from .ops.quant import int4_dequantize
        return int4_dequantize(q4, s).astype(dtype)
    if key + "_q" in d:
        q, s = d[key + "_q"], d[key + "_s"].astype(dtype)
        if q.ndim == 3:
            return q.astype(dtype) * s[:, None, :]
        return q.astype(dtype) * s
    return d[key]


def _int4_halves(q4, s):
    """Sign-extended nibble planes of a packed int4 weight, scaled:
    (lo, hi) each [K/2, N] — h @ W == h[..., 0::2] @ lo + h[..., 1::2]
    @ hi. Pure elementwise on the packed bytes, so XLA fuses the unpack
    into the dot operand loads (the same fusion that makes int8
    weight-only decode win); nothing bf16-sized ever hits HBM."""
    from .ops.quant import int4_planes
    lo, hi = int4_planes(q4)
    return lo.astype(s.dtype) * s, hi.astype(s.dtype) * s


def _mm_w(h, L, key):
    """Quant-aware matmul against a stored weight: weight-only int8
    layouts hold (key_q int8, key_s per-channel f32) and dequantize in
    VMEM right before the matmul (the HBM read is int8 — half the bf16
    bytes that bound decode); fp layouts hold the key directly. The ONE
    place both layouts' matmul goes through. Packed-int4 layouts
    (key_q4) contract even/odd input rows against the nibble planes so
    the unpack fuses into the dot operand loads (_int4_halves)."""
    if key + "_q4" in L:
        # in-kernel unpack for ANY N: packed int4 is the only weight HBM
        # traffic (XLA cannot fuse the shift chain into the MXU feed, so
        # a host-side plane split materializes bf16 planes and runs at
        # bf16 speed — measured r5). Non-128-aligned N (the vocab-16032
        # head) is zero-padded inside the kernel launch and sliced back.
        from .ops.quant import weight_only_linear
        return weight_only_linear(h, L[key + "_q4"], L[key + "_s"],
                                  algo="weight_only_int4")
    return h @ _dq(L, key, h.dtype)


def _ffn_apply(L, h2, st=None):
    """Per-layer FFN on [B, S, H]: dense SwiGLU (fp or weight-only int8)
    or routed-MoE (dropless per-token top-k — numerics match
    MoELayer._dropless exactly so the cached path exact-matches a
    moe_dropless buffer model). ``st`` holds the layer's STATIC routing
    knobs (top_k, renorm) from _mlp_params."""
    if "moe" not in L:
        return _mm_w(jax.nn.silu(_mm_w(h2, L, "wg"))
                     * _mm_w(h2, L, "wu"), L, "wd")
    mo = L["moe"]
    B, S, H = h2.shape
    T = B * S
    xt = h2.reshape(T, H)
    gates = jax.nn.softmax(
        xt.astype(jnp.float32) @ mo["gate"].astype(jnp.float32), axis=-1)
    from .incubate.moe import dense_expert_ffn, dropless_expert_ffn
    # decode steps (tiny T): every-expert dense compute beats the
    # sort+grouped-GEMM path (128-row tile padding) and is bitwise-equal
    ffn = dense_expert_ffn if T <= 32 else dropless_expert_ffn
    dt = h2.dtype
    y, _ = ffn(xt, gates, _dq(mo, "wge", dt),
               _dq(mo, "wup", dt), _dq(mo, "wdn", dt),
               top_k=st["top_k"], renormalize=st["renorm"],
               activation="swiglu")
    y = y.reshape(B, S, H).astype(h2.dtype)
    if "shared" in mo:
        sh = mo["shared"]
        s = jax.nn.silu(h2 @ _dq(sh, "sg", dt)) * (h2 @ _dq(sh, "su", dt))
        y = y + s @ _dq(sh, "sd", dt)
    return y


def _llama_cached_step_body(cfg, max_len: int, moe_static=None):
    """Un-jitted (weights, ids_step, caches, start_pos) ->
    (last_logits, caches) body — jitted per-call-width by
    _make_cached_step for the host-loop path, traced inside one
    scan by generate_compiled."""
    Hh, KV, D = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    eps = cfg.rms_norm_eps
    from .models.llama import apply_rope
    from .flags import flag, flags_guard
    # prefill routes through sdpa, whose kernel choice reads
    # FLAGS_flash_impl at trace time — pin it at construction so the
    # program matches _DECODE_LOOP_CACHE's key (same lazy-trace hazard
    # as the mla impl flag, review r5)
    flash_impl = flag("FLAGS_flash_impl")

    def rms(h, w):
        var = jnp.mean(jnp.square(h.astype(jnp.float32)), -1, keepdims=True)
        return (h * jax.lax.rsqrt(var + eps).astype(h.dtype)) * w

    def step(w, ids, caches, start):
        B, S = ids.shape
        x = w["embed"][ids]
        cos = jax.lax.dynamic_slice_in_dim(w["cos"], start, S, 0)
        sin = jax.lax.dynamic_slice_in_dim(w["sin"], start, S, 0)
        new_caches = []
        pos_k = jnp.arange(max_len)
        q_pos = start + jnp.arange(S)
        # key j visible to query i iff j <= start + i
        vis = pos_k[None, :] <= q_pos[:, None]            # [S, max_len]
        sts = moe_static or (None,) * len(w["layers"])
        for L, (ck, cv), st in zip(w["layers"], caches, sts):
            h = rms(x, L["ln1"])
            q, k, v = (_mm_w(h, L, "wq"), _mm_w(h, L, "wk"),
                       _mm_w(h, L, "wv"))
            if "bq" in L:                      # Qwen2 qkv biases
                q, k, v = q + L["bq"], k + L["bk"], v + L["bv"]
            q = q.reshape(B, S, Hh, D)
            k = k.reshape(B, S, KV, D)
            v = v.reshape(B, S, KV, D)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            ck = jax.lax.dynamic_update_slice(ck, k, (0, start, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, start, 0, 0))
            new_caches.append((ck, cv))
            rep = Hh // KV
            if S > 1 and isinstance(start, int) and start == 0:
                # prefill-from-zero: the cache holds nothing but this
                # window, so attend causally over the fresh k/v through
                # the flash route — the dense path below materializes
                # [*, S, max_len] f32 scores, which both OOMs long
                # contexts and wastes the (max_len - S) masked columns
                # (same routing as the buffer-model forward)
                from .ops.flash_attention import sdpa_prefill
                kr = jnp.repeat(k, rep, 2) if rep > 1 else k
                vr = jnp.repeat(v, rep, 2) if rep > 1 else v
                # trace-time pin of the kernel route for this compiled
                # step; re-applied on every retrace by construction.
                # sdpa_prefill pads non-128-multiple prompts through the
                # segment-id flash kernel instead of the dense fallback.
                with flags_guard(flash_impl=flash_impl):  # paddlelint: disable=PT005
                    o = sdpa_prefill(q, kr, vr,
                                     causal=True).reshape(B, S, Hh * D)
            elif rep > 1:
                # GQA WITHOUT materializing jnp.repeat of the cache: the
                # repeat wrote+read rep x the KV bytes per step — at the
                # MoE serving shape (16q/4kv, 8 layers) that was ~0.8 GB
                # of pure overhead against 1.5 GB of weights, the bulk of
                # the missing moe_decode roofline (VERDICT r4 item 2).
                # Group q as [B,S,KV,rep,D] and batch the dot over the kv
                # head so each cache byte is read exactly once.
                qg = q.reshape(B, S, KV, rep, D)
                scores = jnp.einsum("bsgrd,btgd->bgrst", qg, ck) \
                    * (D ** -0.5)
                scores = jnp.where(vis[None, None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
                o = jnp.einsum("bgrst,btgd->bsgrd", aw, cv).reshape(
                    B, S, Hh * D)
            else:
                scores = jnp.einsum("bshd,bthd->bhst", q, ck) * (D ** -0.5)
                scores = jnp.where(vis[None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
                o = jnp.einsum("bhst,bthd->bshd", aw, cv).reshape(
                    B, S, Hh * D)
            x = x + _mm_w(o, L, "wo")
            h2 = rms(x, L["ln2"])
            x = x + _ffn_apply(L, h2, st)
        x = rms(x, w["norm"])
        last = x[:, -1]
        if "head_q" in w or "head_q4" in w:
            logits = _mm_w(last, w, "head")
        else:
            logits = last @ (w["head"] if w["head"] is not None
                             else w["embed"].T)
        return logits, new_caches

    return step


def _gpt_cached_step_body(cfg, max_len: int):
    """GPT analog of _llama_cached_step_body: learned positions, LN with
    bias, fused qkv, GELU MLP; MHA cache (KV heads == q heads)."""
    nh, hd = cfg.num_attention_heads, cfg.head_dim
    eps = cfg.layer_norm_eps
    from .flags import flag, flags_guard
    flash_impl = flag("FLAGS_flash_impl")   # see _llama_cached_step_body

    def ln(h, wt, b):
        h32 = h.astype(jnp.float32)
        mu = jnp.mean(h32, -1, keepdims=True)
        var = jnp.var(h32, -1, keepdims=True)
        return (((h32 - mu) * jax.lax.rsqrt(var + eps))
                .astype(h.dtype) * wt + b)

    def step(w, ids, caches, start):
        B, S = ids.shape
        x = w["embed"][ids] + jax.lax.dynamic_slice_in_dim(
            w["pos"], start, S, 0)[None]
        pos_k = jnp.arange(max_len)
        q_pos = start + jnp.arange(S)
        vis = pos_k[None, :] <= q_pos[:, None]            # [S, max_len]
        new_caches = []
        for L, (ck, cv) in zip(w["layers"], caches):
            h = ln(x, L["ln1w"], L["ln1b"])
            qkv = h @ L["wqkv"] + L["bqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, S, nh, hd)
            k = k.reshape(B, S, nh, hd)
            v = v.reshape(B, S, nh, hd)
            ck = jax.lax.dynamic_update_slice(ck, k, (0, start, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, start, 0, 0))
            new_caches.append((ck, cv))
            if S > 1 and isinstance(start, int) and start == 0:
                # flash prefill — see _llama_cached_step_body
                from .ops.flash_attention import sdpa_prefill
                # trace-time pin, re-applied on every retrace
                with flags_guard(flash_impl=flash_impl):  # paddlelint: disable=PT005
                    o = sdpa_prefill(q, k, v, causal=True).reshape(B, S, -1)
            else:
                scores = jnp.einsum("bshd,bthd->bhst", q, ck) \
                    * (hd ** -0.5)
                scores = jnp.where(vis[None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
                o = jnp.einsum("bhst,bthd->bshd", aw, cv).reshape(B, S, -1)
            x = x + (o @ L["wo"] + L["bo"])
            h2 = ln(x, L["ln2w"], L["ln2b"])
            x = x + (jax.nn.gelu(h2 @ L["wi"] + L["bi"],
                                 approximate=True) @ L["wf"] + L["bf"])
        x = ln(x, w["normw"], w["normb"])
        last = x[:, -1]
        logits = last @ (w["head"] if w["head"] is not None
                         else w["embed"].T)
        return logits, new_caches

    return step


def _mla_cached_step_body(cfg, max_len: int, moe_static=None):
    """DeepSeek-V2 MLA cached decode with matrix absorption: the cache per
    token is (normalized latent [r], rope key [dr]) — kv_lora_rank + dr
    floats instead of nh*(dn+dv). kv_b is folded into the score (q_nope @
    W_k absorbed onto the latent) and the output (attention over latents,
    W_v applied after). Ref: DeepSeek-V2 inference optimization; PaddleNLP
    deepseek_v2 decode (SURVEY §2.4)."""
    nh = cfg.num_attention_heads
    dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                  cfg.v_head_dim)
    r = cfg.kv_lora_rank
    eps = cfg.rms_norm_eps
    from .models.llama import apply_rope
    # the impl flag is pinned at BODY-CONSTRUCTION time: jax.jit traces
    # lazily at first call, and _DECODE_LOOP_CACHE keys on the flag as
    # read when the loop is built — a trace-time read could cache the
    # other impl's program under this key (review r5)
    from .flags import flag, flags_guard
    impl = flag("FLAGS_mla_decode_impl")
    flash_impl = flag("FLAGS_flash_impl")   # see _llama_cached_step_body

    def rms(h, w):
        var = jnp.mean(jnp.square(h.astype(jnp.float32)), -1, keepdims=True)
        return (h * jax.lax.rsqrt(var + eps).astype(h.dtype)) * w

    def step(w, ids, caches, start):
        B, S = ids.shape
        x = w["embed"][ids]
        cos = jax.lax.dynamic_slice_in_dim(w["cos"], start, S, 0)
        sin = jax.lax.dynamic_slice_in_dim(w["sin"], start, S, 0)
        pos_k = jnp.arange(max_len)
        q_pos = start + jnp.arange(S)
        vis = pos_k[None, :] <= q_pos[:, None]            # [S, max_len]
        scale = 1.0 / float(np.sqrt(dn + dr))
        use_fused = False
        if S == 1 and impl != "xla":
            from .ops import pallas_mla
            use_fused = (impl == "fused"
                         or pallas_mla.mla_kernel_eligible(nh, r, dr))
        new_caches = []
        sts = moe_static or (None,) * len(w["layers"])
        for L, (c_lat, c_pe), st in zip(w["layers"], caches, sts):
            h = rms(x, L["ln1"])
            if "wqa" in L or "wqa_q" in L or "wqa_q4" in L:
                q = _mm_w(rms(_mm_w(h, L, "wqa"), L["gq"]), L, "wqb")
            else:
                q = _mm_w(h, L, "wq")
            q = q.reshape(B, S, nh, dn + dr)
            q_nope, q_pe = q[..., :dn], q[..., dn:]
            q_pe = apply_rope(q_pe, cos, sin)

            kv_a = _mm_w(h, L, "wkva")                    # [B, S, r+dr]
            lat = rms(kv_a[..., :r], L["gkv"])            # normalized latent
            k_pe = apply_rope(kv_a[..., r:][:, :, None, :], cos, sin)[:, :, 0]

            c_lat = jax.lax.dynamic_update_slice(c_lat, lat, (0, start, 0))
            c_pe = jax.lax.dynamic_update_slice(c_pe, k_pe, (0, start, 0))
            new_caches.append((c_lat, c_pe))

            if S > 1 and isinstance(start, int) and start == 0:
                # prefill-from-zero in the NON-absorbed form (k/v heads
                # materialized once — reassociation of the same math) so
                # the flash route applies; the absorbed dense path below
                # materializes [B,nh,S,max_len] f32 scores, which OOMs
                # long-context prefill (matches models/deepseek.py
                # forward, incl. the padded-head route for dv != dn+dr)
                from .ops.flash_attention import sdpa_padded_heads
                kv = (lat @ _dq(L, "wkvb", x.dtype)).reshape(
                    B, S, nh, dn + dv)
                k_h = jnp.concatenate(
                    [kv[..., :dn],
                     jnp.broadcast_to(k_pe[:, :, None, :], (B, S, nh, dr))],
                    -1)
                q_h = jnp.concatenate([q_nope, q_pe], -1)
                # trace-time pin, re-applied on every retrace
                with flags_guard(flash_impl=flash_impl):  # paddlelint: disable=PT005
                    o_v = sdpa_padded_heads(q_h, k_h, kv[..., dn:],
                                            causal=True, scale=scale)
                x = x + _mm_w(o_v.reshape(B, S, nh * dv), L, "wo")
                h2 = rms(x, L["ln2"])
                x = x + _ffn_apply(L, h2, st)
                continue
            wkb = _dq(L, "wkvb", x.dtype).reshape(r, nh, dn + dv)
            w_k, w_v = wkb[..., :dn], wkb[..., dn:]
            # absorb W_k onto the query: score = q_eff . latent + q_pe . k_pe
            q_eff = jnp.einsum("bsnd,rnd->bsnr", q_nope, w_k)
            if use_fused:
                # single-read fused decode: each latent-cache byte feeds
                # the score AND the output from one VMEM tile (the XLA
                # einsum pair below reads c_lat twice across the softmax
                # barrier — the measured 0.09 roofline residual)
                lens = jnp.full((B,), start + 1, jnp.int32)
                o_lat = pallas_mla.mla_decode_attention(
                    q_eff[:, 0], q_pe[:, 0], c_lat, c_pe, lens,
                    scale=scale)[:, None]
            else:
                scores = (jnp.einsum("bsnr,btr->bnst", q_eff, c_lat)
                          + jnp.einsum("bsnd,btd->bnst", q_pe, c_pe)) * scale
                scores = jnp.where(vis[None, None],
                                   scores.astype(jnp.float32), -1e30)
                aw = jax.nn.softmax(scores, axis=-1).astype(c_lat.dtype)
                o_lat = jnp.einsum("bnst,btr->bsnr", aw, c_lat)
            o = jnp.einsum("bsnr,rnv->bsnv", o_lat, w_v)
            x = x + _mm_w(o.reshape(B, S, nh * dv), L, "wo")
            h2 = rms(x, L["ln2"])
            x = x + _ffn_apply(L, h2, st)
        x = rms(x, w["norm"])
        last = x[:, -1]
        if "head_q" in w or "head_q4" in w:
            logits = _mm_w(last, w, "head")
        else:
            logits = last @ (w["head"] if w["head"] is not None
                             else w["embed"].T)
        return logits, new_caches

    return step


def _cached_step_body(p, max_len: int):
    if p["family"] == "gpt":
        return _gpt_cached_step_body(p["cfg"], max_len)
    if p["family"] == "mla":
        return _mla_cached_step_body(p["cfg"], max_len,
                                     p.get("moe_static"))
    return _llama_cached_step_body(p["cfg"], max_len, p.get("moe_static"))


def _init_caches(p, B: int, total: int):
    """Family-shaped zero KV caches for one sequence batch."""
    cfg = p["cfg"]
    dt = p["embed"].dtype
    n_layers = len(p["layers"])
    if p["family"] == "gpt":
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        return [(jnp.zeros((B, total, nh, hd), dt),
                 jnp.zeros((B, total, nh, hd), dt))
                for _ in range(n_layers)]
    if p["family"] == "mla":
        return [(jnp.zeros((B, total, cfg.kv_lora_rank), dt),
                 jnp.zeros((B, total, cfg.qk_rope_head_dim), dt))
                for _ in range(n_layers)]
    KV, D = cfg.num_key_value_heads, cfg.head_dim
    return [(jnp.zeros((B, total, KV, D), dt),
             jnp.zeros((B, total, KV, D), dt))
            for _ in range(n_layers)]


def _make_cached_step(p, max_len: int):
    """Jitted cached step: one compile per distinct step width (prefill
    S0, decode 1). Weights ride as jit arguments (see _llama_weights).
    A multi-token call at start=0 pins start STATICALLY so the body can
    take the flash prefill route (O(S) memory) instead of the dense
    [S, max_len] score path; decode keeps start traced (no retrace per
    position)."""
    w = _llama_weights(p)
    body = _cached_step_body(p, max_len)
    jit_dec = jax.jit(body)
    jit_pre = jax.jit(lambda w, ids, caches: body(w, ids, caches, 0))

    def call(ids, caches, start):
        if ids.shape[1] > 1 and isinstance(start, int) and start == 0:
            return jit_pre(w, ids, caches)
        return jit_dec(w, ids, caches, start)
    return call


def generate_cached(model, input_ids, max_new_tokens: int = 20,
                    decode_strategy: str = "sampling",
                    top_k: Optional[int] = None, top_p: Optional[float] = None,
                    temperature: float = 1.0,
                    eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                    weight_only_int8: bool = False,
                    weight_only_quant=None,
                    deadline_s: Optional[float] = None):
    """KV-cache generation for LlamaForCausalLM-family models: prefill once
    over the prompt, then O(1) work per new token (the compiled-decode
    analog of the reference's masked_multihead_attention loop).
    ``deadline_s``: per-request wall-clock budget — see generate().

    Numerics note: matches the buffer path exactly under f32 matmul
    precision; under the TPU bf16 default the two paths may argmax-flip
    near-tied logits (same situation as the reference's fp16 decode
    kernels vs the fp32 training graph). MoE models: decode always routes
    DROPLESS (serving never discards tokens), so exactness vs generate()
    holds for moe_dropless=True models; capacity-mode models get a
    warning (drops are a training-time regularizer).
    """
    if decode_strategy not in ("greedy_search", "sampling"):
        raise ValueError(f"decode_strategy {decode_strategy!r}: expected "
                         "'greedy_search' or 'sampling'")
    p = _decode_params(model, weight_only_int8, weight_only_quant)
    cfg = p["cfg"]
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, S0 = ids.shape
    total = S0 + max_new_tokens
    if total > cfg.max_position_embeddings:
        raise ValueError(f"{total} tokens exceed max_position_embeddings")
    caches = _init_caches(p, B, total)
    step = _make_cached_step(p, total)
    finished = jnp.zeros((B,), bool)
    out_tokens, out_scores = [], []
    dl = _res.Deadline(deadline_s) if deadline_s else None
    timed_out = False
    mx = _obs.enabled()
    if mx:
        _SRV_REQS.labels(path="cached").inc()
        _SRV_BATCH.observe(B)
        _SRV_PREFILL_TOK.inc(B * S0)
    import time as _time
    with ag.no_grad():
        t0 = _time.perf_counter() if mx else 0.0
        logits, caches = step(ids, caches, 0)          # prefill
        if mx:
            _SRV_PREFILL_S.labels(path="cached").observe(
                _time.perf_counter() - t0)
            t0 = _time.perf_counter()
        pos = S0
        while pos < total:
            tok = _sample_token(logits, decode_strategy, top_k, top_p,
                                temperature)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            score = jnp.take_along_axis(logp, tok[:, None], -1)[:, 0]
            if eos_token_id is not None:
                tok = jnp.where(finished, pad_token_id, tok)
                score = jnp.where(finished, 0.0, score)
                finished = finished | (tok == eos_token_id)
            out_tokens.append(tok)
            out_scores.append(score)
            if pos == total - 1 or (eos_token_id is not None
                                    and bool(jnp.all(finished))):
                break
            if dl is not None and dl.expired():
                timed_out = True
                break
            logits, caches = step(tok[:, None], caches, pos)
            pos += 1
    if mx:
        _SRV_DECODE_S.labels(path="cached").observe(
            _time.perf_counter() - t0)
        _SRV_DECODE_TOK.inc(B * len(out_tokens))
    partial = _finalize_tokens(out_tokens, out_scores, B, max_new_tokens,
                               pad_token_id)
    if timed_out:
        return _timeout_result("generate_cached", dl, len(out_tokens),
                               partial)
    return partial


def _make_decode_loop(p, S0: int, max_new_tokens: int,
                      decode_strategy: str, top_k, top_p,
                      temperature: float, eos_token_id, pad_token_id):
    """Compile prefill + the ENTIRE decode loop into one XLA program:
    a lax.scan over max_new_tokens cached decode steps. No host round-trip
    per token — on a tunneled/remote TPU the host-loop path pays
    dispatch+transfer latency every token; this is the serving-grade path
    (the XLA analog of the reference's fused decode loop over
    masked_multihead_attention, paddle/phi/kernels/fusion/gpu/
    masked_multihead_attention.cu). Fixed trip count (no early-eos exit)
    keeps the loop compiled; finished rows emit pad_token_id."""
    total = S0 + max_new_tokens
    cfg = p["cfg"]
    body = _cached_step_body(p, total)

    def run(w, ids, key):
        B = ids.shape[0]
        caches = _init_caches(p, B, total)
        logits, caches = body(w, ids, caches, 0)         # prefill
        finished = jnp.zeros((B,), bool)

        def scan_step(carry, i):
            logits, caches, finished, key = carry
            if decode_strategy == "greedy_search" or (
                    temperature is not None and temperature <= 0.0):
                tok = jnp.argmax(logits, -1).astype(jnp.int32)
            else:
                key, sub = jax.random.split(key)
                tok = jax.random.categorical(
                    sub, _filter_logits(logits, top_k, top_p, temperature),
                    -1).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            score = jnp.take_along_axis(logp, tok[:, None], -1)[:, 0]
            if eos_token_id is not None:
                tok = jnp.where(finished, pad_token_id, tok)
                score = jnp.where(finished, 0.0, score)
                finished = finished | (tok == eos_token_id)
            logits, caches = body(w, tok[:, None], caches, S0 + i)
            return (logits, caches, finished, key), (tok, score)

        (_, _, _, _), (toks, scores) = jax.lax.scan(
            scan_step, (logits, caches, finished, key),
            jnp.arange(max_new_tokens))
        return toks.T, scores.T                          # [B, max_new]

    cfg_key = (p["family"], cfg.num_hidden_layers, cfg.hidden_size,
               cfg.num_attention_heads,
               getattr(cfg, "num_key_value_heads", 0),
               getattr(cfg, "head_dim", 0), cfg.vocab_size,
               getattr(cfg, "intermediate_size", 0),
               getattr(cfg, "rms_norm_eps", 0.0),
               getattr(cfg, "layer_norm_eps", 0.0),  # eps bakes into the body
               # MoE / MLA program-shaping knobs
               getattr(cfg, "num_experts", 0), getattr(cfg, "top_k", 0),
               getattr(cfg, "moe_intermediate_size", 0),
               getattr(cfg, "shared_expert_intermediate_size", 0),
               getattr(cfg, "first_k_dense_replace", 0),
               getattr(cfg, "kv_lora_rank", 0),
               getattr(cfg, "q_lora_rank", 0) or 0,
               getattr(cfg, "qk_nope_head_dim", 0),
               getattr(cfg, "qk_rope_head_dim", 0),
               getattr(cfg, "v_head_dim", 0))
    from .flags import flag
    prog_key = (cfg_key, S0, max_new_tokens, decode_strategy, top_k,
                top_p, temperature, eos_token_id, pad_token_id,
                # trace-time flags that shape the step body: a flipped
                # impl flag must MISS, not return the other impl's
                # compiled program (gmm routes the MoE prefill experts)
                flag("FLAGS_mla_decode_impl"), flag("FLAGS_gmm_impl"),
                flag("FLAGS_flash_impl"))
    jitted = _DECODE_LOOP_CACHE.get(prog_key)
    if _obs.enabled():
        _JIT_CACHE.labels(cache="decode_loop",
                          event="hit" if jitted is not None
                          else "miss").inc()
    if jitted is None:
        if len(_DECODE_LOOP_CACHE) >= 32:
            _DECODE_LOOP_CACHE.pop(next(iter(_DECODE_LOOP_CACHE)))
            if _obs.enabled():
                _JIT_CACHE.labels(cache="decode_loop",
                                  event="evict").inc()
        jitted = jax.jit(run)
        _DECODE_LOOP_CACHE[prog_key] = jitted
    weights = _llama_weights(p)
    return lambda ids, key: jitted(weights, ids, key)


# compiled decode loops keyed on everything that shapes the program: the
# weights ride as ARGUMENTS, so one executable serves every same-config
# model and every generate_compiled call with the same lengths/strategy —
# and the weights are re-read per call (no stale-closure capture after a
# training step updates the model)
_DECODE_LOOP_CACHE: dict = {}


def generate_compiled(model, input_ids, max_new_tokens: int = 20,
                      decode_strategy: str = "sampling",
                      top_k: Optional[int] = None,
                      top_p: Optional[float] = None, temperature: float = 1.0,
                      eos_token_id: Optional[int] = None,
                      pad_token_id: int = 0,
                      weight_only_int8: bool = False,
                      weight_only_quant=None,
                      deadline_s: Optional[float] = None):
    """KV-cache generation with the whole decode loop compiled (see
    _make_decode_loop). Same contract (and defaults) as
    generate_cached; sampling draws from the framework RNG stream once
    per call (the per-step keys are split on-device).

    ``deadline_s``: the scan-fused loop is one atomic XLA program, so
    the deadline is enforced at the dispatch boundaries — an expired
    budget before launch short-circuits to a TimeoutResult (partial
    None), and a launch that finishes past the budget returns a
    TimeoutResult whose .partial holds the full output."""
    if decode_strategy not in ("greedy_search", "sampling"):
        raise ValueError(f"decode_strategy {decode_strategy!r}: expected "
                         "'greedy_search' or 'sampling'")
    p = _decode_params(model, weight_only_int8, weight_only_quant)
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, S0 = ids.shape
    if S0 + max_new_tokens > p["cfg"].max_position_embeddings:
        raise ValueError(f"{S0 + max_new_tokens} tokens exceed "
                         "max_position_embeddings")
    dl = _res.Deadline(deadline_s) if deadline_s else None
    if dl is not None and dl.expired():
        return _timeout_result("generate_compiled", dl, 0, None)
    run = _make_decode_loop(p, S0, max_new_tokens, decode_strategy,
                            top_k, top_p, temperature, eos_token_id,
                            pad_token_id)
    mx = _obs.enabled()
    if mx:
        _SRV_REQS.labels(path="compiled").inc()
        _SRV_BATCH.observe(B)
        _SRV_PREFILL_TOK.inc(B * S0)
    import time as _time
    t0 = _time.perf_counter() if mx else 0.0
    with ag.no_grad():
        gen, sc = run(ids, next_key())
    if mx:
        # one XLA program fuses prefill + decode; the whole call is
        # charged to the decode section
        _SRV_DECODE_S.labels(path="compiled").observe(
            _time.perf_counter() - t0)
        _SRV_DECODE_TOK.inc(B * max_new_tokens)
    out = (Tensor(gen), Tensor(sc))
    if dl is not None and dl.expired():
        return _timeout_result("generate_compiled", dl, max_new_tokens,
                               out)
    return out


# ---------------------------------------------------------------------------
# Beam search (ref: PaddleNLP GenerationMixin beam_search / group_beam_search,
# paddlenlp/generation/utils.py + BeamHypotheses in beam_utils) — with length
# penalty (score / len**length_penalty), repetition penalty (CTRL-style
# multiply/divide), and diverse groups (Hamming diversity: later groups pay
# diversity_rate per token already chosen this step by earlier groups).
# Fixed-shape: the model always sees [B*num_beams, S0+max_new_tokens].
# ---------------------------------------------------------------------------
def _repetition_penalize(logits, seen_tokens, penalty):
    """logits [R, V] (raw, pre-softmax); seen_tokens [R, T] int; CTRL
    penalty on the logits — seen tokens' negative logits are multiplied
    by `penalty`, positive ones divided — so the subsequent log_softmax
    still yields normalized log-probabilities (ref: paddlenlp
    RepetitionPenaltyLogitsProcessor.__call__)."""
    if penalty == 1.0:
        return logits
    R, V = logits.shape
    seen = jnp.zeros((R, V), bool).at[
        jnp.arange(R)[:, None], seen_tokens].set(True)
    penalized = jnp.where(logits < 0, logits * penalty, logits / penalty)
    return jnp.where(seen, penalized, logits)


def _beam_step(scores, finished, logp, num_beams, num_beam_groups,
               diversity_rate, pad_token_id, eos_token_id):
    """One beam-search selection. scores/finished [B, nb]; logp
    [B*nb, V] log-softmaxed. Returns (scores, tok, src_beam) [B, nb]."""
    B, nb = scores.shape
    V = logp.shape[-1]
    logp = logp.reshape(B, nb, V)
    # finished beams emit pad with frozen score
    frozen = jnp.full((V,), -jnp.inf).at[pad_token_id].set(0.0)
    logp = jnp.where(finished[..., None], frozen[None, None], logp)
    gs = nb // num_beam_groups
    parts = []
    chosen = jnp.zeros((B, V), jnp.float32)
    for g in range(num_beam_groups):
        lg = logp[:, g * gs:(g + 1) * gs]
        cand = scores[:, g * gs:(g + 1) * gs, None] + lg
        if g > 0 and diversity_rate:
            cand = cand - diversity_rate * chosen[:, None, :]
        top_s, top_i = jax.lax.top_k(cand.reshape(B, gs * V), gs)
        src = top_i // V + g * gs
        tok = (top_i % V).astype(jnp.int32)
        if num_beam_groups > 1:
            chosen = chosen.at[jnp.arange(B)[:, None], tok].add(1.0)
        parts.append((top_s, tok, src))
    new_scores = jnp.concatenate([p[0] for p in parts], 1)
    new_tok = jnp.concatenate([p[1] for p in parts], 1)
    new_src = jnp.concatenate([p[2] for p in parts], 1)
    return new_scores, new_tok, new_src


def _beam_engine(step_logits, reorder_state, ids, max_new_tokens,
                 num_beams, num_beam_groups, diversity_rate,
                 length_penalty, repetition_penalty, eos_token_id,
                 pad_token_id, num_return_sequences):
    """Shared beam loop. step_logits(t) -> [B*nb, V] logits at position
    t given current buffers; reorder_state(src_beam [B, nb], tok [B,nb],
    t) commits the beam permutation + chosen tokens."""
    B, S0 = ids.shape
    nb = num_beams
    if nb % num_beam_groups:
        raise ValueError(f"num_beams {nb} not divisible by "
                         f"num_beam_groups {num_beam_groups}")
    if num_return_sequences > nb:
        raise ValueError("num_return_sequences > num_beams")
    # beam 0 of each group starts live, the rest -inf (identical prompts
    # would otherwise fill the beam with duplicates)
    gs = nb // num_beam_groups
    init = np.full((B, nb), -1e9, np.float32)
    init[:, 0::gs] = 0.0
    scores = jnp.asarray(init)
    finished = jnp.zeros((B, nb), bool)
    toks = []  # committed tokens per step, [B, nb] AFTER reordering
    for t in range(S0 - 1, S0 + max_new_tokens - 1):
        logits = step_logits(t)
        logits = _repetition_penalize(
            logits.astype(jnp.float32),
            reorder_state.current_tokens(t), repetition_penalty)
        logp = jax.nn.log_softmax(logits, -1)
        scores, tok, src = _beam_step(scores, finished, logp, nb,
                                      num_beam_groups, diversity_rate,
                                      pad_token_id, eos_token_id)
        finished = jnp.take_along_axis(finished, src, 1)
        if eos_token_id is not None:
            finished = finished | (tok == eos_token_id)
        reorder_state.commit(src, tok, t)
        toks = [jnp.take_along_axis(x, src, 1) for x in toks]
        toks.append(tok)
        if eos_token_id is not None and bool(jnp.all(finished)):
            break
    gen = jnp.stack(toks, -1)                      # [B, nb, L]
    L = gen.shape[-1]
    if eos_token_id is not None:
        is_eos = gen == eos_token_id
        has = is_eos.any(-1)
        first = jnp.where(has, jnp.argmax(is_eos, -1) + 1, L)
    else:
        first = jnp.full(gen.shape[:2], L)
    lengths = first.astype(jnp.float32)
    final = scores / (lengths ** length_penalty) \
        if length_penalty != 0.0 else scores
    order = jnp.argsort(-final, axis=1)[:, :num_return_sequences]
    gen = jnp.take_along_axis(gen, order[..., None], 1)  # [B, nrs, L]
    best_sc = jnp.take_along_axis(final, order, 1)
    # mask everything after (and incl.) nothing — pad after eos
    pos = jnp.arange(L)[None, None, :]
    keep = pos < jnp.take_along_axis(first, order, 1)[..., None]
    gen = jnp.where(keep, gen, pad_token_id)
    if L < max_new_tokens:
        gen = jnp.concatenate(
            [gen, jnp.full(gen.shape[:2] + (max_new_tokens - L,),
                           pad_token_id, jnp.int32)], -1)
    gen = gen.reshape(B * num_return_sequences, max_new_tokens)
    return Tensor(gen), Tensor(best_sc.reshape(-1))


class _BufferBeamState:
    """Fixed-buffer model state for beam search: [B*nb, total] ids."""

    def __init__(self, model, ids, nb, max_new_tokens, pad_token_id):
        B, S0 = ids.shape
        self.B, self.nb, self.S0 = B, nb, S0
        total = S0 + max_new_tokens
        buf = jnp.concatenate(
            [ids, jnp.full((B, max_new_tokens), pad_token_id,
                           jnp.int32)], 1)
        self.buf = jnp.repeat(buf, nb, axis=0)     # [B*nb, total]
        self.model = model

    def logits_at(self, t):
        return _logits_fn(self.model, self.buf)[:, t]

    def current_tokens(self, t):
        return self.buf[:, :t + 1]  # pad tail excluded from penalties

    def commit(self, src, tok, t):
        B, nb = self.B, self.nb
        buf = self.buf.reshape(B, nb, -1)
        buf = jnp.take_along_axis(buf, src[..., None], 1)
        buf = buf.at[:, :, t + 1].set(tok)
        self.buf = buf.reshape(B * nb, -1)


def beam_search(model, input_ids, max_new_tokens: int = 20,
                num_beams: int = 4, num_beam_groups: int = 1,
                diversity_rate: float = 0.0, length_penalty: float = 0.0,
                repetition_penalty: float = 1.0,
                eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                num_return_sequences: int = 1):
    """ref: PaddleNLP GenerationMixin.beam_search / group_beam_search.
    Returns (generated_ids [B*num_return_sequences, max_new_tokens],
    scores [B*num_return_sequences]) — sequences ranked by
    sum-logprob / len**length_penalty; tokens after eos are pad."""
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    state = _BufferBeamState(model, ids, num_beams, max_new_tokens,
                             pad_token_id)
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        with ag.no_grad():
            return _beam_engine(state.logits_at, state, ids,
                                max_new_tokens, num_beams,
                                num_beam_groups, diversity_rate,
                                length_penalty, repetition_penalty,
                                eos_token_id, pad_token_id,
                                num_return_sequences)
    finally:
        if was_training and hasattr(model, "train"):
            model.train()


class _CachedBeamState:
    """KV-cache model state for beam search: caches gathered by the beam
    permutation every step (the reference's cache reorder on beam_idx)."""

    def __init__(self, model, ids, nb, max_new_tokens,
                 weight_only_int8=False, weight_only_quant=None):
        p = _decode_params(model, weight_only_int8, weight_only_quant)
        self.p = p
        cfg = p["cfg"]
        B, S0 = ids.shape
        self.B, self.nb, self.S0 = B, nb, S0
        total = S0 + max_new_tokens
        if total > cfg.max_position_embeddings:
            raise ValueError(
                f"{total} tokens exceed max_position_embeddings")
        self.caches = _init_caches(p, B * nb, total)
        self.step = _make_cached_step(p, total)
        self.buf = jnp.repeat(
            jnp.concatenate([ids, jnp.zeros((B, max_new_tokens),
                                            jnp.int32)], 1), nb, 0)
        self._logits = None
        self._pending = None  # (tok, t) decode deferred until needed

    def logits_at(self, t):
        # lazy: the engine may break on all-finished right after a
        # commit — deferring the decode forward here saves that call
        if self._logits is None:
            logits, self.caches = self.step(self.buf[:, :self.S0],
                                            self.caches, 0)
            self._logits = logits
        elif self._pending is not None:
            tok, tp = self._pending
            self._pending = None
            self._logits, self.caches = self.step(
                tok.reshape(-1, 1), self.caches, tp + 1)
        return self._logits

    def current_tokens(self, t):
        return self.buf[:, :t + 1]

    def commit(self, src, tok, t):
        B, nb = self.B, self.nb
        flat_src = (src + jnp.arange(B)[:, None] * nb).reshape(-1)
        self.caches = [(ck[flat_src], cv[flat_src])
                       for ck, cv in self.caches]
        buf = self.buf.reshape(B, nb, -1)
        buf = jnp.take_along_axis(buf, src[..., None], 1)
        buf = buf.at[:, :, t + 1].set(tok)
        self.buf = buf.reshape(B * nb, -1)
        self._pending = (tok, t)


def beam_search_cached(model, input_ids, max_new_tokens: int = 20,
                       num_beams: int = 4, num_beam_groups: int = 1,
                       diversity_rate: float = 0.0,
                       length_penalty: float = 0.0,
                       repetition_penalty: float = 1.0,
                       eos_token_id: Optional[int] = None,
                       pad_token_id: int = 0,
                       num_return_sequences: int = 1,
                       weight_only_int8: bool = False,
                       weight_only_quant=None):
    """KV-cache beam search for the Llama family (cache rows gathered by
    the beam permutation each step); same contract as beam_search."""
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    state = _CachedBeamState(model, ids, num_beams, max_new_tokens,
                             weight_only_int8, weight_only_quant)
    with ag.no_grad():
        return _beam_engine(state.logits_at, state, ids, max_new_tokens,
                            num_beams, num_beam_groups, diversity_rate,
                            length_penalty, repetition_penalty,
                            eos_token_id, pad_token_id,
                            num_return_sequences)


__all__ += ["generate_cached", "generate_compiled", "beam_search",
            "beam_search_cached"]
