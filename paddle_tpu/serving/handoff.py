"""Cross-replica KV-page handoff for disaggregated prefill/decode.

`KVPageHandoff` is the wire format between a prefill-role replica and a
decode-role replica (ROADMAP item 2, arXiv 2604.15464): everything a
decode replica needs to resume a request WITHOUT re-prefill —

  - request identity and sampling state (prompt, max_new, eos/pad,
    priority/tenant/deadline, emitted `tokens`, the `pending` token);
  - the KV payload: per-layer host copies of exactly the sequence's
    pages, gathered from the exporter's device pools in page-table
    order. n-gram spec-decode needs no extra state — its drafts are
    derived from prompt+tokens, which travel here;
  - a `release()` callback that drops the exporter's allocator pins.

The protocol is pin → export → import → unpin: `export_seq` pins every
page before the payload is read, so a preemption, queue expiry, or even
`free()` landing mid-handoff cannot recycle a page under the copy, and
trie-pinned shared-prefix pages keep their refcounts across the window.
The payload itself is physical-page-id agnostic: the importer writes it
to whatever pages its own allocator hands out and only the page TABLE
differs, so greedy decode on the importer is bit-identical to decode on
the exporter (the disaggregated exactness contract).

In-process (tier-1 / CPU) the "transfer" is a host array copy; on a real
fleet the same payload rides the DCN tier `build_hybrid_mesh` now
models (`dcn_dp`/`dcn_pp`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from .. import observability as _obs

__all__ = ["KVPageHandoff"]

HANDOFFS = _obs.registry().counter(
    "serving.handoff.requests",
    "KV-page handoffs by direction", labels=("direction",))
HANDOFF_PAGES = _obs.registry().counter(
    "serving.handoff.pages", "KV pages moved by handoffs")
HANDOFF_BYTES = _obs.registry().counter(
    "serving.handoff.bytes", "KV block payload bytes moved by handoffs")


@dataclass
class KVPageHandoff:
    """One request's portable decode state (see module docstring)."""

    request_id: object
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    pad_token_id: int
    priority: int
    tenant: Optional[str]
    deadline_s: Optional[float]
    tokens: List[int]            # emitted so far; pending == tokens[-1]
    pending: int                 # staged for the next decode step
    shared_tokens: int           # prefill skipped at original admission
    kv_length: int               # tokens materialized in `blocks`
    blocks: list                 # per-layer page payloads (np arrays)
    page_size: int
    family: str
    source: str                  # exporting replica name
    #: portable trace context (request id, span lineage, events so far)
    #: from TraceRecorder.export_context — the importer adopts it so the
    #: request keeps ONE logical timeline across replicas
    trace: Optional[dict] = None
    _release: Optional[Callable[[], int]] = field(default=None,
                                                  repr=False)
    _released: bool = field(default=False, repr=False)

    @property
    def n_pages(self) -> int:
        return -(-self.kv_length // self.page_size)

    @property
    def payload_bytes(self) -> int:
        total = 0
        for blk in self.blocks:
            for a in (blk if isinstance(blk, tuple) else (blk,)):
                total += a.nbytes
        return total

    def release(self) -> int:
        """Drop the exporter's page pins (idempotent). Call once the
        payload has been imported — or when abandoning the handoff."""
        if self._released or self._release is None:
            return 0
        self._released = True
        return self._release()
