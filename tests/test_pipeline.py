"""Compiled pipeline schedules (SURVEY §2.3 P6): GPipe-style and
interleaved-VPP runs on the simulated 8-device mesh must reproduce the
sequential (no-pipeline) forward exactly, and train end-to-end under grad.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.distributed.mesh import build_hybrid_mesh
from paddle_tpu.distributed.pipeline import (
    spmd_pipeline, spmd_pipeline_interleaved, stack_layer_params,
    stack_layer_params_interleaved, _vpp_injection_schedule)

L, H = 8, 16
M, MB = 4, 2  # microbatches, per-microbatch batch


def _layers(rng):
    return [{"w": jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.3),
             "b": jnp.asarray(rng.randn(H).astype(np.float32) * 0.1)}
            for _ in range(L)]


def _stage_fn(params_slice, x, scale):
    def body(h, lp):
        return jnp.tanh(h @ lp["w"] + lp["b"]) * scale, None
    h, _ = jax.lax.scan(body, x, params_slice)
    return h


def _seq_reference(layers, mbs, scale):
    outs = []
    for i in range(mbs.shape[0]):
        h = mbs[i]
        for lp in layers:
            h = jnp.tanh(h @ lp["w"] + lp["b"]) * scale
        outs.append(h)
    return jnp.stack(outs)


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    layers = _layers(rng)
    mbs = jnp.asarray(rng.randn(M, MB, H).astype(np.float32))
    scale = jnp.asarray(1.1, jnp.float32)
    return layers, mbs, scale, _seq_reference(layers, mbs, scale)


def test_gpipe_matches_sequential(data):
    layers, mbs, scale, ref = data
    mesh = build_hybrid_mesh(pp_degree=4, dp_degree=2)
    stacked = stack_layer_params(layers, 4)
    out = spmd_pipeline(_stage_fn, stacked, mbs, mesh, M,
                        extra_args=(scale,))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("v", [2, 4])
def test_vpp_matches_sequential(data, v):
    layers, mbs, scale, ref = data
    mesh = build_hybrid_mesh(pp_degree=2, dp_degree=4)
    stacked = stack_layer_params_interleaved(layers, 2, v)
    out = spmd_pipeline_interleaved(_stage_fn, stacked, mbs, mesh, M, v,
                                    extra_args=(scale,))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_vpp_interleaved_layout():
    """Round-robin assignment: device s chunk c holds layers
    (c*S + s)*per_chunk + i — the reference's interleave layout."""
    layers = [{"w": jnp.full((1,), float(i))} for i in range(L)]
    S, v = 2, 2
    st = stack_layer_params_interleaved(layers, S, v)["w"]
    assert st.shape == (S, v, L // (S * v), 1)
    # virtual stage j = chunk*S + stage; layers are split contiguously
    # across the V virtual stages in order
    per_chunk = L // (S * v)
    for s in range(S):
        for c in range(v):
            j = c * S + s
            expect = [float(j * per_chunk + i) for i in range(per_chunk)]
            got = [float(x) for x in np.asarray(st[s, c, :, 0])]
            assert got == expect, (s, c, got, expect)


def test_vpp_schedule_collision_free():
    for (S, v, M_) in ((2, 2, 4), (4, 2, 8), (2, 4, 5)):
        inject, total = _vpp_injection_schedule(S, v, M_)
        entries = [t for t, m in enumerate(inject) if m >= 0]
        assert len(entries) == M_
        # device-0 occupancy: fresh injections and k*S returns never collide
        busy = set()
        for e in entries:
            for k in range(1, v):
                assert e + k * S not in entries, (S, v, M_, e)
                busy.add(e + k * S)
        assert total == entries[-1] + S * v


def test_vpp_grad_flows(data):
    layers, mbs, scale, _ = data
    mesh = build_hybrid_mesh(pp_degree=2, dp_degree=4)
    stacked = stack_layer_params_interleaved(layers, 2, 2)

    def loss(stacked):
        out = spmd_pipeline_interleaved(_stage_fn, stacked, mbs, mesh, M, 2,
                                        extra_args=(scale,))
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(stacked)
    # gradient must reach every layer chunk (non-zero per chunk)
    gw = np.asarray(g["w"])
    for s in range(2):
        for c in range(2):
            assert np.abs(gw[s, c]).max() > 0, (s, c)

    # and must equal the gradient of the sequential reference
    def ref_loss(layers_list):
        out = _seq_reference(layers_list, mbs, scale)
        return jnp.sum(out ** 2)
    gref = jax.grad(ref_loss)(layers)
    gref_w = np.stack([np.asarray(g_["w"]) for g_ in gref])
    got_w = np.asarray(
        jnp.swapaxes(g["w"], 0, 1).reshape(gref_w.shape))
    np.testing.assert_allclose(got_w, gref_w, rtol=2e-4, atol=2e-4)
