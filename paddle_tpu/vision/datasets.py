"""paddle.vision.datasets parity (ref: python/paddle/vision/datasets/).

This environment has zero egress, so the download paths the reference uses
are unavailable; datasets load from local files when present and `FakeData`
provides deterministic synthetic data for tests/benchmarks (the reference's
own unit tests use small fake batches the same way).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10"]


class FakeData(Dataset):
    """Deterministic synthetic image dataset."""

    def __init__(self, num_samples=64, image_shape=(3, 32, 32),
                 num_classes=10, transform: Optional[Callable] = None,
                 seed=0):
        self.n = num_samples
        self.shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self.images = rng.rand(num_samples, *self.shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes, num_samples) \
            .astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


class MNIST(Dataset):
    """Loads the standard IDX files from ``root`` (no download)."""

    FILES = {
        "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }

    def __init__(self, root: str = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False):
        self.transform = transform
        if root is None or not os.path.isdir(root):
            raise RuntimeError(
                "MNIST requires local IDX files (zero-egress environment): "
                "pass root= pointing at train-images-idx3-ubyte.gz etc.")
        img_f, lab_f = self.FILES["train" if mode == "train" else "test"]
        self.images = self._read_images(os.path.join(root, img_f))
        self.labels = self._read_labels(os.path.join(root, lab_f))

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            _, n, h, w = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, h, w)

    def _read_labels(self, path):
        with self._open(path) as f:
            struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar10(Dataset):
    """Loads the python-pickle CIFAR-10 batches from ``root``."""

    def __init__(self, root: str = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False):
        import pickle
        self.transform = transform
        if root is None or not os.path.isdir(root):
            raise RuntimeError(
                "Cifar10 requires the local cifar-10-batches-py directory "
                "(zero-egress environment)")
        names = [f"data_batch_{i}" for i in range(1, 6)] \
            if mode == "train" else ["test_batch"]
        xs, ys = [], []
        for nm in names:
            with open(os.path.join(root, nm), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8)
                      .reshape(-1, 3, 32, 32))
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs)
        self.labels = np.asarray(ys, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)
