"""MoE / DeepSeek-MLA cached+compiled decode (VERDICT r3 item 6): the
serving family must cover the MoE LMs and MLA, exact-matching the buffer
path (ref capability: PaddleNLP use_cache generation over the fused MoE /
MLA decode kernels — SURVEY §2.1 fused row, §2.4).

Exactness contract: per-token dropless routing is order-independent, so an
incremental decode step routes each token identically to the full-buffer
recompute; capacity-mode drops are a TRAINING regularizer and would make
prefix-recompute and incremental decode diverge by construction (same
reason production MoE serving never drops)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.generation import (generate, generate_cached,
                                   generate_compiled)
from paddle_tpu.models.moe_llm import MoEForCausalLM, qwen2_moe_tiny_config
from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                        deepseek_v2_tiny_config)
from paddle_tpu.models.gpt import GPTForCausalLM


def _ids(B, S, vocab, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randint(1, vocab, size=(B, S)).astype("int32"))


@pytest.fixture(scope="module")
def moe_model():
    paddle.seed(7)
    cfg = qwen2_moe_tiny_config(moe_dropless=True, first_k_dense_replace=1,
                                max_position_embeddings=64)
    m = MoEForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def mla_model():
    paddle.seed(11)
    cfg = deepseek_v2_tiny_config(moe_dropless=True,
                                  max_position_embeddings=64)
    m = DeepSeekV2ForCausalLM(cfg)
    m.eval()
    return m


class TestMoEServing:
    def test_cached_exact_match_buffer(self, moe_model):
        ids = _ids(2, 6, moe_model.config.vocab_size)
        ref, ref_sc = generate(moe_model, ids, max_new_tokens=6,
                               decode_strategy="greedy_search")
        got, got_sc = generate_cached(moe_model, ids, max_new_tokens=6,
                                      decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())
        np.testing.assert_allclose(got_sc.numpy(), ref_sc.numpy(),
                                   rtol=2e-4, atol=2e-5)

    def test_compiled_matches_cached(self, moe_model):
        ids = _ids(2, 5, moe_model.config.vocab_size, seed=3)
        ref, _ = generate_cached(moe_model, ids, max_new_tokens=5,
                                 decode_strategy="greedy_search")
        got, _ = generate_compiled(moe_model, ids, max_new_tokens=5,
                                   decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())

    def test_eos_padding(self, moe_model):
        ids = _ids(1, 4, moe_model.config.vocab_size, seed=5)
        first, _ = generate_cached(moe_model, ids, max_new_tokens=1,
                                   decode_strategy="greedy_search")
        eos = int(first.numpy()[0, 0])
        gen, _ = generate_cached(moe_model, ids, max_new_tokens=5,
                                 decode_strategy="greedy_search",
                                 eos_token_id=eos, pad_token_id=0)
        assert int(gen.numpy()[0, 0]) == eos
        assert (gen.numpy()[0, 1:] == 0).all()


class TestDenseVsDroplessFFN:
    """The decode-sized dense-all-expert path must match the grouped
    dropless path exactly (the T<=32 switch in generation._ffn_apply
    relies on it), including at the threshold boundary."""

    @pytest.mark.parametrize("T", [1, 8, 32, 33, 64])
    def test_equality_across_threshold(self, T):
        from paddle_tpu.incubate.moe import (dense_expert_ffn,
                                             dropless_expert_ffn)
        import jax
        rng = np.random.RandomState(T)
        H, I, E, k = 64, 32, 4, 2
        xt = jnp.asarray(rng.randn(T, H), jnp.float32)
        gates = jax.nn.softmax(
            jnp.asarray(rng.randn(T, E), jnp.float32), -1)
        wg = jnp.asarray(rng.randn(E, H, I) * 0.1, jnp.float32)
        wu = jnp.asarray(rng.randn(E, H, I) * 0.1, jnp.float32)
        wd = jnp.asarray(rng.randn(E, I, H) * 0.1, jnp.float32)
        yd, td = dense_expert_ffn(xt, gates, wg, wu, wd, top_k=k,
                                  renormalize=True)
        yg, tg = dropless_expert_ffn(xt, gates, wg, wu, wd, top_k=k,
                                     renormalize=True)
        np.testing.assert_array_equal(np.asarray(td), np.asarray(tg))
        np.testing.assert_allclose(np.asarray(yd), np.asarray(yg),
                                   rtol=1e-6, atol=1e-6)


class TestCapacityModeWarning:
    def test_capacity_model_decode_warns(self):
        paddle.seed(23)
        cfg = qwen2_moe_tiny_config(moe_dropless=False,
                                    max_position_embeddings=32)
        m = MoEForCausalLM(cfg)
        m.eval()
        ids = _ids(1, 4, cfg.vocab_size, seed=8)
        with pytest.warns(UserWarning, match="DROPLESS"):
            generate_cached(m, ids, max_new_tokens=2,
                            decode_strategy="greedy_search")


class TestMLAServing:
    def test_cached_matches_buffer_tokens(self, mla_model):
        # absorbed decode reassociates the kv_b matmuls, so logits differ
        # at the fp round-off level; greedy tokens must still agree
        ids = _ids(2, 6, mla_model.config.vocab_size)
        ref, ref_sc = generate(mla_model, ids, max_new_tokens=6,
                               decode_strategy="greedy_search")
        got, got_sc = generate_cached(mla_model, ids, max_new_tokens=6,
                                      decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())
        np.testing.assert_allclose(got_sc.numpy(), ref_sc.numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_compiled_matches_cached(self, mla_model):
        ids = _ids(2, 5, mla_model.config.vocab_size, seed=9)
        ref, _ = generate_cached(mla_model, ids, max_new_tokens=5,
                                 decode_strategy="greedy_search")
        got, _ = generate_compiled(mla_model, ids, max_new_tokens=5,
                                   decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())

    def test_latent_cache_is_small(self, mla_model):
        # the MLA cache must store r + dr floats per token, not
        # nh * (dn + dv) — the whole point of latent attention serving
        from paddle_tpu.generation import _decode_params, _init_caches
        p = _decode_params(mla_model)
        caches = _init_caches(p, B=1, total=8)
        c_lat, c_pe = caches[0]
        cfg = mla_model.config
        assert c_lat.shape == (1, 8, cfg.kv_lora_rank)
        assert c_pe.shape == (1, 8, cfg.qk_rope_head_dim)

    def test_q_lora_disabled_variant(self):
        paddle.seed(13)
        cfg = deepseek_v2_tiny_config(q_lora_rank=None, moe_dropless=True,
                                      max_position_embeddings=64)
        m = DeepSeekV2ForCausalLM(cfg)
        m.eval()
        ids = _ids(1, 4, cfg.vocab_size, seed=2)
        ref, _ = generate(m, ids, max_new_tokens=4,
                          decode_strategy="greedy_search")
        got, _ = generate_cached(m, ids, max_new_tokens=4,
                                 decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())


class TestGPTCachedDecode:
    """ADVICE r3: the GPT cached-decode body was wired but unreachable;
    generate_cached/compiled now route through _decode_params."""

    def test_cached_exact_match_buffer(self):
        paddle.seed(17)
        from paddle_tpu.models.gpt import gpt_tiny_config
        cfg = gpt_tiny_config(max_position_embeddings=64)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = _ids(2, 5, cfg.vocab_size, seed=4)
        ref, _ = generate(m, ids, max_new_tokens=5,
                          decode_strategy="greedy_search")
        got, _ = generate_cached(m, ids, max_new_tokens=5,
                                 decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())

    def test_compiled_matches_cached(self):
        paddle.seed(19)
        from paddle_tpu.models.gpt import gpt_tiny_config
        cfg = gpt_tiny_config(max_position_embeddings=64)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = _ids(1, 4, cfg.vocab_size, seed=6)
        ref, _ = generate_cached(m, ids, max_new_tokens=4,
                                 decode_strategy="greedy_search")
        got, _ = generate_compiled(m, ids, max_new_tokens=4,
                                   decode_strategy="greedy_search")
        np.testing.assert_array_equal(got.numpy(), ref.numpy())
