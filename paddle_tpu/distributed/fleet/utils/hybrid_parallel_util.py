"""ref: python/paddle/distributed/fleet/utils/hybrid_parallel_util.py —
the manual data-parallel gradient sync used when a model is NOT wrapped in
DataParallel (SURVEY §2.3 P1: "manual alternative
fused_allreduce_gradients").

TPU-native mechanism: one flattened eager all_reduce (mean) over the dp
axis of the hybrid mesh (GSPMD handles the in-graph case; this is the
explicit eager path for hand-rolled training loops) — matching the
reference's fused-buffer NCCL allreduce semantics. With no active mesh
(single process) it is a no-op, like the reference on world_size 1.
"""

from __future__ import annotations

import jax.numpy as jnp


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Allreduce-mean every parameter's .grad across the data-parallel
    group. Grads are fused into one flat buffer for a single collective
    (tensor-fusion parity), then scattered back."""
    from ...collective import Group, all_reduce, get_group
    from ....core.tensor import Tensor

    params = [p for p in parameter_list if getattr(p, "grad", None)
              is not None]
    if not params:
        return
    # hcg may be the HybridTopology (the reference call pattern) — the dp
    # group is what gradient sync uses either way
    group = hcg if isinstance(hcg, (Group, str)) else get_group("dp")
    # documented no-op on a 1-wide (or absent) dp axis: skip the
    # flatten/scatter copies entirely
    from ...mesh import get_mesh
    mesh = get_mesh()
    axis = group if isinstance(group, str) else getattr(group, "axis", "dp")
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return
    # fuse per dtype (reference buckets per dtype too): concatenating
    # mixed bf16/f32 grads would silently promote and re-type them
    by_dtype = {}
    for p in params:
        by_dtype.setdefault(jnp.dtype(p.grad._data.dtype), []).append(p)
    for dt, group_params in by_dtype.items():
        flat = jnp.concatenate([p.grad._data.reshape(-1)
                                for p in group_params])
        reduced = all_reduce(Tensor(flat), op="avg", group=group)._data
        off = 0
        for p in group_params:
            n = int(jnp.size(p.grad._data))
            p.grad._data = reduced[off:off + n].reshape(
                p.grad._data.shape)
            off += n
