"""Worker for the executed multi-host test (SURVEY §3.1 / §5.8 DCN half):
launched by python -m paddle_tpu.distributed.launch on 2 simulated hosts;
each process owns 4 virtual CPU devices, init_parallel_env bridges the
TCPStore rendezvous into jax.distributed.initialize, and a psum runs
across all 8 global devices."""
import os

# this process simulates ONE host with 4 local devices; keep the
# collective-timeout flags the suite uses, drop the 8-device forcing
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4"
    " --xla_cpu_collective_call_terminate_timeout_seconds=900"
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300")

import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist

dist.init_parallel_env()

assert jax.process_count() == int(os.environ["PADDLE_TRAINERS_NUM"]), \
    (jax.process_count(), os.environ["PADDLE_TRAINERS_NUM"])
assert jax.device_count() == 4 * jax.process_count()

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

pid = jax.process_index()
mesh = Mesh(jax.devices(), ("x",))
data = jnp.arange(4.0) + 10 * pid
g = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("x")), data)
out = shard_map(lambda v: jax.lax.psum(v, "x"), mesh=mesh,
                in_specs=P("x"), out_specs=P())(g)
val = float(out[0])
expected = sum(float(i + 10 * p) for p in range(jax.process_count())
               for i in range(4))
assert val == expected, (val, expected)

with open(os.path.join(os.environ["MH_OUT"],
                       f"ok.{os.environ['PADDLE_TRAINER_ID']}"), "w") as f:
    f.write(f"{val}")
print("PSUM OK", val, flush=True)
