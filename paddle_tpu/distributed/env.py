"""Process-level distributed environment (ref: PADDLE_TRAINER_* env contract
set by the launcher — python/paddle/distributed/parallel.py env parsing)."""

from __future__ import annotations

import os

__all__ = ["get_rank", "get_world_size", "is_initialized"]


def get_rank() -> int:
    v = os.environ.get("PADDLE_TRAINER_ID")
    if v is not None:
        return int(v)
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    v = os.environ.get("PADDLE_TRAINERS_NUM")
    if v is not None:
        return int(v)
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


def is_initialized() -> bool:
    return True
