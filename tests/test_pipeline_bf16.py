"""bf16 through the pipeline paths on the CPU mesh (VERDICT r2 item 6).

Round 2 upcast every CPU-mesh pipelined region to f32
(_cpu_f32_upcast), so the flagship's bf16 numerics never executed in
any pipeline test. Round 3 removed the upcast: AD's psum of sub-f32
cotangents (the XLA-CPU "Invalid binary instruction opcode copy"
crash) is routed through the f32-transposed `_pvary_safe` instead, so
the stage compute genuinely runs bf16 everywhere. These tests pin (a)
the dtype actually executed inside the stage, (b) bf16-vs-f32 loss
agreement within bf16 tolerance, for the compiled, 1F1B, and VPP
paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import llama_tiny_config
from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                         build_llama_pretrain_step,
                                         make_hybrid_mesh_for)


def _run(pp_schedule, param_dtype, vpp=1, dtype_probe=None):
    paddle.seed(21)
    mc = llama_tiny_config(num_hidden_layers=4, max_position_embeddings=64,
                           sequence_parallel=False)
    cfg = PretrainConfig(mc, global_batch=4, seq_len=32, n_microbatches=4,
                         dp=1, mp=2, pp=2, sharding=1, sep=1, vpp=vpp,
                         pp_schedule=pp_schedule, param_dtype=param_dtype)
    mesh = make_hybrid_mesh_for(cfg, devices=jax.devices()[:4])
    state, step, meta = build_llama_pretrain_step(cfg, mesh)
    if dtype_probe is not None:
        # the compute params the step will consume
        for leaf in jax.tree.leaves(state.params):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                dtype_probe.append(str(leaf.dtype))
    rng = np.random.RandomState(0)
    ids = jax.device_put(jnp.asarray(
        rng.randint(0, mc.vocab_size, (4, 32)), jnp.int32),
        meta["data_sharding"])
    state, m = step(state, ids, ids)
    return float(m["loss"])


@pytest.mark.parametrize("sched,vpp", [("compiled", 1), ("1F1B", 1),
                                       ("VPP", 2)])
def test_bf16_pipeline_matches_f32(sched, vpp):
    probe = []
    l_bf16 = _run(sched, "bfloat16", vpp=vpp, dtype_probe=probe)
    l_f32 = _run(sched, "float32", vpp=vpp)
    assert np.isfinite(l_bf16)
    # the executed compute-param dtype IS bf16 (not silently upcast)
    assert probe and all(d == "bfloat16" for d in probe), set(probe)
    # bf16 rounding on a tiny model: ~1e-2 relative is the honest bound
    np.testing.assert_allclose(l_bf16, l_f32, rtol=2e-2)


def test_bf16_stage_activation_dtype_is_bf16():
    """Direct executor probe: the activation arriving at stage_fn under
    the compiled pipeline must be bf16 when fed bf16 (the old upcast
    widened it to f32 on CPU)."""
    from paddle_tpu.distributed.mesh import build_hybrid_mesh
    from paddle_tpu.distributed.pipeline import spmd_pipeline

    mesh = build_hybrid_mesh(pp_degree=2, devices=jax.devices()[:2])
    seen = []

    def stage_fn(local, x):
        seen.append(str(x.dtype))
        return jnp.tanh(x @ local["w"][0])

    rng = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(
        rng.standard_normal((2, 1, 8, 8)), jnp.bfloat16)}
    mbs = jnp.asarray(rng.standard_normal((4, 3, 8)), jnp.bfloat16)

    def loss(sp, xb):
        out = spmd_pipeline(stage_fn, sp, xb, mesh, 4)
        return out.astype(jnp.float32).sum()

    val, grads = jax.value_and_grad(loss, argnums=(0, 1))(stacked, mbs)
    assert np.isfinite(float(val))
    assert seen and all(d == "bfloat16" for d in seen), set(seen)
    assert grads[1].dtype == jnp.bfloat16
    assert float(jnp.abs(grads[1].astype(jnp.float32)).sum()) > 0
