"""Pipeline schedule generator tests (ref: the schedule options of
python/paddle/distributed/passes/pipeline_scheduler_pass.py — FThenB / 1F1B /
VPP / ZBH1; SURVEY §2.3 P6).

Pure-host checks: dependency-valid timetables, the 1F1B activation-memory
bound, and the ZBH1 zero-bubble improvement.
"""

import pytest

from paddle_tpu.distributed.pp_schedule import (
    SCHEDULERS, fthenb_schedule, generate_schedule,
    interleaved_1f1b_schedule, one_f_one_b_schedule, zbh1_schedule)

CASES = [(2, 4), (4, 8), (4, 4), (8, 16), (3, 9)]


@pytest.mark.parametrize("S,M", CASES)
def test_all_schedules_complete_and_dependency_valid(S, M):
    for mode in SCHEDULERS:
        chunks = 2 if mode == "VPP" else 1
        sched = generate_schedule(mode, S, M, n_chunks=chunks)
        sched.validate()


def test_non_vpp_rejects_chunks():
    with pytest.raises(ValueError):
        generate_schedule("1F1B", 4, 8, n_chunks=4)


@pytest.mark.parametrize("S,M", CASES)
def test_1f1b_bounds_activation_memory(S, M):
    gpipe = fthenb_schedule(S, M)
    ofob = one_f_one_b_schedule(S, M)
    # GPipe holds every microbatch at stage 0; 1F1B holds at most the
    # stage depth — and never more than GPipe
    assert gpipe.peak_inflight(0) == M
    assert ofob.peak_inflight(0) <= min(S, M)
    for s in range(S):
        assert ofob.peak_inflight(s) <= min(S - s, M)


@pytest.mark.parametrize("S,M", [(2, 4), (4, 8), (8, 16)])
def test_zbh1_zero_bubble_at_1f1b_memory(S, M):
    ofob = one_f_one_b_schedule(S, M)
    zb = zbh1_schedule(S, M)
    zb.validate()
    # strictly fewer bubbles...
    assert zb.bubble_ratio() < ofob.bubble_ratio()
    # ...at the same activation-memory class (H1)
    for s in range(S):
        assert zb.peak_inflight(s) <= ofob.peak_inflight(s)


@pytest.mark.parametrize("S,M,C", [(2, 8, 2), (4, 8, 2), (4, 16, 4)])
def test_vpp_shrinks_bubble(S, M, C):
    ofob = one_f_one_b_schedule(S, M)
    vpp = interleaved_1f1b_schedule(S, M, C)
    vpp.validate()
    assert vpp.bubble_ratio() < ofob.bubble_ratio()


def test_generate_schedule_rejects_unknown():
    with pytest.raises(ValueError):
        generate_schedule("nope", 2, 4)
