"""Distributed launcher (ref: python/paddle/distributed/launch/ — SURVEY
§2.3 P14, §3.5 CLI, §5.3 failure detection).

`python -m paddle_tpu.distributed.launch [--nproc_per_node N] script.py ...`
"""

from .main import launch, main  # noqa: F401
from .controllers import CollectiveController, ElasticManager  # noqa: F401
