"""Unit tests for the grid memory-effects model (ISSUE 19 tentpole).

These exercise :mod:`paddle_tpu.analysis.effectsmodel` directly at the
primitive level — revisit-axis derivation, guard classification, escape
analysis, alias-pair naming, scatter modeling, verdict signatures — on
small synthetic kernels, plus whole-repo invariants the PE rules rely
on (every canonical site builds a model; write bytes match the cost
registry exactly).  The rule-level behavior (findings, baselines,
seeded mutations) lives in tests/test_paddlelint.py.
"""

import os
import textwrap

from paddle_tpu.analysis import effectsmodel as em
from paddle_tpu.analysis import kernelmodel as km
from paddle_tpu.analysis import vmemmodel as vm
from paddle_tpu.analysis.callgraph import PackageIndex
from paddle_tpu.analysis.runner import discover

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_HEADER = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

"""


def _effects(src):
    index = PackageIndex.from_source(_HEADER + textwrap.dedent(src),
                                     modname="snip", rel="snip.py")
    sites = km.collect_kernel_calls(index)
    assert len(sites) == 1, "fixture must contain exactly one launch"
    eff = em.build_effects(sites[0])
    assert eff is not None, "fixture site failed to model"
    return eff


class TestRevisitAxes:
    def test_statically_unreferenced_dim_revisits(self):
        eff = _effects("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4, 8),
                    in_specs=[pl.BlockSpec((1, 128),
                                           lambda i, j: (i, j))],
                    out_specs=pl.BlockSpec((1, 128),
                                           lambda i, j: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
        """)
        out = eff.outputs[0]
        assert out.revisit_axes == {1}
        assert out.table_axes == set()
        # and the launch declares nothing
        assert eff.dim_semantics is None

    def test_table_driven_dim_revisits_even_though_referenced(self):
        eff = _effects("""
            def _kern(pg_ref, x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x, pg):
                def page_map(t, pg):
                    return (jnp.clip(pg[t], 0, 7), 0)
                return pl.pallas_call(
                    _kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1,
                        grid=(8,),
                        in_specs=[pl.BlockSpec((1, 128),
                                               lambda t, pg: (t, 0))],
                        out_specs=pl.BlockSpec((1, 128), page_map),
                    ),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(pg, x)
        """)
        out = eff.outputs[0]
        # page_map references t, but only through the pg table: the
        # block index is data-dependent and may repeat along dim 0
        assert out.table_axes == {0}
        assert out.revisit_axes == {0}
        # the plain input sweeps dim 0 directly — no revisit
        assert eff.of_kind("in")[0].revisit_axes == set()

    def test_declared_arbitrary_axis(self):
        eff = _effects("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4, 8),
                    in_specs=[pl.BlockSpec((1, 128),
                                           lambda i, j: (i, j))],
                    out_specs=pl.BlockSpec((1, 128),
                                           lambda i, j: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    compiler_params=pltpu.CompilerParams(
                        dimension_semantics=("parallel", "arbitrary")),
                )(x)
        """)
        assert eff.dim_semantics == ["parallel", "arbitrary"]
        assert not eff.declared_arbitrary(0)
        assert eff.declared_arbitrary(1)
        assert em.ww_hazards(eff) == []


class TestGuardsAndAccesses:
    SRC = """
        def _kern(x_ref, o_ref, acc_ref):
            j = pl.program_id(1)
            nj = pl.num_programs(1)

            @pl.when(j == 0)
            def _init():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            acc_ref[:] = acc_ref[:] + x_ref[:]

            @pl.when(j == nj - 1)
            def _emit():
                o_ref[:] = acc_ref[:]

        def run(x):
            return pl.pallas_call(
                _kern,
                grid=(4, 8),
                in_specs=[pl.BlockSpec((1, 128), lambda i, j: (i, j))],
                out_specs=pl.BlockSpec((1, 128), lambda i, j: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                scratch_shapes=[pltpu.VMEM((1, 128), jnp.float32)],
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("parallel", "arbitrary")),
            )(x)
    """

    def test_guard_classification_first_and_last(self):
        eff = _effects(self.SRC)
        acc = eff.refs["acc_ref"]
        assert {s.guard for s in acc.stores} == {"first", None}
        # the emit read is classified "last" through the nj local
        assert "last" in {a.guard for a in acc.loads}
        assert em.accumulator_hazards(eff) == []

    def test_dead_init_does_not_count(self):
        # identical kernel minus the @pl.when decorator: _init is never
        # called, so its store must not satisfy the init requirement
        src = self.SRC.replace("            @pl.when(j == 0)\n"
                               "            def _init():",
                               "            def _init():")
        eff = _effects(src)
        hazards = em.accumulator_hazards(eff)
        assert [h["detail"] for h in hazards] == ["acc:acc_ref"]

    def test_unconditional_init_before_first_read_ok(self):
        eff = _effects("""
            def _kern(x_ref, o_ref, acc_ref):
                acc_ref[:] = jnp.zeros_like(acc_ref)
                acc_ref[:] = acc_ref[:] + x_ref[:]
                o_ref[:] = acc_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128),
                                           lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    scratch_shapes=[pltpu.VMEM((1, 128), jnp.float32)],
                )(x)
        """)
        assert em.accumulator_hazards(eff) == []

    def test_escaping_ref_degrades_to_unknown(self):
        # the scratch ref is handed to a helper the scanner cannot
        # follow (the paged-v2 DMA idiom) — no PE503, no false claim
        eff = _effects("""
            def _kern(x_ref, o_ref, buf_ref):
                def fill(dst):
                    return dst
                fill(buf_ref)
                o_ref[:] = buf_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128),
                                           lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    scratch_shapes=[pltpu.VMEM((1, 128), jnp.float32)],
                )(x)
        """)
        assert eff.refs["buf_ref"].escapes
        assert em.accumulator_hazards(eff) == []


class TestAliasPairsAndScatter:
    SRC = """
        def _kern(pg_ref, off_ref, r_ref, pin_ref, po_ref):
            t = pl.program_id(0)
            prev = pg_ref[t - 1]

            @pl.when((t == 0) | (pg_ref[t] != prev))
            def _seed():
                po_ref[:] = pin_ref[:]

            po_ref[:, pl.dslice(off_ref[t], {width}), :] = r_ref[:]

        def run(rows, pages, pg, off):
            def page_map(t, pg, off):
                return (jnp.clip(pg[t], 0, 7), 0, 0)
            return pl.pallas_call(
                _kern,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=2,
                    grid=(8,),
                    in_specs=[
                        pl.BlockSpec((1, 1, 128),
                                     lambda t, pg, off: (t, 0, 0)),
                        pl.BlockSpec((1, 32, 128), page_map),
                    ],
                    out_specs=pl.BlockSpec((1, 32, 128), page_map),
                ),
                out_shape=jax.ShapeDtypeStruct(pages.shape,
                                               pages.dtype),
                input_output_aliases={{3: 0}},
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary",)),
            )(pg, off, rows, pages)
    """

    def test_alias_pair_maps_flat_index_past_prefetch(self):
        eff = _effects(self.SRC.format(width=1))
        assert [(a.name, b.name) for a, b in eff.alias_pairs] \
            == [("pin_ref", "po_ref")]
        assert em.alias_read_hazards(eff) == []

    def test_width_one_table_scatter_is_proven(self):
        eff = _effects(self.SRC.format(width=1))
        errors, notes = em.scatter_hazards(eff)
        assert errors == []
        assert [n["detail"] for n in notes] == ["scatter-contract:po_ref"]
        store = next(s for s in eff.refs["po_ref"].stores if s.dynamic)
        assert store.dyn_width == 1 and store.dyn_stepped

    def test_widened_scatter_is_a_hazard(self):
        eff = _effects(self.SRC.format(width=2))
        errors, notes = em.scatter_hazards(eff)
        assert [e["detail"] for e in errors] == ["scatter:po_ref:w2"]
        assert notes == []

    def test_read_after_donated_write_orders_by_line(self):
        # move the donated-input read AFTER the scatter store: the
        # alias makes pin/po one buffer, so the read is a hazard
        src = self.SRC.format(width=1).replace(
            "po_ref[:, pl.dslice(off_ref[t], 1), :] = r_ref[:]",
            "po_ref[:, pl.dslice(off_ref[t], 1), :] = r_ref[:]\n"
            "            x = pin_ref[:]")
        eff = _effects(src)
        hazards = em.alias_read_hazards(eff)
        assert [h["detail"] for h in hazards] \
            == ["radw:pin_ref->po_ref"]


class TestWholeRepoInvariants:
    def _index(self):
        return PackageIndex.from_files(
            discover(os.path.join(REPO, "paddle_tpu")))

    def test_every_canonical_site_builds_a_model(self):
        index = self._index()
        sites = vm.canonical_sites(self._index())
        assert len(sites) == len(vm.CANONICAL)
        for qn, site in sorted(sites.items()):
            eff = em.build_effects(site)
            assert eff is not None, qn
            assert eff.outputs, qn

    def test_every_revisited_output_is_declared(self):
        # the repo-wide PE501 invariant, asserted at the model level:
        # each revisit axis of each canonical output is "arbitrary"
        index = self._index()
        for qn, site in sorted(vm.canonical_sites(index).items()):
            eff = em.build_effects(site)
            for out in eff.outputs:
                for axis in sorted(out.revisit_axes or ()):
                    assert eff.declared_arbitrary(axis), (qn, out.name,
                                                         axis)

    def test_write_bytes_match_cost_registry_exactly(self):
        # PE506's clean-tree contract is stronger than the 5% gate:
        # every resolvable canonical kernel's derived write bytes equal
        # costmodel.bytes_written exactly
        recs = em.derive_write_bytes(self._index())
        assert recs
        checked = [r for r in recs if r["status"] in ("ok", "drift")]
        assert checked, "no canonical write side resolved"
        for r in checked:
            assert r["status"] == "ok", r
            assert r["derived"] == r["expected"], r

    def test_layer_body_composition_is_certified_legal(self):
        # ISSUE 20 shipped the old front_half_qkv_rope_append
        # composition as fused_qkv_rope_append; the registered
        # follow-on is the <=4-launch whole-body chain
        verdicts = em.compose_verdicts(self._index())
        comp = next(v for v in verdicts
                    if v["composition"] == "decode_layer_le4")
        assert comp["verdict"] == "legal"
        assert comp["members"] == ["fused_rms_norm",
                                   "fused_qkv_rope_append",
                                   "fused_oproj_norm", "fused_ffn"]
        # every verdict is JSON-shaped: strings and lists only
        import json
        json.dumps(verdicts)
