"""In-tree paged-attention decode kernel (ops/pallas_paged.py — VERDICT
r2 Missing #7; ref: paddle/phi/kernels/fusion/gpu/
block_multi_head_attention*). The XLA gather composite
(paged_attention_reference) is the correctness oracle. Runs in Pallas
interpret mode on CPU: same kernel logic as the TPU path."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.paged_attention import (paged_attention,
                                            paged_attention_reference)
from paddle_tpu.ops.pallas_paged import (paged_decode_attention,
                                         paged_kernel_eligible)


def _setup(B, H, KV, D, psz, pages_per_seq, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    total = B * pages_per_seq
    q = jnp.asarray(rng.randn(B, H, D), dtype)
    kp = jnp.asarray(rng.randn(KV, total, psz, D), dtype)
    vp = jnp.asarray(rng.randn(KV, total, psz, D), dtype)
    tab = jnp.asarray(rng.permutation(total).reshape(B, pages_per_seq),
                      jnp.int32)
    lens = jnp.asarray(rng.randint(1, pages_per_seq * psz + 1, (B,)),
                       jnp.int32)
    return q, kp, vp, lens, tab


class TestPagedKernelParity:
    @pytest.mark.parametrize("B,H,KV,D,psz,pps", [
        (3, 8, 2, 128, 16, 8),    # GQA rep=4, random table, ragged lens
        (2, 4, 1, 64, 16, 4),     # MQA, D=64
        (2, 4, 4, 128, 32, 4),    # MHA (rep=1), bigger pages
    ])
    def test_matches_reference(self, B, H, KV, D, psz, pps):
        q, kp, vp, lens, tab = _setup(B, H, KV, D, psz, pps)
        out = paged_decode_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_token_length(self):
        # lens=1: only the first slot of the first page is visible
        q, kp, vp, _, tab = _setup(2, 4, 2, 128, 16, 4, seed=3)
        lens = jnp.asarray([1, 1], jnp.int32)
        out = paged_decode_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16(self):
        q, kp, vp, lens, tab = _setup(2, 8, 2, 128, 16, 4, seed=5,
                                      dtype=jnp.bfloat16)
        out = paged_decode_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   atol=3e-2, rtol=3e-2)

    def test_custom_scale(self):
        q, kp, vp, lens, tab = _setup(2, 4, 2, 128, 16, 4, seed=7)
        out = paged_decode_attention(q, kp, vp, lens, tab, scale=0.05)
        ref = paged_attention_reference(q, kp, vp, lens, tab, scale=0.05)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestRouting:
    def test_default_routes_intree(self):
        from paddle_tpu.flags import flag
        assert flag("FLAGS_paged_impl") == "intree"
        q, kp, vp, lens, tab = _setup(2, 4, 2, 128, 16, 4)
        out = paged_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_ineligible_falls_back(self):
        # D=96 is not MXU-eligible; the route must still be correct
        q, kp, vp, lens, tab = _setup(2, 4, 2, 96, 16, 4)
        assert not paged_kernel_eligible(4, 2, 96, 16)
        out = paged_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flag_reference_impl(self):
        from paddle_tpu.flags import flags_guard
        q, kp, vp, lens, tab = _setup(2, 4, 2, 128, 16, 4)
        with flags_guard(paged_impl="reference"):
            out = paged_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestPagedV2GroupedDMA:
    """The grouped-DMA kernel (paged_decode_attention_v2): VERDICT r3
    weak #1 — multi-page prefetch with double buffering; must match the
    XLA-composite oracle bit-for-logical-bit at every routing shape."""

    @pytest.mark.parametrize("G", [1, 3, 4])
    def test_parity_group_sizes(self, G):
        from paddle_tpu.ops.pallas_paged import paged_decode_attention_v2
        q, kp, vp, lens, tab = _setup(B=3, H=4, KV=2, D=128, psz=16,
                                      pages_per_seq=8, seed=3)
        out = paged_decode_attention_v2(q, kp, vp, lens, tab,
                                        pages_per_group=G)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_zero_length_and_full_length_rows(self):
        from paddle_tpu.ops.pallas_paged import paged_decode_attention_v2
        q, kp, vp, _, tab = _setup(B=2, H=4, KV=1, D=128, psz=16,
                                   pages_per_seq=4, seed=5)
        lens = jnp.asarray([0, 64], jnp.int32)
        out = paged_decode_attention_v2(q, kp, vp, lens, tab,
                                        pages_per_group=2)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        assert bool(jnp.all(jnp.isfinite(out)))
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(ref[1]),
                                   rtol=2e-5, atol=2e-5)

    def test_ragged_group_tail(self):
        # pages_per_seq not divisible by the group size
        from paddle_tpu.ops.pallas_paged import paged_decode_attention_v2
        q, kp, vp, lens, tab = _setup(B=2, H=2, KV=2, D=128, psz=16,
                                      pages_per_seq=7, seed=7)
        out = paged_decode_attention_v2(q, kp, vp, lens, tab,
                                        pages_per_group=4)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_default_group_heuristic(self):
        from paddle_tpu.ops.pallas_paged import default_pages_per_group
        assert default_pages_per_group(256, 16) == 16    # 4k ctx
        assert default_pages_per_group(1024, 16) == 32   # 16k ctx
        assert default_pages_per_group(512, 32) == 32    # 16k ctx

    def test_intree_routing_uses_v2(self):
        from paddle_tpu.flags import flags_guard
        q, kp, vp, lens, tab = _setup(B=2, H=4, KV=2, D=128, psz=16,
                                      pages_per_seq=4, seed=9)
        with flags_guard(paged_impl="intree"):
            out = paged_attention(q, kp, vp, lens, tab)
        ref = paged_attention_reference(q, kp, vp, lens, tab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
