"""paddle.autograd parity surface (ref: python/paddle/autograd/).

backward/grad on the tape, PyLayer custom autograd functions, hooks.
"""

from __future__ import annotations

from typing import Any

import jax

from ..core import autograd as _engine
from ..core.autograd import (GradNode, enable_grad, is_grad_enabled, no_grad,
                             set_grad_enabled)
from ..core.dispatch import apply
from ..core.tensor import Tensor

from .functional import hessian, jacobian, jvp, vjp  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "vjp", "jvp", "jacobian", "hessian",
           "set_grad_enabled", "PyLayer", "PyLayerContext"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    for t, g in zip(tensors, grad_tensors):
        _engine.backward(t, g, retain_graph)


grad = _engine.grad


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.attrs = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def __getattr__(self, k):
        try:
            return self.__dict__["attrs"][k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        if k in ("_saved", "attrs"):
            object.__setattr__(self, k, v)
        else:
            self.attrs[k] = v


class PyLayer:
    """Custom autograd function (ref: paddle.autograd.PyLayer).

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx, *grads)``.
    TPU note: forward/backward run as eager tensor code; under tracing they
    are traced like any other op chain.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs = is_grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)
        if not needs:
            return outs

        # one slot per tensor input (vjp returns a grad per slot); grads for
        # stop_gradient inputs are dropped by marking the slot None
        parents = [t if not t.stop_gradient else None for t in tensor_inputs]

        def vjp_fn(cots):
            cots = cots if isinstance(cots, tuple) else (cots,)
            with no_grad():
                gin = cls.backward(
                    ctx, *[Tensor(c, stop_gradient=True) for c in cots])
            gin = (gin,) if isinstance(gin, Tensor) else tuple(gin)
            return tuple(g._data if isinstance(g, Tensor) else g for g in gin)

        node = GradNode(
            vjp_fn, parents,
            [jax.ShapeDtypeStruct(o._data.shape, o._data.dtype) for o in outs_t],
            name=cls.__name__)
        import weakref
        results = []
        for o in outs_t:
            r = Tensor(o._data, stop_gradient=False)
            r._node = node
            node.out_refs.append(weakref.ref(r))
            results.append(r)
        return results[0] if single else tuple(results)
