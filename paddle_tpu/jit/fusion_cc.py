"""Driver for the C++ StableHLO fusion pass (csrc/fusion_pass.cc) —
the CINN-parity static-program compiler pipeline (ref: paddle/cinn
ApplyCinnPass on the static Program; SURVEY §2.1 L8, VERDICT r2 item 3).

Pipeline, mirroring the reference's static-graph flow:
  1. lower the traced function to StableHLO text (the static program),
  2. C++ pass: pattern-match sdpa / rmsnorm / swiglu regions and report,
  3. Python lowers a replacement kernel function per match (the Pallas
     kernel on TPU, the reference composite elsewhere) at the matched
     shapes,
  4. C++ pass rewrites the module text: interior ops deleted, final op
     replaced by a func.call, kernel funcs spliced in,
  5. the rewritten text is re-parsed by the MLIR verifier and compiled
     by PJRT; `fuse_compile` returns the loaded executable wrapped as a
     python callable.

This is the inference/static path (like CINN); the eager/AD path keeps
the jaxpr-level pass in jit/fusion.py. Both share FLAGS_use_fusion_compiler.
"""

from __future__ import annotations

import ctypes
import functools
import json
import os
import re
import subprocess
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fuse_compile", "analyze_text", "rewrite_text", "available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "..", "csrc", "fusion_pass.cc")
_SO = os.path.join(_DIR, "..", "native", "_fusion_pass.so")

_lib = None


def _build() -> Optional[str]:
    src = os.path.abspath(_SRC)
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", src,
             "-o", _SO], check=True, capture_output=True, timeout=180)
        return _SO
    except Exception:
        return None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    so = _build()
    if so is None:
        return None
    L = ctypes.CDLL(so)
    L.ptpu_fusion_analyze.argtypes = [ctypes.c_char_p]
    L.ptpu_fusion_analyze.restype = ctypes.c_void_p
    L.ptpu_fusion_rewrite.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    L.ptpu_fusion_rewrite.restype = ctypes.c_void_p
    L.ptpu_free.argtypes = [ctypes.c_void_p]
    _lib = L
    return L


def available() -> bool:
    return _load() is not None


def _call_c(fn, *args: bytes) -> str:
    ptr = fn(*args)
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        _load().ptpu_free(ptr)


def analyze_text(module_text: str) -> List[Dict[str, Any]]:
    """Run the C++ matcher over StableHLO text -> list of match dicts."""
    L = _load()
    if L is None:
        raise RuntimeError("fusion_pass.so unavailable (no g++?)")
    rep = _call_c(L.ptpu_fusion_analyze, module_text.encode())
    return json.loads(rep)["matches"]


def rewrite_text(module_text: str, plan: str) -> str:
    L = _load()
    if L is None:
        raise RuntimeError("fusion_pass.so unavailable (no g++?)")
    return _call_c(L.ptpu_fusion_rewrite, module_text.encode(),
                   plan.encode())


# ---------------------------------------------------------------------------
# type parsing + replacement kernels
# ---------------------------------------------------------------------------
# only dtypes we can lower replacement kernels at FAITHFULLY — an f64/i64
# module must not silently get f32/i32 kernels spliced in (the synthesized
# call keeps the original operand types and the module would fail MLIR
# verification, or worse, lose precision)
_DT = {"f32": jnp.float32, "f16": jnp.float16, "bf16": jnp.bfloat16,
       "i32": jnp.int32, "i8": jnp.int8, "i1": jnp.bool_}


def _parse_tensor_type(t: str) -> jax.ShapeDtypeStruct:
    m = re.match(r"tensor<(.*)>", t.strip())
    if not m:
        raise ValueError(f"not a tensor type: {t!r}")
    parts = m.group(1).split("x")
    dt = _DT[parts[-1]]
    dims = tuple(int(p) for p in parts[:-1])
    return jax.ShapeDtypeStruct(dims, dt)


def _sdpa_kernel(scale: float):
    # shares jit/fusion.py's executor so kernel dispatch policy lives in
    # exactly one place
    from .fusion import _exec_sdpa

    def fn(q, k, v):
        m = {"scale": scale, "q": 0, "k": 1, "v": 2}
        return _exec_sdpa(m, lambda i: (q, k, v)[i])
    return fn


def _rmsnorm_kernel(eps: float):
    def fn(x, w):
        from ..ops.fused import fused_rms_norm
        return fused_rms_norm(x, w, eps=eps)
    return fn


def _swiglu_kernel():
    def fn(gate, up):
        from ..ops.fused import swiglu
        return swiglu(gate, up)
    return fn


# stablehlo elementwise op -> jnp impl (the generic-region interpreter's
# instruction set; mirror of fusion_pass.cc ew_ops())
_EW_IMPL = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "exponential": jnp.exp, "log": jnp.log, "tanh": jnp.tanh,
    "logistic": jax.nn.sigmoid, "rsqrt": jax.lax.rsqrt, "sqrt": jnp.sqrt,
    "negate": jnp.negative, "abs": jnp.abs, "power": jnp.power,
}


def _run_generic_prog(prog, vals):
    """Execute a reported region program on concrete/traced arrays."""
    env = {}

    def get(tok):
        if tok.startswith("#"):
            return vals[int(tok[1:])]
        return env[tok]

    out = None
    for st in prog:
        out = _EW_IMPL[st["op"]](*[get(t) for t in st["ins"]])
        env[st["out"]] = out
    return out


def _generic_kernel(match: Dict[str, Any]):
    """Synthesize ONE Pallas loop for an arbitrary matched elementwise
    region (CINN generic-fusion parity): flatten to [M, 128] lanes, tile
    the rows, and run the region program on each tile in VMEM."""
    import numpy as _np
    from jax.experimental import pallas as pl

    prog = match["prog"]
    out_aval = _parse_tensor_type(match["result_type"])
    shape = out_aval.shape
    total = int(_np.prod(shape)) if shape else 1
    M = total // 128

    def fn(*xs):
        bm = min(M, 256)
        while M % bm:
            bm //= 2

        def kernel(*refs):
            ins, out = refs[:-1], refs[-1]
            out[:] = _run_generic_prog(
                prog, [r[:] for r in ins]).astype(out.dtype)

        flat = [x.reshape(M, 128) for x in xs]
        out = pl.pallas_call(
            kernel,
            grid=(M // bm,),
            in_specs=[pl.BlockSpec((bm, 128), lambda i: (i, 0))
                      for _ in xs],
            out_specs=pl.BlockSpec((bm, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((M, 128), out_aval.dtype),
            interpret=jax.default_backend() != "tpu",
        )(*flat)
        return out.reshape(shape)

    return fn


def _replacement_fn(match: Dict[str, Any]):
    p = match["pattern"]
    if p == "sdpa":
        return _sdpa_kernel(float(match["scale"]))
    if p == "rmsnorm":
        return _rmsnorm_kernel(float(match["eps"]))
    if p == "swiglu":
        return _swiglu_kernel()
    if p == "generic":
        return _generic_kernel(match)
    raise ValueError(f"unknown pattern {p!r}")


def _eligible(match: Dict[str, Any]) -> bool:
    """Same kernel-eligibility gates as the jaxpr pass (shared fns)."""
    try:
        avals = [_parse_tensor_type(t) for t in match["operand_types"]]
    except (ValueError, KeyError):
        return False
    if match["pattern"] == "sdpa":
        from .fusion import _flash_eligible_shapes
        return _flash_eligible_shapes(avals[0], avals[1])
    if match["pattern"] == "generic":
        import numpy as _np
        try:
            out_aval = _parse_tensor_type(match["result_type"])
        except (ValueError, KeyError):
            return False
        if not _np.issubdtype(out_aval.dtype, _np.floating):
            return False
        # one flattened [M, 128] Pallas view must fit every operand: the
        # matcher guarantees same-type interiors, so same shape throughout
        total = int(_np.prod(out_aval.shape)) if out_aval.shape else 1
        if total % 128 != 0 or total < 128 * 8:
            return False
        return all(a.shape == out_aval.shape and a.dtype == out_aval.dtype
                   for a in avals)
    if jax.default_backend() == "tpu":
        return avals[0].shape[-1] % 128 == 0
    return True


def _extract_and_rename_funcs(kernel_text: str, main_name: str) -> str:
    """Pull the func.func blocks out of a lowered kernel module, rename
    @main -> @{main_name} (private) and suffix every other symbol so
    splicing into the target module cannot collide."""
    lines = kernel_text.splitlines()
    # module body = between the first line ending in '{' and the last '}'
    start = next(i for i, ln in enumerate(lines)
                 if ln.rstrip().endswith("{")) + 1
    end = max(i for i, ln in enumerate(lines) if ln.strip() == "}")
    body = lines[start:end]
    names = set(re.findall(r"func\.func\s+(?:public|private)?\s*@"
                           r"([A-Za-z_][\w.]*)", "\n".join(body)))
    text = "\n".join(body)
    for n in sorted(names, key=len, reverse=True):
        new = main_name if n == "main" else f"{n}_{main_name}"
        text = re.sub(rf"@{re.escape(n)}\b", f"@{new}", text)
    text = text.replace("func.func public", "func.func private")
    # strip arg/result attribute dicts jax attaches to @main's signature
    text = re.sub(r" \{jax\.[^}]*\}", "", text)
    text = re.sub(r" \{mhlo\.[^}]*\}", "", text)
    return text + "\n"


def fuse_compile(fn, *example_args):
    """Compile `fn` through the C++ StableHLO fusion pipeline; returns
    a callable wrapper around the PJRT LoadedExecutable (inference/
    static path). example_args may be arrays OR jax.ShapeDtypeStruct
    specs (no buffers allocated). Wrapper attributes: .module_text
    (rewritten StableHLO), .matches (the C++ pass's report), .n_fused."""
    lowered = jax.jit(fn).lower(*example_args)
    text = lowered.as_text()
    out_shape = jax.eval_shape(fn, *example_args)
    out_leaves, out_tree = jax.tree_util.tree_flatten(out_shape)

    matches = [m for m in analyze_text(text) if _eligible(m)]

    if not matches:
        # nothing to rewrite: return the plain jitted fn (no second
        # compile of an identical module; Predictor keeps its jit path)
        wrapped0 = jax.jit(fn)

        @functools.wraps(fn)
        def passthrough(*args):
            flat, tree = jax.tree_util.tree_flatten(args)
            flat = [x._data if hasattr(x, "_data") else x for x in flat]
            return wrapped0(*jax.tree_util.tree_unflatten(tree, flat))
        passthrough.module_text = text
        passthrough.matches = []
        passthrough.n_fused = 0
        return passthrough

    if matches:
        plan_parts = []
        for m in matches:
            avals = [_parse_tensor_type(t) for t in m["operand_types"]]
            kname = f"ptpu_fused_{m['pattern']}_{m['id']}"
            ktext = jax.jit(_replacement_fn(m)).lower(*avals).as_text()
            funcs = _extract_and_rename_funcs(ktext, kname)
            header = (f"#MATCH {m['final_line']} {kname} {m['result']}"
                      f"\t{m['result_type']}"
                      f"\t{', '.join(m['operands'])}"
                      f"\t{', '.join(m['operand_types'])}"
                      f"\t{' '.join(str(i) for i in m['chain_lines'])}")
            plan_parts.append(header + "\n" + funcs + "#END")
        new_text = rewrite_text(text, "\n".join(plan_parts))
    else:
        new_text = text

    from jax._src import compiler, xla_bridge
    from jax._src.interpreters import mlir
    from jax._src.lib import xla_client as xc
    from jax._src.lib.mlir import ir

    backend = xla_bridge.get_backend()
    with mlir.make_ir_context():
        module = ir.Module.parse(new_text)   # MLIR verifier gate
        opts = xc.CompileOptions()
        if hasattr(compiler, "backend_compile_and_load"):
            devs = xc.DeviceList(tuple(backend.local_devices()[:1]))
            exe = compiler.backend_compile_and_load(
                backend, module, devs, opts, [])
        else:  # older jax: no explicit executable-device list
            exe = compiler.backend_compile(backend, module, opts, [])

    n_out = len(out_leaves)

    @functools.wraps(fn)
    def wrapped(*args):
        flat, tree = jax.tree_util.tree_flatten(args)
        bufs = [jax.device_put(x._data if hasattr(x, "_data") else x)
                for x in flat]
        res = exe.execute_sharded(bufs)
        # keep results as device arrays: a np.asarray handler here would
        # force a device->host->device round-trip on every call
        outs = res.consume_with_handlers([
            (lambda shards: shards[0])] * n_out)
        arrs = [jnp.asarray(o).astype(l.dtype)
                for o, l in zip(outs, out_leaves)]
        return jax.tree_util.tree_unflatten(out_tree, arrs)

    wrapped.module_text = new_text
    wrapped.matches = matches
    wrapped.n_fused = len(matches)
    return wrapped
