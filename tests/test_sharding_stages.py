"""ZeRO sharding stages 1-3 (SURVEY §2.3 P2/P3) on the simulated mesh."""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import build_hybrid_mesh, mesh_context
from paddle_tpu.distributed.sharding import (DygraphShardingOptimizer,
                                             group_sharded_parallel,
                                             compose_sharding_spec,
                                             HybridParallelOptimizer)
from jax.sharding import PartitionSpec as P


def _mk_model(seed=0):
    np.random.seed(seed)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    return m


def _train_steps(model, optim, n=3, seed=1):
    rng = np.random.RandomState(seed)
    losses = []
    for i in range(n):
        x = Tensor(jnp.asarray(rng.randn(4, 16).astype(np.float32)))
        y = model(x)
        loss = (y * y).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss))
    return losses


def _weights(model):
    return {k: np.asarray(v._data) for k, v in model.state_dict().items()}


def test_compose_spec():
    assert compose_sharding_spec(P(), (8, 4), "sharding", 2) == \
        P("sharding", None)
    assert compose_sharding_spec(P("mp"), (8, 4), "sharding", 2) == \
        P("mp", "sharding")
    # already on the axis: unchanged
    assert compose_sharding_spec(P("sharding"), (8,), "sharding", 2) == \
        P("sharding")
    # indivisible dims skipped
    assert compose_sharding_spec(P(), (3, 4), "sharding", 2) == P(None, "sharding")


def test_stage1_matches_dense():
    ref_model = _mk_model()
    ref_w = _weights(ref_model)
    ref_opt = opt.AdamW(learning_rate=1e-2, parameters=ref_model.parameters())
    ref_losses = _train_steps(ref_model, ref_opt)

    model = _mk_model()
    for k, v in model.state_dict().items():
        v._data = jnp.asarray(ref_w[k])
    mesh = build_hybrid_mesh(dp_degree=4, sharding_degree=2)
    with mesh_context(mesh):
        base = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
        sopt = DygraphShardingOptimizer(base)
        losses = _train_steps(model, sopt)
        # accumulator really carries the sharding axis
        p0 = model[0].weight
        acc = base._accumulators["moment1"][id(p0)]
        spec = acc.sharding.spec
        assert any("sharding" in (e if isinstance(e, tuple) else (e,))
                   for e in spec if e is not None), spec
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    for k, v in _weights(model).items():
        np.testing.assert_allclose(v, _weights(ref_model)[k], rtol=1e-4,
                                   atol=1e-5)


def test_stage2_and_3_match_dense():
    ref_model = _mk_model()
    ref_w = _weights(ref_model)
    ref_opt = opt.AdamW(learning_rate=1e-2,
                        parameters=ref_model.parameters())
    ref_losses = _train_steps(ref_model, ref_opt)

    for level in ("os_g", "p_g_os"):
        model = _mk_model()
        for k, v in model.state_dict().items():
            v._data = jnp.asarray(ref_w[k])
        mesh = build_hybrid_mesh(dp_degree=4, sharding_degree=2)
        with mesh_context(mesh):
            base = opt.AdamW(learning_rate=1e-2,
                             parameters=model.parameters())
            model2, sopt, _ = group_sharded_parallel(model, base, level)
            losses = _train_steps(model2, sopt)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-5), level
        for k, v in _weights(model2).items():
            np.testing.assert_allclose(v, _weights(ref_model)[k], rtol=1e-4,
                                       atol=1e-5)


def test_hybrid_parallel_optimizer_delegates():
    model = _mk_model()
    base = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    hopt = HybridParallelOptimizer(base)
    losses = _train_steps(model, hopt, n=2)
    assert all(np.isfinite(losses))
    assert hopt.get_lr() == base.get_lr()


def test_save_group_sharded_model(tmp_path):
    from paddle_tpu.distributed.sharding import save_group_sharded_model
    model = _mk_model()
    base = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    _train_steps(model, base, n=1)
    save_group_sharded_model(model, str(tmp_path), base)
    import os
    assert os.path.exists(os.path.join(str(tmp_path), "model.pdparams"))
    assert os.path.exists(os.path.join(str(tmp_path), "model.pdopt"))
