"""Fleet SLO autopilot (ISSUE 18): the two-scope feedback controller.

Covers the `EngineController` actuators (chunk up/down with hysteresis
and cooldown, spec-k cut-to-off, prefix-admission gating, graduated
shedding), `ServingEngine.reconfigure` greedy-exactness + single-entry
program caches, the `shed` terminal trace outcome (distinct from
`refused`/`overloaded`, carried into chrome export and fleet
stitching), the readmit/poll_elastic cold-stats warmup weights
(dogpile regression), the `FleetController` (weight rebalance, role
flips through the PR-15 drain path, capacity-loss guard), seeded
convergence properties (settles, bounded flips, cooldown honored), and
the scenario-level acceptance: controller-on meets the declared
step-indexed SLO targets that the static config provably misses, plus
a combined replica-kill + thrash chaos soak with zero request loss."""

import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu import resilience as res
from paddle_tpu.observability import fleet as fleet_mod
from paddle_tpu.observability import tracing as tracing_mod
from paddle_tpu.serving import (EngineController, FleetController,
                                FleetRouter, ServingEngine, SLOTargets)
from paddle_tpu.serving import workloads
from paddle_tpu.serving.scheduler import Request, Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _obs_on():
    pm, pt = obs.enabled(), tracing_mod.enabled()
    obs.set_enabled(True)
    tracing_mod.set_enabled(True)
    yield
    obs.set_enabled(pm)
    tracing_mod.set_enabled(pt)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    cfg = llama_tiny_config(num_hidden_layers=1)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    base = dict(max_slots=2, page_size=4, prefill_chunk=4)
    base.update(kw)
    return ServingEngine(model, **base)


def _queue(eng, n, start=0):
    """Park `n` real requests in the admission queue (controller
    sensors read len(waiting); no device work is run)."""
    for i in range(start, start + n):
        eng.scheduler.submit(Request(np.arange(1, 5, dtype=np.int32), 2,
                                     request_id=f"q{i}"))


def _run(eng, prompt, max_new=4, rid="r0"):
    eng.add_request(prompt, max_new, request_id=rid)
    while eng.has_work():
        eng.step()
    return eng.collect()[rid]


# ---------------------------------------------------------------------------
# SLOTargets
# ---------------------------------------------------------------------------

class TestSLOTargets:
    def test_as_row_drops_none_and_sorts(self):
        t = SLOTargets(ttft_p90_steps=8, e2e_p90_ms=None)
        row = t.as_row()
        assert "e2e_p90_ms" not in row and "ttft_p90_ms" not in row
        assert row["ttft_p90_steps"] == 8
        assert row["queue_depth"] == 4 and row["shed_priority"] == 0
        assert list(row) == sorted(row)

    def test_shed_disabled_by_none(self, model):
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=1,
                                               shed_priority=None),
                               patience=1, cooldown=1)
        _queue(eng, 6)
        for _ in range(10):
            ctl.on_step()
        assert ctl.shed_level == 0 and ctl.flips["shed"] == 0


# ---------------------------------------------------------------------------
# EngineController actuators (no device stepping: sensors are counts)
# ---------------------------------------------------------------------------

class TestEngineController:
    def test_chunk_escalates_then_releases(self, model):
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=2),
                               patience=1, cooldown=1)
        _queue(eng, 5)
        for _ in range(6):
            ctl.on_step()
        assert eng.prefill_chunk == ctl.max_chunk == 16
        assert eng.rebuilds >= 2
        ups = [d for d in ctl.decisions if d["actuator"] == "prefill_chunk"
               and d["direction"] == "up"]
        assert ups and all("queue_depth" in d for d in ups)
        eng.scheduler.waiting.clear()
        for _ in range(12):
            ctl.on_step()
        assert eng.prefill_chunk == ctl.base_chunk == 4
        assert any(d["direction"] == "down" for d in ctl.decisions
                   if d["actuator"] == "prefill_chunk")

    def test_steady_pressure_bounds_flips(self, model):
        """Convergence: a constant overload moves the chunk actuator a
        bounded number of times (4 -> 8 -> 16, then it holds)."""
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=2))
        _queue(eng, 8)
        for _ in range(60):
            ctl.on_step()
        assert eng.prefill_chunk == 16
        assert ctl.flips["prefill_chunk"] == 2
        assert ctl.flips["shed"] <= 2      # escalated and then held

    def test_cooldown_spacing_honored(self, model):
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=1),
                               patience=1, cooldown=5)
        _queue(eng, 6)
        for _ in range(20):
            ctl.on_step()
        moves = [d["step"] for d in ctl.decisions
                 if d["actuator"] == "prefill_chunk"]
        assert moves
        assert all(b - a >= 5 for a, b in zip(moves, moves[1:]))

    def test_frozen_actuator_never_moves(self, model):
        """Runbook override: freezing an actuator pins it."""
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=1),
                               patience=1, cooldown=1)
        ctl.frozen.add("prefill_chunk")
        _queue(eng, 6)
        for _ in range(10):
            ctl.on_step()
        assert eng.prefill_chunk == 4
        assert ctl.flips["prefill_chunk"] == 0

    def test_guard_pressures_without_queue(self, model):
        """FleetController capacity-loss guard: pressure with an EMPTY
        queue (the pre-emptive tightening after a drain)."""
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=4),
                               patience=1, cooldown=1)
        ctl.guard(4)
        for _ in range(3):
            ctl.on_step()
        assert eng.prefill_chunk > 4
        assert ctl.flips["prefill_chunk"] >= 1

    def test_shed_escalates_to_refusal_and_releases(self, model):
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(queue_depth=1,
                                               shed_priority=0),
                               patience=1, cooldown=1)
        _queue(eng, 6)
        for _ in range(8):
            ctl.on_step()
        assert ctl.shed_level == 2
        assert eng.scheduler.shed_below_priority == 0
        with pytest.raises(res.Shed):
            eng.add_request(np.arange(1, 5, dtype=np.int32), 2,
                            request_id="victim", priority=-1)
        # priority >= floor still admits while shedding
        eng.add_request(np.arange(1, 5, dtype=np.int32), 2,
                        request_id="vip", priority=1)
        eng.scheduler.waiting.clear()
        for _ in range(12):
            ctl.on_step()
        assert ctl.shed_level == 0
        assert eng.scheduler.shed_below_priority is None
        assert eng.scheduler.queue_timeout_s == ctl._base_timeout

    def test_spec_k_cuts_to_off_and_never_rearms(self, model):
        eng = _engine(model, spec_decode=2)
        ctl = EngineController(eng, SLOTargets(spec_accept=0.9),
                               patience=1, cooldown=1, min_spec_sample=4)
        eng.spec_drafted, eng.spec_accepted = 10, 1   # 10% acceptance
        ctl.on_step()
        assert eng.spec_k == 1
        eng.spec_drafted += 10
        ctl.on_step()
        assert eng.spec_k == 0
        for _ in range(10):                            # never auto re-raises
            ctl.on_step()
        assert eng.spec_k == 0 and ctl.flips["spec_k"] == 2
        cut = [d for d in ctl.decisions if d["actuator"] == "spec_k"]
        assert all(d["direction"] == "down" for d in cut)
        assert cut[0]["accept_rate"] == 0.1
        # the runbook re-arm path: an operator reconfigure
        assert eng.reconfigure(spec_decode=2) is True
        assert eng.spec_k == 2

    def test_prefix_admission_hysteresis(self, model):
        eng = _engine(model)
        ctl = EngineController(eng, SLOTargets(pool_high=0.5,
                                               pool_low=0.2),
                               patience=1, cooldown=1)
        stats = {"utilization": 0.0}
        eng.allocator.stats = lambda: stats         # sensor stub
        stats["utilization"] = 0.9
        ctl.on_step()
        assert eng.prefix_cache_admit is False
        stats["utilization"] = 0.4                  # inside the band
        ctl.on_step()
        assert eng.prefix_cache_admit is False      # hysteresis holds
        stats["utilization"] = 0.1
        ctl.on_step()
        assert eng.prefix_cache_admit is True
        assert ctl.flips["prefix_admit"] == 2

    def test_decisions_traced_with_measurement(self, model):
        tracing_mod.recorder().clear()
        eng = _engine(model, replica="r0")
        ctl = EngineController(eng, SLOTargets(queue_depth=1),
                               patience=1, cooldown=1)
        _queue(eng, 4)
        ctl.on_step()
        ctls = [t for t in tracing_mod.recorder().finished()
                if t.kind == "controller"]
        assert ctls
        tr = ctls[0]
        assert tr.outcome == "decision"
        last = tr.timeline()[-1].meta
        assert last["actuator"] == "prefill_chunk"
        assert last["queue_depth"] == 4
        assert "utilization" in last

    def test_convergence_property_seeded(self, model):
        """Seeded property: any ramp-then-drain load settles — bounded
        flips, chunk back at base, and every move outside cooldown."""
        eng = _engine(model)
        for seed in (0, 1, 2):
            rng = np.random.default_rng(seed)
            ctl = EngineController(eng, SLOTargets(queue_depth=3),
                                   patience=2, cooldown=4)
            eng.reconfigure(prefill_chunk=4)
            for step in range(80):
                depth = int(rng.integers(4, 9)) if step < 40 else 0
                eng.scheduler.waiting = [None] * depth
                ctl.on_step()
            eng.scheduler.waiting = []
            assert eng.prefill_chunk == 4, f"seed {seed} did not settle"
            assert sum(ctl.flips.values()) <= 10, f"seed {seed} oscillated"
            for a in ctl.ACTUATORS:
                moves = [d["step"] for d in ctl.decisions
                         if d["actuator"] == a]
                assert all(b - x >= 4 for x, b in zip(moves, moves[1:]))


# ---------------------------------------------------------------------------
# reconfigure: greedy-exact, single-entry program caches
# ---------------------------------------------------------------------------

class TestReconfigure:
    def test_outputs_exact_across_chunk_change(self, model):
        rng = np.random.RandomState(3)
        prompt = rng.randint(1, model.config.vocab_size, 10).astype(np.int32)
        ref = _run(_engine(model), prompt)
        eng = _engine(model)
        assert eng.reconfigure(prefill_chunk=8) is True
        assert eng.rebuilds == 1
        np.testing.assert_array_equal(_run(eng, prompt), ref)
        assert all(v <= 1 for v in eng.program_cache_sizes().values())

    def test_noop_reconfigure_skips_rebuild(self, model):
        eng = _engine(model)
        assert eng.reconfigure(prefill_chunk=4) is False
        assert eng.reconfigure() is False
        assert eng.rebuilds == 0

    def test_rebuild_midstream_keeps_decode_exact(self, model):
        rng = np.random.RandomState(4)
        prompt = rng.randint(1, model.config.vocab_size, 8).astype(np.int32)
        ref = _run(_engine(model), prompt, max_new=6)
        eng = _engine(model)
        eng.add_request(prompt, 6, request_id="r0")
        for _ in range(3):
            eng.step()
        eng.reconfigure(prefill_chunk=8)     # mid-request, pages intact
        while eng.has_work():
            eng.step()
        np.testing.assert_array_equal(eng.collect()["r0"], ref)


# ---------------------------------------------------------------------------
# the `shed` terminal outcome (satellite 2)
# ---------------------------------------------------------------------------

class TestShedOutcome:
    def test_shed_distinct_from_refused_with_measurement(self):
        tracing_mod.recorder().clear()
        sched = Scheduler(1, max_inflight=1)
        sched.submit(Request(np.arange(1, 4, dtype=np.int32), 2,
                             request_id="ok"))
        with pytest.raises(res.Overloaded) as over:
            sched.submit(Request(np.arange(1, 4, dtype=np.int32), 2,
                                 request_id="full"))
        assert not isinstance(over.value, res.Shed)
        sched.shed_below_priority = 0
        sched.shed_measurement = {"queue_depth": 7, "utilization": 0.9}
        with pytest.raises(res.Shed) as shed:
            sched.submit(Request(np.arange(1, 4, dtype=np.int32), 2,
                                 request_id="victim", priority=-1))
        assert shed.value.measurement["queue_depth"] == 7
        fins = {t.request_id: t
                for t in tracing_mod.recorder().finished()}
        assert fins["full"].outcome == "refused"
        assert fins["victim"].outcome == "shed"
        meta = fins["victim"].timeline()[-1].meta
        assert meta["priority"] == -1 and meta["floor"] == 0
        assert meta["queue_depth"] == 7     # the triggering measurement

    def test_shed_rides_chrome_export_and_fleet_stitch(
            self, model, tmp_path):
        tracing_mod.recorder().clear()
        eng = _engine(model, replica="r0")
        eng.scheduler.shed_below_priority = 0
        before = obs.snapshot()["serving.engine.requests"]
        with pytest.raises(res.Shed):
            eng.add_request(np.arange(1, 5, dtype=np.int32), 2,
                            request_id="shed-1", priority=-1)
        # the engine counter grows a distinct outcome label value
        series = {tuple(sorted(s["labels"].items())): s["value"]
                  for s in obs.snapshot()["serving.engine.requests"]
                  ["series"]}
        old = {tuple(sorted(s["labels"].items())): s["value"]
               for s in before["series"]}
        key = (("outcome", "shed"),)
        assert series[key] == old.get(key, 0) + 1
        p1 = str(tmp_path / "solo.json")
        tracing_mod.recorder().export_chrome_trace(p1)
        assert any(e.get("args", {}).get("outcome") == "shed"
                   for e in json.load(open(p1))["traceEvents"])
        p2 = str(tmp_path / "fleet.json")
        fleet_mod.stitch_chrome_trace(p2)
        assert any(e.get("args", {}).get("outcome") == "shed"
                   for e in json.load(open(p2))["traceEvents"])


# ---------------------------------------------------------------------------
# readmit / poll_elastic cold-stats warmup weights (satellite 1)
# ---------------------------------------------------------------------------

class TestReadmitWeights:
    def _router(self, model, n=2):
        engines = {f"r{i}": _engine(model, replica=f"r{i}")
                   for i in range(n)}
        return FleetRouter(engines), engines

    def test_readmit_seeds_weight_from_last_scrape(self, model):
        router, engines = self._router(model)
        for i in range(3):
            engines["r0"].add_request(np.arange(1, 6, dtype=np.int32), 2,
                                      request_id=f"w{i}")
        router.scrape()                       # federated view cached
        router.drain("r0")
        router.readmit("r0")
        # the busier it went down, the deeper the discount
        assert router.placement_weight["r0"] == \
            pytest.approx(router.readmit_warmup / (1.0 + 3))
        assert router.placement_weight["r1"] == 1.0

    def test_readmit_without_scrape_uses_default_warmup(self, model):
        router, _ = self._router(model)
        router.drain("r1")
        router.readmit("r1")
        assert router.placement_weight["r1"] == router.readmit_warmup

    def test_cold_weight_charges_phantom_load(self, model):
        """The dogpile regression: an empty just-readmitted replica must
        NOT outscore a warm one — the warmup weight charges phantom
        queue load until the ramp restores it."""
        router, engines = self._router(model)
        prompt = np.arange(1, 6, dtype=np.int32)
        router.placement_weight["r0"] = 0.5
        cold, _ = router._score(engines["r0"], prompt)
        warm, _ = router._score(engines["r1"], prompt)
        assert cold < warm
        phantom = router.queue_cost_tokens * 0.5 * router.warmup_load
        assert warm - cold == pytest.approx(phantom)

    def test_weight_ramps_back_per_step(self, model):
        router, _ = self._router(model)
        router.drain("r0")
        router.readmit("r0")
        w0 = router.placement_weight["r0"]
        assert w0 < 1.0
        router.step()
        assert router.placement_weight["r0"] == \
            pytest.approx(min(1.0, w0 + router.weight_recovery))
        for _ in range(6):
            router.step()
        assert router.placement_weight["r0"] == 1.0

    def test_poll_elastic_readmit_is_warmup_seeded(self, model):
        class FlappingElastic:
            def __init__(self):
                self.alive = [0, 1]

            def alive_nodes(self, n):
                return self.alive

        engines = {f"r{i}": _engine(model, replica=f"r{i}")
                   for i in range(2)}
        el = FlappingElastic()
        router = FleetRouter(engines, elastic=el)
        el.alive = [1]
        router.poll_elastic()
        assert router.live_replicas() == ["r1"]
        el.alive = [0, 1]
        router.poll_elastic()
        assert router.live_replicas() == ["r0", "r1"]
        assert router.placement_weight["r0"] == router.readmit_warmup


# ---------------------------------------------------------------------------
# FleetController: rebalance, role shifts, capacity guard
# ---------------------------------------------------------------------------

class TestFleetController:
    def test_rebalance_discounts_hot_replica(self, model):
        engines = {f"r{i}": _engine(model, max_slots=1, replica=f"r{i}")
                   for i in range(3)}
        router = FleetRouter(engines)
        fc = FleetController(router, SLOTargets(), interval=1)
        for i in range(8):
            engines["r0"].scheduler.submit(
                Request(np.arange(1, 5, dtype=np.int32), 2,
                        request_id=f"h{i}"))
        fc.on_step()
        assert router.placement_weight["r0"] == 0.5
        assert router.placement_weight["r1"] == 1.0
        assert fc.flips["weight"] == 1
        d = [d for d in fc.decisions if d["action"] == "rebalance"][0]
        assert d["replica"] == "r0" and d["load"] == 8

    def test_role_flip_on_handoff_backlog_never_last(self, model):
        engines = {"pf0": _engine(model, role="prefill", replica="pf0"),
                   "pf1": _engine(model, role="prefill", replica="pf1"),
                   "dec0": _engine(model, role="decode", replica="dec0")}
        router = FleetRouter(engines)
        fc = FleetController(router, SLOTargets(), interval=1,
                             handoff_backlog=2, role_patience=2)
        router._pending.extend([object(), object()])   # standing backlog
        fc.on_step()
        assert fc.flips["role"] == 0                    # patience not met
        fc.on_step()
        assert fc.flips["role"] == 1
        roles = sorted(e.role for e in engines.values())
        assert roles == ["decode", "decode", "prefill"]
        router._pending.clear()
        # with one prefill replica left, a backlog can never flip it
        router._pending.extend([object(), object()])
        for _ in range(6):
            fc.on_step()
        assert sum(e.role == "prefill" for e in engines.values()) == 1

    def test_capacity_loss_guards_survivors(self, model):
        slo = SLOTargets(queue_depth=4)
        engines = {f"r{i}": _engine(model, replica=f"r{i}",
                                    slo_targets=slo)
                   for i in range(2)}
        router = FleetRouter(engines)
        fc = FleetController(router, slo, guard_steps=6)
        assert router.controller is fc
        router.drain("r0")
        assert fc.flips["guard"] == 1
        assert engines["r1"].controller._guard == 6
        d = [d for d in fc.decisions if d["action"] == "capacity_guard"][0]
        assert d["lost"] == "r0" and d["survivors"] == 1
        # role repurposing is NOT a capacity loss: no second guard
        router.readmit("r0")
        router.set_role("r0", "prefill")
        assert fc.flips["guard"] == 1


# ---------------------------------------------------------------------------
# scenario-level acceptance: autopilot meets what static misses
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def burst_pair(model):
    obs.set_enabled(True)
    tracing_mod.set_enabled(True)
    return (workloads.run_scenario("burst", model),
            workloads.run_scenario("burst", model, autopilot=True))


@pytest.fixture(scope="module")
def thrash_pair(model):
    obs.set_enabled(True)
    tracing_mod.set_enabled(True)
    return (workloads.run_scenario("thrash", model),
            workloads.run_scenario("thrash", model, autopilot=True))


def _meets(row, field):
    return row[field] <= row["slo"][field]


class TestAutopilotAcceptance:
    def test_burst_on_meets_targets_static_misses(self, burst_pair):
        off, on = burst_pair
        for f in ("ttft_p90_steps", "e2e_p90_steps"):
            assert _meets(on, f), (f, on[f], on["slo"][f])
        assert not all(_meets(off, f)
                       for f in ("ttft_p90_steps", "e2e_p90_steps"))
        # the control loop never costs correctness or availability
        assert on["output_checksum"] == off["output_checksum"]
        assert on["zero_loss"] == off["zero_loss"] == 1
        assert on["shed"] == 0

    def test_thrash_on_meets_targets_static_misses(self, thrash_pair):
        off, on = thrash_pair
        for f in ("ttft_p90_steps", "e2e_p90_steps"):
            assert _meets(on, f), (f, on[f], on["slo"][f])
        assert not all(_meets(off, f)
                       for f in ("ttft_p90_steps", "e2e_p90_steps"))
        assert on["output_checksum"] == off["output_checksum"]
        assert on["zero_loss"] == off["zero_loss"] == 1

    def test_autopilot_row_replays_bit_exactly(self, model, burst_pair):
        """The determinism contract behind the committed _autopilot
        rows: controller sensors are counts, never clocks."""
        _, on = burst_pair
        again = workloads.run_scenario("burst", model, autopilot=True)
        for f in workloads.ROW_DETERMINISTIC:
            assert again[f] == on[f], f
        assert again["autopilot"] == 1
        assert again["scenario"] == "burst_autopilot"

    def test_replica_kill_autopilot_zero_loss_and_recovery(self, model):
        row = workloads.run_scenario("replica_kill", model,
                                     autopilot=True)
        assert row["zero_loss"] == 1
        assert row["completed"] == row["requests"]
        assert row["handoffs"] > row["requests"]    # the drain re-export
        for f in ("ttft_p90_steps", "e2e_p90_steps"):
            assert _meets(row, f), (f, row[f], row["slo"][f])

    def test_chaos_soak_thrash_plus_replica_kill(self, model):
        """Soak: the thrash adversary AND a mid-run replica kill with
        both controller scopes live — zero accepted-request loss, the
        fleet converges back to idle, and the capacity guard fired."""
        slo = SLOTargets(queue_depth=3, pool_high=0.7, pool_low=0.4)
        engines = {
            "pf0": _engine(model, role="prefill", replica="pf0",
                           slo_targets=slo),
            "dec0": _engine(model, role="decode", replica="dec0",
                            slo_targets=slo),
            "dec1": _engine(model, role="decode", replica="dec1",
                            slo_targets=slo),
        }
        router = FleetRouter(engines)
        fc = FleetController(router, slo)
        rng = np.random.default_rng(12)
        V = model.config.vocab_size
        shared = rng.integers(1, V, 8).astype(np.int32)
        submitted = []
        for step in range(10):
            if step < 4:   # good tenant: shared prefix
                rid = f"good{step}"
                router.submit(np.concatenate(
                    [shared, rng.integers(1, V, 2).astype(np.int32)]),
                    3, request_id=rid, tenant="good")
                submitted.append(rid)
            if step < 6:   # adversary: never-repeating prompts
                rid = f"evil{step}"
                router.submit(rng.integers(1, V, 12).astype(np.int32),
                              2, request_id=rid, tenant="adversary")
                submitted.append(rid)
            if step == 5:
                router.drain("dec0")
            if step == 8:
                router.readmit("dec0")
            router.step()
        results = router.run_to_completion()
        assert sorted(results) == sorted(submitted)   # zero request loss
        assert all(len(v) > 0 for v in results.values())
        assert fc.flips["guard"] >= 1                  # drain was guarded
        assert not router.has_work()                   # converged to idle
        summary = router.step_slo_summary()
        assert summary["e2e_p90_steps"] is not None


# ---------------------------------------------------------------------------
# bench-row plumbing for the autopilot artifacts
# ---------------------------------------------------------------------------

class TestArtifactPlumbing:
    def test_rows_declare_their_slo_targets(self, burst_pair):
        off, on = burst_pair
        for row in (off, on):
            assert row["slo"]["ttft_p90_steps"] == 12
            assert row["slo"]["e2e_p90_steps"] == 18
        assert off["autopilot"] == 0 and on["autopilot"] == 1

    def test_committed_artifact_has_paired_autopilot_rows(self):
        with open(os.path.join(REPO, "docs", "FLEET_BENCH.json")) as f:
            art = json.load(f)
        for name in workloads.SCENARIOS:
            assert name in art["scenarios"]
            ap = art["scenarios"].get(f"{name}_autopilot")
            assert ap is not None, f"{name}_autopilot row missing"
            assert ap["autopilot"] == 1
            assert ap["shed"] == 0
            assert ap["zero_loss"] == 1
            # paired rows ran the same traffic: greedy-exact outputs
            assert ap["output_checksum"] == \
                art["scenarios"][name]["output_checksum"]

    def test_perf_gate_bands_cover_autopilot_rows(self):
        import perf_gate
        rows = {r["key"]: r for r in perf_gate.fleet_rows(REPO)}
        for name in workloads.SCENARIOS:
            for f in ("ttft_p90_steps", "e2e_p90_steps", "shed"):
                key = f"fleet.{name}_autopilot.{f}"
                assert key in rows, key
                assert rows[key]["direction"] == "both"
                assert rows[key]["band"][0] == rows[key]["band"][1]
        assert rows["fleet.burst_autopilot.ttft_p99_ms"]["direction"] \
            == "lower"
        assert rows["fleet.burst_autopilot.e2e_p99_ms"]["direction"] \
            == "lower"
