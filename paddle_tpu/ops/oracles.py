"""XLA reference-oracle registry — the kernel certification contract
(ROADMAP item 5, enforced statically by paddlelint rule PK105).

Every authored Pallas kernel registers, *in its own module*, the triple
that certifies it:

    register_oracle(
        "fused_rms_norm",
        kernel=fused_rms_norm,                       # public entry point
        reference="paddle_tpu.ops.references:rms_norm_reference",
        parity_test="tests/test_fused_ops.py::TestRmsNorm")

- ``kernel`` is the public callable whose call graph reaches the
  ``pallas_call`` site(s) — PK105 resolves this statically, so it must
  be a name defined or imported in the registering module.
- ``reference`` is a plain-XLA implementation with the same signature,
  either a callable or a lazy ``"module:attr"`` string (lazy strings
  break import cycles: ``flash_attention.sdpa_reference`` is the oracle
  for ``pallas_flash.flash_sdpa``, but ``flash_attention`` imports
  ``pallas_flash``).
- ``parity_test`` names the pytest node that pins kernel == reference in
  interpret mode; ``tests/test_oracles.py`` asserts the node exists and
  re-runs parity for registered examples.

The registry is intentionally dumb — a dict, no framework imports — so
both the runtime parity tests and the static analyzer agree on the same
source of truth.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Callable, Dict, Optional, Union

__all__ = ["OracleEntry", "register_oracle", "oracles",
           "resolve_reference"]


@dataclasses.dataclass(frozen=True)
class OracleEntry:
    name: str
    kernel: Callable
    reference: Union[Callable, str]     # callable or lazy "module:attr"
    parity_test: str                    # pytest node id (file::name)


_REGISTRY: Dict[str, OracleEntry] = {}


def register_oracle(name: str, kernel: Callable,
                    reference: Union[Callable, str], *,
                    parity_test: str) -> OracleEntry:
    entry = OracleEntry(name=name, kernel=kernel, reference=reference,
                        parity_test=parity_test)
    _REGISTRY[name] = entry
    return entry


def resolve_reference(entry: OracleEntry) -> Callable:
    ref = entry.reference
    if isinstance(ref, str):
        modname, attr = ref.split(":")
        return getattr(importlib.import_module(modname), attr)
    return ref


def oracles() -> Dict[str, OracleEntry]:
    return dict(_REGISTRY)
