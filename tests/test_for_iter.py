"""for-over-iterable capture + per-site nonlocal containment (VERDICT r4
item 4; ref: python/paddle/jit/dy2static/convert_operators.py
convert_for_iter / convert_enumerate / convert_zip). Concrete iterables
keep exact python semantics; tensor components lower to a bounded
differentiable scan over the static leading axis."""

import numpy as np
import pytest

import paddle_tpu as paddle


class TestForIterConcrete:
    def test_list_iteration_unchanged(self):
        def f(x):
            out = x
            for w in [1.0, 2.0, 3.0]:
                out = out * w
            return out

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([1.0])).sum()) == 6.0

    def test_dict_items_tuple_target(self):
        def f(x):
            acc = x * 0.0
            for k, v in {"a": 1.0, "b": 2.0}.items():
                acc = acc + v
            return acc

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([0.0])).sum()) == 3.0

    def test_generator_consumed_exactly(self):
        def f(x):
            acc = x * 0.0
            for v in (i * 10.0 for i in range(3)):
                acc = acc + v
            return acc

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([0.0])).sum()) == 30.0

    def test_enumerate_list_with_start(self):
        def f(x):
            acc = x * 0.0
            for i, v in enumerate([5.0, 7.0], start=2):
                acc = acc + v * float(i)
            return acc

        sf = paddle.jit.to_static(f)
        # 2*5 + 3*7 = 31
        assert float(sf(paddle.to_tensor([0.0])).sum()) == 31.0

    def test_zip_lists(self):
        def f(x):
            acc = x * 0.0
            for a, b in zip([1.0, 2.0], [10.0, 20.0, 30.0]):
                acc = acc + a * b
            return acc

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([0.0])).sum()) == 50.0

    def test_shadowed_zip_stays_python(self):
        def f(x):
            def zip(a, b):  # noqa: A001 - deliberate shadow
                return [(a[0], b[1])]
            acc = x * 0.0
            for p, q in zip([1.0, 2.0], [10.0, 20.0]):
                acc = acc + p * q
            return acc

        sf = paddle.jit.to_static(f)
        assert float(sf(paddle.to_tensor([0.0])).sum()) == 20.0


class TestForIterConcreteNested:
    def test_enumerate_of_zip_of_tensors(self):
        # enumerate(zip(t, u)): the zip OBJECT is the enumerate component
        # (not a Tensor), so the concrete path iterates it — which under
        # trace unrolls through Tensor.__iter__ over the static leading
        # axis. Exact python semantics either way.
        def f(a, b):
            acc = paddle.to_tensor(0.0)
            for i, (u, v) in enumerate(zip(a, b)):
                acc = acc + u * v + i
            return acc

        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        b = paddle.to_tensor(np.array([10.0, 20.0], np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(a, b)) == float(f(a, b)) == 51.0


class TestForIterTensor:
    def test_tensor_iteration_parity(self):
        def f(t, x):
            acc = x
            for row in t:
                acc = acc + row.sum()
            return acc

        t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
        x = paddle.to_tensor(0.0)
        sf = paddle.jit.to_static(f)
        assert float(sf(t, x)) == pytest.approx(float(f(t, x)))

    def test_tensor_iteration_is_scanned_not_unrolled(self):
        # the loop must lower to ONE scan/while region: a 1000-row tensor
        # would produce a pathological jaxpr if the body were unrolled
        def f(t):
            acc = paddle.to_tensor(0.0)
            for row in t:
                acc = acc + row.sum()
            return acc

        t = paddle.to_tensor(np.ones((1000, 2), np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(t)) == 2000.0

    def test_enumerate_tensor(self):
        def f(t):
            acc = paddle.to_tensor(0.0)
            for i, row in enumerate(t, 1):
                acc = acc + row.sum() * i
            return acc

        t = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
        sf = paddle.jit.to_static(f)
        # 1*1 + 2*2 + 3*3 = 14
        assert float(sf(t)) == pytest.approx(14.0)
        assert float(f(t)) == pytest.approx(14.0)

    def test_zip_tensors_min_length(self):
        def f(a, b):
            acc = paddle.to_tensor(0.0)
            for u, v in zip(a, b):
                acc = acc + u * v
            return acc

        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        b = paddle.to_tensor(np.array([10.0, 20.0, 30.0], np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(a, b)) == 50.0
        assert float(f(a, b)) == 50.0

    def test_plain_row_unpack(self):
        def f(pairs):
            acc = paddle.to_tensor(0.0)
            for a, b in pairs:
                acc = acc + a * b
            return acc

        pairs = paddle.to_tensor(
            np.array([[1.0, 10.0], [2.0, 20.0]], np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(pairs)) == 50.0

    def test_inner_tensor_if_inside_for_iter(self):
        def f(t):
            acc = paddle.to_tensor(0.0)
            for row in t:
                if row.sum() > 2.0:
                    acc = acc + row.sum()
                else:
                    acc = acc - 1.0
            return acc

        t = paddle.to_tensor(np.array([[1.0], [5.0], [3.0]], np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(t)) == pytest.approx(float(f(t)))

    def test_zero_length_tensor(self):
        def f(t, x):
            acc = x
            for row in t:
                acc = acc + row.sum()
            return acc

        t = paddle.to_tensor(np.zeros((0, 3), np.float32))
        x = paddle.to_tensor(7.0)
        sf = paddle.jit.to_static(f)
        assert float(sf(t, x)) == 7.0

    def test_target_value_after_loop(self):
        def f(t):
            last = t[0] * 0.0
            for row in t:
                pass
            return row + last  # noqa: F821 - bound by the loop

        t = paddle.to_tensor(np.array([[1.0], [9.0]], np.float32))
        sf = paddle.jit.to_static(f)
        assert float(sf(t).sum()) == 9.0

    def test_mixed_zip_tensor_list_raises(self):
        def f(t):
            acc = paddle.to_tensor(0.0)
            for u, v in zip(t, [1.0, 2.0]):
                acc = acc + u * v
            return acc

        t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        sf = paddle.jit.to_static(f)
        with pytest.raises(TypeError, match="every zip/enumerate component"):
            sf(t)

    def test_gradient_through_tensor_loop(self):
        lin = paddle.nn.Linear(2, 2)

        def loss_fn(t):
            acc = paddle.to_tensor(0.0)
            for row in t:
                y = lin(row)
                acc = acc + (y * y).sum()
            loss = acc
            loss.backward()
            return loss

        t = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        sf = paddle.jit.to_static(loss_fn)
        sf(t)
        g_static = lin.weight.grad.numpy().copy()
        lin.weight._grad = None
        loss_fn(t)  # eager reference (concrete path, same seedless math)
        np.testing.assert_allclose(g_static, lin.weight.grad.numpy(),
                                   rtol=1e-4)


class TestNonlocalContainment:
    def test_clean_statement_converts_next_to_nonlocal(self):
        # the nested def writes `c` through a cell; the if threads only
        # `y` -> still converts (tensor predicate works under to_static)
        def f(x):
            c = [0]
            count = 0

            def bump():
                nonlocal count
                count += 1

            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            bump()
            c[0] = count
            return y + float(c[0])

        sf = paddle.jit.to_static(f)
        a = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(sf(a).numpy(), f(a).numpy(), rtol=1e-6)
        b = paddle.to_tensor(-np.ones((2,), np.float32))
        np.testing.assert_allclose(sf(b).numpy(), f(b).numpy(), rtol=1e-6)

    def test_contaminated_statement_falls_back_locally(self):
        # `count` is nonlocal-written AND assigned in the first branch:
        # that statement must stay python (cell mutation by bump() stays
        # visible); the second if threads only `y` and must convert.
        # Verified structurally (exactly ONE generated branch pair) and
        # semantically (eager parity including the cell mutation).
        import types as pytypes
        from paddle_tpu.jit import dy2static

        def f(x, flg):
            count = 0

            def bump():
                nonlocal count
                count += 1

            if flg:              # contaminated: threads `count`
                bump()
                count = count + 10
            if x.sum() > 0:      # clean: threads only `y`
                y = x * 2.0
            else:
                y = x - 1.0
            return y + float(count)

        cf = dy2static.convert(f)
        assert getattr(cf, "__pt_dy2static__", False)
        n_branch_fns = sum(
            1 for c in cf.__code__.co_consts
            if isinstance(c, pytypes.CodeType)
            and c.co_name.startswith("_pt_true_"))
        assert n_branch_fns == 1, \
            f"expected only the clean if converted, got {n_branch_fns}"
        a = paddle.to_tensor(np.ones((2,), np.float32))
        b = paddle.to_tensor(-np.ones((2,), np.float32))
        for t, flg in [(a, True), (a, False), (b, True)]:
            np.testing.assert_allclose(cf(t, flg).numpy(),
                                       f(t, flg).numpy(), rtol=1e-6)

    def test_contaminated_write_in_tail_folded_if(self):
        # review r5: the early-return fold filters written names to the
        # return variable; contamination must be judged BEFORE that
        # filter, or a cell write inside the folded branch converts and
        # binds a local instead of the cell
        from paddle_tpu.jit import dy2static

        def f(x):
            n = 0

            def get():
                nonlocal n
                return n

            if x.sum() > 0:
                n = 5
                return x * float(get())
            return x * float(get())

        cf = dy2static.convert(f)
        a = paddle.to_tensor(np.ones((2,), np.float32))
        assert float(cf(a).sum()) == float(f(a).sum()) == 10.0

    def test_shadowed_range_with_break_stays_python(self):
        # review r5: the break/continue desugar path must honor a local
        # `range` shadow like the plain path does
        from paddle_tpu.jit import dy2static

        def f(x):
            def range(n):  # noqa: A001 - deliberate shadow
                return [7.0]
            total = x * 0.0
            for i in range(3):
                total = total + i
                if float(total.sum()) > 100.0:
                    break
            return total

        cf = dy2static.convert(f)
        a = paddle.to_tensor(np.zeros((1,), np.float32))
        assert float(cf(a).sum()) == float(f(a).sum()) == 7.0

    def test_module_global_write_contained(self):
        def f(x):
            global _g_counter_for_test
            _g_counter_for_test = 0
            if x.sum() > 0:
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        sf = paddle.jit.to_static(f)
        a = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(sf(a).numpy(), f(a).numpy(), rtol=1e-6)
        assert _g_counter_for_test == 0
