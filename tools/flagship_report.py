"""Generate the measured data behind docs/FLAGSHIP.md.

AOT-compiles the flagship per-chip shard train step (the bench.py config)
on the local TPU and extracts XLA's memory_analysis() and cost_analysis()
— the HLO-derived HBM footprint and FLOP count that anchor the v5p-64
MFU projection. Writes docs/FLAGSHIP_data.json.

Usage: python tools/flagship_report.py [--batch 3] [--remat none]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=3)
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--remat", default="none")
    ap.add_argument("--mp", type=int, default=8)
    ap.add_argument("--pp", type=int, default=4)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.models.llama import (llama3_8b_config,
                                         llama3_8b_shard_config)
    from paddle_tpu.trainer.pretrain import (PretrainConfig,
                                             build_llama_pretrain_step,
                                             make_hybrid_mesh_for,
                                             flops_per_token)

    mc = llama3_8b_shard_config(mp=args.mp, pp=args.pp,
                                max_position_embeddings=args.seq,
                                sequence_parallel=False)
    cfg = PretrainConfig(mc, global_batch=args.batch, seq_len=args.seq,
                         n_microbatches=1, param_dtype="bfloat16",
                         scan_layers=False, remat=args.remat)
    mesh = make_hybrid_mesh_for(cfg, devices=jax.devices()[:1])
    state, train_step, meta = build_llama_pretrain_step(cfg, mesh)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, mc.vocab_size,
                                  (args.batch, args.seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, mc.vocab_size,
                                     (args.batch, args.seq)), jnp.int32)
    lowered = train_step.lower(state, ids, labels)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]

    n_shard_params = sum(
        int(np.prod(v.shape)) for grp in state.params.values()
        for v in grp.values())
    full = llama3_8b_config()
    full_fpt = flops_per_token(full)
    shard_fpt = flops_per_token(mc)
    gib = 1024 ** 3
    out = {
        "shard": {"mp": args.mp, "pp": args.pp, "batch": args.batch,
                  "seq": args.seq, "remat": args.remat,
                  "params": n_shard_params,
                  "flops_per_token_6N": shard_fpt},
        "full_8b": {"flops_per_token_6N": full_fpt},
        "memory_analysis_bytes": {
            "argument_size": getattr(mem, "argument_size_in_bytes", None),
            "output_size": getattr(mem, "output_size_in_bytes", None),
            "temp_size": getattr(mem, "temp_size_in_bytes", None),
            "generated_code_size": getattr(
                mem, "generated_code_size_in_bytes", None),
            "peak_estimate": (getattr(mem, "argument_size_in_bytes", 0)
                              + getattr(mem, "temp_size_in_bytes", 0)),
        },
        "cost_analysis": {
            "flops_per_step": cost.get("flops") if cost else None,
            "bytes_accessed": cost.get("bytes accessed") if cost else None,
        },
        "device": str(jax.devices()[0].device_kind),
    }
    out["memory_analysis_gib"] = {
        k: (round(v / gib, 3) if isinstance(v, (int, float)) else v)
        for k, v in out["memory_analysis_bytes"].items()}
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "FLAGSHIP_data.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
