"""Observability layer (ISSUE 1): metric semantics, exporter round-trips,
span/step-log correlation, the disabled-path overhead gate, and the
acceptance check that >=4 subsystems actually report into the default
registry (ops dispatch, collectives, trainer, serving)."""

import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import (Registry, StepLogger, parse_prometheus,
                                      sample_values, span, to_prometheus)


@pytest.fixture(autouse=True)
def _metrics_on():
    """Every test here assumes metrics are recording; restore on exit."""
    prev = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(prev)


# ---------------------------------------------------------------------------
# metric semantics
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_counter(self):
        r = Registry()
        c = r.counter("c_total", "help text")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge(self):
        r = Registry()
        g = r.gauge("g", "a gauge")
        g.set(4.0)
        g.inc()
        g.dec(2.0)
        assert g.value == 3.0

    def test_histogram_buckets(self):
        r = Registry()
        h = r.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count == 5
        assert h.sum == pytest.approx(56.05)
        flat = sample_values(r)
        # cumulative exposition: le=0.1 -> 1, le=1 -> 3, le=10 -> 4, +Inf -> 5
        assert flat['h_seconds_bucket{le="0.1"}'] == 1
        assert flat['h_seconds_bucket{le="1"}'] == 3
        assert flat['h_seconds_bucket{le="10"}'] == 4
        assert flat['h_seconds_bucket{le="+Inf"}'] == 5

    def test_histogram_timer(self):
        r = Registry()
        h = r.histogram("t_seconds")
        with h.time():
            time.sleep(0.002)
        assert h.count == 1
        assert h.sum >= 0.002

    def test_labels_vend_children(self):
        r = Registry()
        c = r.counter("ops_total", labels=("op",))
        c.labels(op="add").inc()
        c.labels(op="add").inc()
        c.labels(op="mul").inc()
        assert c.labels(op="add").value == 2
        assert c.labels(op="mul").value == 1
        with pytest.raises(ValueError):
            c.labels(notalabel="x")

    def test_get_or_create_and_mismatch(self):
        r = Registry()
        a = r.counter("same", "h")
        assert r.counter("same") is a
        with pytest.raises(ValueError):
            r.gauge("same")
        with pytest.raises(ValueError):
            r.counter("same", labels=("x",))

    def test_disabled_mutations_are_dropped(self):
        r = Registry()
        c = r.counter("off_total")
        h = r.histogram("off_seconds")
        obs.set_enabled(False)
        c.inc()
        h.observe(1.0)
        obs.set_enabled(True)
        assert c.value == 0 and h.count == 0

    def test_thread_safety(self):
        r = Registry()
        c = r.counter("mt_total", labels=("t",))
        u = r.counter("mt_unlabeled_total")

        def work(i):
            for _ in range(1000):
                c.labels(t=str(i % 2)).inc()
                u.inc()
        threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert u.value == 4000
        assert (c.labels(t="0").value + c.labels(t="1").value) == 4000

    def test_reset(self):
        r = Registry()
        c = r.counter("r_total", labels=("k",))
        c.labels(k="a").inc(5)
        r.reset()
        assert c.labels(k="a").value == 0


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def _populated_registry():
    r = Registry()
    r.counter("req_total", 'requests with "quotes" and \\ and\nnewline',
              labels=("path", "code")).labels(path="/v1", code="200").inc(7)
    g = r.gauge("temp", "a gauge")
    g.set(36.6)
    h = r.histogram("lat_seconds", "latency", labels=("route",),
                    buckets=(0.01, 0.1, 1.0))
    h.labels(route="a").observe(0.005)
    h.labels(route="a").observe(0.5)
    h.labels(route="b").observe(99.0)
    return r


class TestExporters:
    def test_prometheus_round_trip(self):
        r = _populated_registry()
        text = to_prometheus(r)
        assert "# TYPE req_total counter" in text
        assert "# TYPE lat_seconds histogram" in text
        assert parse_prometheus(text) == sample_values(r)

    def test_json_snapshot_round_trip(self):
        r = _populated_registry()
        snap = r.snapshot()
        # survives actual JSON serialization, not just dict equality
        snap2 = json.loads(json.dumps(snap))
        rebuilt = Registry.from_snapshot(snap2)
        assert rebuilt.snapshot() == snap
        assert sample_values(rebuilt) == sample_values(r)

    def test_prometheus_escaping(self):
        r = Registry()
        r.counter("e_total", labels=("v",)).labels(v='a"b\\c\nd').inc()
        flat = parse_prometheus(to_prometheus(r))
        assert flat == sample_values(r)


# ---------------------------------------------------------------------------
# spans + step log (chrome-trace correlation)
# ---------------------------------------------------------------------------

class TestStepLog:
    def test_span_ids_join_trace_and_jsonl(self, tmp_path):
        from paddle_tpu import native
        native.prof_clear()
        native.prof_enable(True)
        log_path = str(tmp_path / "steps.jsonl")
        with StepLogger(log_path) as sl:
            with span("train_step") as sp:
                sum(range(100))
            sl.log(step=1, span_id=sp.span_id, loss=0.5)
        native.prof_enable(False)
        trace = str(tmp_path / "trace.json")
        native.prof_export(trace)
        events = json.load(open(trace))["traceEvents"]
        names = [e["name"] for e in events]
        assert f"train_step[span={sp.span_id}]" in names
        rows = [json.loads(l) for l in open(log_path)]
        assert rows[0]["step"] == 1
        assert rows[0]["span_id"] == sp.span_id
        assert rows[0]["loss"] == 0.5
        assert isinstance(rows[0]["metrics"], dict)
        native.prof_clear()

    def test_step_log_snapshots_metrics(self, tmp_path):
        r = Registry()
        c = r.counter("steps_total")
        p = str(tmp_path / "s.jsonl")
        with StepLogger(p, reg=r) as sl:
            c.inc()
            sl.log(step=1)
            c.inc()
            sl.log(step=2)
        rows = [json.loads(l) for l in open(p)]
        assert rows[0]["metrics"]["steps_total"] == 1
        assert rows[1]["metrics"]["steps_total"] == 2


# ---------------------------------------------------------------------------
# subsystem population (acceptance: >=4 subsystems report in)
# ---------------------------------------------------------------------------

class TestSubsystems:
    def test_dispatch_and_collectives_and_serving_and_trainer(self, tmp_path):
        reg = obs.registry()

        # 1. ops dispatch: one eager add
        before = sample_values(reg).get('pt_ops_dispatch_total{op="add"}', 0)
        t = paddle.to_tensor(np.ones((2, 2), np.float32))
        _ = t + t
        flat = sample_values(reg)
        assert flat['pt_ops_dispatch_total{op="add"}'] == before + 1

        # 2. collectives: all_reduce (meshless degrades to identity but the
        #    call-level instrumentation still fires)
        from paddle_tpu.distributed import collective
        b4_calls = flat.get(
            'pt_collective_calls_total{collective="all_reduce"}', 0)
        collective.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
        flat = sample_values(reg)
        assert flat['pt_collective_calls_total{collective="all_reduce"}'] \
            == b4_calls + 1
        assert flat['pt_collective_bytes_total{collective="all_reduce"}'] > 0
        assert flat['pt_collective_seconds_count'
                    '{collective="all_reduce"}'] >= 1

        # 3. serving: paged decode attention samples KV-page utilization and
        #    counts the routed kernel
        from paddle_tpu.ops.paged_attention import paged_attention
        q = np.random.RandomState(0).randn(2, 2, 8).astype(np.float32)
        kp = np.random.RandomState(1).randn(1, 4, 4, 8).astype(np.float32)
        vp = np.random.RandomState(2).randn(1, 4, 4, 8).astype(np.float32)
        lens = np.array([3, 6], np.int32)
        tab = np.array([[0, 1], [2, 3]], np.int32)
        import jax.numpy as jnp
        paged_attention(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                        jnp.asarray(lens), jnp.asarray(tab))
        flat = sample_values(reg)
        util = flat["pt_serving_kv_page_utilization"]
        assert util == pytest.approx(4.5 / 8.0)
        assert sum(v for k, v in flat.items()
                   if k.startswith("pt_kernel_launch_total")) >= 1

        # 4. trainer: a 2-step run populates the step breakdown + gauges
        from paddle_tpu import nn
        from paddle_tpu.io import Dataset
        from paddle_tpu.trainer.trainer import Trainer, TrainingArguments

        class DS(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                x = np.full((4,), i, np.float32)
                return x, x.sum(keepdims=True)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 1)

            def forward(self, x, y=None):
                out = self.fc(x)
                if y is not None:
                    return ((out - y) ** 2).mean()
                return out

        b4_steps = flat.get("pt_train_steps_total", 0)
        tr = Trainer(model=Net(),
                     args=TrainingArguments(
                         output_dir=str(tmp_path), max_steps=2,
                         per_device_train_batch_size=4, logging_steps=1,
                         flops_per_sample=1e6, hardware_peak_flops=1e12),
                     train_dataset=DS())
        tr.train()
        flat = sample_values(reg)
        assert flat["pt_train_steps_total"] == b4_steps + 2
        assert flat["pt_train_forward_seconds_count"] >= 2
        assert flat["pt_train_backward_seconds_count"] >= 2
        assert flat["pt_train_optimizer_seconds_count"] >= 2
        assert flat["pt_train_data_seconds_count"] >= 2
        assert flat["pt_train_grad_norm_count"] >= 2
        assert flat["pt_train_samples_per_second"] > 0
        assert flat["pt_train_tokens_per_second"] > 0
        assert flat["pt_train_mfu"] > 0

        # the four subsystems are all visible in one Prometheus scrape
        text = to_prometheus(reg)
        for family in ("pt_ops_dispatch_total", "pt_collective_calls_total",
                       "pt_serving_kv_page_utilization",
                       "pt_train_steps_total"):
            assert f"# TYPE {family}" in text

    def test_jit_cache_hit_miss_counters(self):
        reg = obs.registry()
        from paddle_tpu import jit

        @jit.to_static
        def f(x):
            return x * 2 + 1

        x = paddle.to_tensor(np.ones((3,), np.float32))
        f(x)
        f(x)
        f(x)
        flat = sample_values(reg)
        calls = flat['pt_jit_call_total{kind="to_static"}']
        traces = flat['pt_jit_trace_total{kind="to_static"}']
        assert calls >= 3
        # same shape/dtype -> exactly one trace for the three calls
        assert traces >= 1
        assert calls - traces >= 2  # cache hits


# ---------------------------------------------------------------------------
# overhead gate: disabled metrics must not tax the hot loop
# ---------------------------------------------------------------------------

class TestOverhead:
    def test_disabled_overhead_under_5pct(self):
        from paddle_tpu.observability import tracing as tr
        r = Registry()
        c = r.counter("ov_total")
        h = r.histogram("ov_seconds")
        rec = tr.TraceRecorder(capacity=8)
        a = np.random.RandomState(0).randn(160, 160).astype(np.float32)
        n = 600

        def plain():
            t0 = time.perf_counter()
            for _ in range(n):
                a.dot(a)
            return time.perf_counter() - t0

        def instrumented():
            t0 = time.perf_counter()
            for i in range(n):
                a.dot(a)
                c.inc()
                h.observe(1.0)
                rec.stamp(i, "token", index=i)
            return time.perf_counter() - t0

        obs.set_enabled(False)
        tr.set_enabled(False)
        try:
            # warm both paths, then interleave rounds and compare the best
            # observation of each (min filters scheduler noise)
            plain()
            instrumented()
            tp, ti = [], []
            for _ in range(7):
                tp.append(plain())
                ti.append(instrumented())
        finally:
            obs.set_enabled(True)
            tr.set_enabled(True)
        assert c.value == 0  # the flag really gated recording
        assert not rec.live() and not rec.finished()  # stamps gated too
        assert min(ti) < min(tp) * 1.05, (
            f"disabled-metrics loop {min(ti):.4f}s vs plain {min(tp):.4f}s "
            f"(+{(min(ti) / min(tp) - 1) * 100:.1f}%)")

    def test_disabled_counter_tracks_under_5pct(self):
        # ISSUE 11: the per-step attribution stamps the engine adds —
        # counter-track points and gauge sampling — must also vanish
        # under the metrics-off gate
        from paddle_tpu.observability import tracing as tr
        r = Registry()
        g = r.gauge("ov_gauge")
        g.set(1.0)
        rec = tr.TraceRecorder(capacity=8)
        a = np.random.RandomState(0).randn(160, 160).astype(np.float32)
        n = 600

        def plain():
            t0 = time.perf_counter()
            for _ in range(n):
                a.dot(a)
            return time.perf_counter() - t0

        def instrumented():
            t0 = time.perf_counter()
            for i in range(n):
                a.dot(a)
                rec.counter("ov.track", float(i))
                rec.sample_gauges(("ov_gauge",), reg=r)
            return time.perf_counter() - t0

        obs.set_enabled(False)
        tr.set_enabled(False)
        try:
            plain()
            instrumented()
            tp, ti = [], []
            for _ in range(7):
                tp.append(plain())
                ti.append(instrumented())
        finally:
            obs.set_enabled(True)
            tr.set_enabled(True)
        assert rec.counters() == {}  # the flag really gated sampling
        assert min(ti) < min(tp) * 1.05, (
            f"disabled counter-track loop {min(ti):.4f}s vs plain "
            f"{min(tp):.4f}s (+{(min(ti) / min(tp) - 1) * 100:.1f}%)")

    def test_disabled_fleet_paths_under_5pct(self):
        # ISSUE 16: the fleet plane's hot-path hooks — the router's SLO
        # observes and the per-stamp replica-context/handoff-context
        # machinery — must also vanish under the off flags
        from paddle_tpu.observability import fleet as fleet_mod
        from paddle_tpu.observability import tracing as tr
        rec = tr.TraceRecorder(capacity=8)
        # four gated calls ride each iteration (vs three in the tests
        # above), so give them a bigger work unit to hide under
        a = np.random.RandomState(0).randn(256, 256).astype(np.float32)
        n = 300

        def plain():
            t0 = time.perf_counter()
            for _ in range(n):
                a.dot(a)
            return time.perf_counter() - t0

        def instrumented():
            t0 = time.perf_counter()
            for i in range(n):
                a.dot(a)
                fleet_mod.observe_ttft(0.1)
                fleet_mod.observe_handoff(0.01)
                rec.set_replica_context("pf0")
                rec.adopt(i, rec.export_context(i))
            return time.perf_counter() - t0

        before = obs.snapshot()["serving.fleet.ttft_seconds"]
        obs.set_enabled(False)
        tr.set_enabled(False)
        try:
            plain()
            instrumented()
            tp, ti = [], []
            for _ in range(7):
                tp.append(plain())
                ti.append(instrumented())
        finally:
            obs.set_enabled(True)
            tr.set_enabled(True)
        after = obs.snapshot()["serving.fleet.ttft_seconds"]
        assert after["series"][0]["count"] \
            == before["series"][0]["count"]  # observes really gated
        assert not rec.live() and not rec.finished()
        assert min(ti) < min(tp) * 1.05, (
            f"disabled fleet-path loop {min(ti):.4f}s vs plain "
            f"{min(tp):.4f}s (+{(min(ti) / min(tp) - 1) * 100:.1f}%)")


class TestReplicaPrefixMetrics:
    """ISSUE 15 satellite: the fleet router's locality signal is visible
    per replica — hit tokens, pinned pages, and evictions carry a
    replica label in the registry."""

    @staticmethod
    def _series(name, replica):
        from paddle_tpu import serving as srv
        fam = srv.metrics().get(name) or {"series": []}
        return sum(s["value"] for s in fam["series"]
                   if s["labels"].get("replica") == replica)

    def test_per_replica_hit_pin_evict_counters(self):
        from paddle_tpu.serving import PageBlockAllocator, PrefixCache
        a = PageBlockAllocator(num_pages=9, page_size=4, pages_per_seq=4)
        pc = PrefixCache(a, replica="pf_obs_test")
        prompt = np.arange(100, 112, dtype=np.int32)
        a.allocate("s", 12)
        a.extend("s", 12)

        hit0 = self._series("serving.prefix_cache.replica_hit_tokens",
                            "pf_obs_test")
        ev0 = self._series("serving.prefix_cache.replica_evicted_pages",
                           "pf_obs_test")
        pc.insert(prompt, a.seq_pages("s"))
        assert self._series(
            "serving.prefix_cache.replica_pinned_pages",
            "pf_obs_test") == 3
        # a lookup on the cached prompt counts matched tokens (capped
        # one token short of the prompt: 2 of the 3 pages)
        m = pc.lookup(prompt)
        assert self._series(
            "serving.prefix_cache.replica_hit_tokens",
            "pf_obs_test") == hit0 + 8
        m.release()
        a.free("s")
        pc.flush()
        assert self._series(
            "serving.prefix_cache.replica_evicted_pages",
            "pf_obs_test") == ev0 + 3
        assert self._series(
            "serving.prefix_cache.replica_pinned_pages",
            "pf_obs_test") == 0

    def test_set_replica_renames_late(self):
        # the FleetRouter names engines it was handed anonymously:
        # set_replica adopts the label for subsequent traffic
        from paddle_tpu.serving import PageBlockAllocator, PrefixCache
        a = PageBlockAllocator(num_pages=9, page_size=4, pages_per_seq=4)
        pc = PrefixCache(a)
        pc.set_replica("late_name_test")
        a.allocate("s", 8)
        a.extend("s", 8)
        pc.insert(np.arange(8, dtype=np.int32), a.seq_pages("s"))
        assert self._series(
            "serving.prefix_cache.replica_pinned_pages",
            "late_name_test") == 2

    def test_handoff_and_router_families_registered(self):
        # the handoff/router metric families exist in the default
        # registry with their label schema (values are exercised by the
        # serving tests; this pins the observable surface)
        snap = obs.registry().snapshot()
        assert snap["serving.handoff.requests"]["labels"] == ["direction"]
        assert "serving.handoff.pages" in snap
        assert "serving.handoff.bytes" in snap
        assert sorted(snap["serving.router.placements"]["labels"]) \
            == ["replica", "signal"]
        assert snap["serving.router.drains"]["labels"] == ["replica"]
        assert "serving.router.requeued" in snap
        assert "serving.router.replicas_up" in snap
