"""paddle_tpu.analysis — AST-based static analysis for TPU/JAX hazards.

Pure-stdlib (``ast`` only): importing this package never imports jax, so
``tools/paddlelint.py`` can run in any environment, including CI hosts
with no accelerator stack. Rules PT001-PT006 are documented in
docs/ANALYSIS.md; the CLI lives in :mod:`paddle_tpu.analysis.cli`.
"""

from .baseline import load as load_baseline
from .baseline import save as save_baseline
from .baseline import split as split_baseline
from .callgraph import PackageIndex
from .model import RULES, Config, Finding
from .runner import analyze_paths, analyze_source

__all__ = [
    "PackageIndex", "RULES", "Config", "Finding",
    "analyze_paths", "analyze_source",
    "load_baseline", "save_baseline", "split_baseline",
]
