"""Offline post-mortem CLI for collective flight-recorder dumps (ISSUE 3).

A hung job leaves one ``flightdump.<rank>.json`` per rank in the worker
log dir (written by ``paddle_tpu.distributed.watchdog`` when
``FLAGS_collective_timeout`` fires, or collected live into
``flight_report.json`` by the launch controller). This tool merges and
diffs those dumps after the fact — on a workstation, without the job:

    python tools/flight_recorder.py merge LOGDIR [-o report.json]
        merge every flightdump.*.json under LOGDIR (files also accepted)
        into one report: per-rank last-completed seq, the lagging rank,
        the first divergence, and the union of records sorted by seq.

    python tools/flight_recorder.py diff LOGDIR
        print just the desync verdict: the first seq where ranks disagree
        (op/shape mismatch, a non-ok status, or a rank that never got
        there) and which ranks are behind.

Exit code: 0 = ranks consistent, 1 = divergence found, 2 = no dumps.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.distributed import watchdog  # noqa: E402


def load_dumps(paths):
    """Expand dirs to their flightdump.*.json files and parse everything."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "flightdump.*.json"))))
        else:
            files.append(p)
    dumps = []
    for f in files:
        try:
            with open(f) as fh:
                dumps.append(json.load(fh))
        except (OSError, ValueError) as e:
            print(f"skipping {f}: {e}", file=sys.stderr)
    return dumps


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="flight_recorder",
        description="merge/diff per-rank collective flight dumps")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("merge", help="merge dumps into one report")
    mp.add_argument("paths", nargs="+",
                    help="log dirs (globbed for flightdump.*.json) or files")
    mp.add_argument("-o", "--output", default=None,
                    help="write the merged report here (default: stdout)")
    dp = sub.add_parser("diff", help="print the first cross-rank divergence")
    dp.add_argument("paths", nargs="+",
                    help="log dirs (globbed for flightdump.*.json) or files")
    args = ap.parse_args(argv)

    dumps = load_dumps(args.paths)
    if not dumps:
        print("no flight dumps found", file=sys.stderr)
        return 2
    report = watchdog.merge_dumps(dumps)
    div = report["first_divergence"]

    if args.cmd == "merge":
        text = json.dumps(report, indent=2)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
            print(f"wrote {args.output} ({len(report['records'])} records, "
                  f"{report['world']} ranks)")
        else:
            print(text)
    else:
        if div is None:
            print(f"{report['world']} ranks consistent through seq "
                  f"{max(report['last_completed_seq'].values(), default=0)}")
        else:
            print(json.dumps({"lagging_rank": report["lagging_rank"],
                              "last_completed_seq":
                                  report["last_completed_seq"],
                              "first_divergence": div}, indent=2))
    return 1 if div is not None else 0


if __name__ == "__main__":
    sys.exit(main())
