"""Fleet observability plane (ISSUE 16): cross-replica trace stitching
(one merged chrome trace, a handed-off request as a single flow across
replica lanes), metric federation semantics, router-measured fleet SLO
histograms (acceptance: percentiles agree with trace-derived TTFTs to
within one histogram bucket), the single-timeline contract under
replica-kill chaos, the seeded hostile-traffic workload harness with its
perf_gate bands, and the metric-doc drift gate."""

import bisect
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import DEFAULT_BUCKETS, Registry
from paddle_tpu.observability import fleet as fleet_mod
from paddle_tpu.observability import tracing as tracing_mod
from paddle_tpu.serving import FleetRouter, ServingEngine
from paddle_tpu.serving import workloads

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _obs_on():
    """The plane under test assumes metrics + tracing are recording."""
    pm, pt = obs.enabled(), tracing_mod.enabled()
    obs.set_enabled(True)
    tracing_mod.set_enabled(True)
    yield
    obs.set_enabled(pm)
    tracing_mod.set_enabled(pt)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    cfg = llama_tiny_config(num_hidden_layers=1)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _fleet_kw():
    return dict(max_slots=2, page_size=4, prefill_chunk=4)


def _ttft_snapshot():
    snap = obs.snapshot()
    e = snap.get("serving.fleet.ttft_seconds")
    return e["series"][0] if e and e["series"] else None


# ---------------------------------------------------------------------------
# metric federation (pure unit tests — no model)
# ---------------------------------------------------------------------------

class TestFederation:
    def _snap(self, build):
        r = Registry()
        build(r)
        return r.snapshot()

    def test_counters_summed_per_label_key(self):
        def mk(n):
            def build(r):
                c = r.counter("req_total", "h", labels=("path",))
                c.labels(path="gen").inc(n)
                c.labels(path="chat").inc(1)
            return build
        roll = fleet_mod.federate({"a": self._snap(mk(2)),
                                   "b": self._snap(mk(5))})
        vals = obs.sample_values(reg=roll)
        assert vals['req_total{path="gen"}'] == 7.0
        assert vals['req_total{path="chat"}'] == 2.0

    def test_gauges_gain_replica_label(self):
        def mk(v):
            return lambda r: r.gauge("kv_util", "h").set(v)
        roll = fleet_mod.federate({"pf0": self._snap(mk(0.25)),
                                   "dec0": self._snap(mk(0.75))})
        vals = obs.sample_values(reg=roll)
        assert vals['kv_util{replica="pf0"}'] == 0.25
        assert vals['kv_util{replica="dec0"}'] == 0.75

    def test_histograms_gain_replica_label(self):
        def mk(xs):
            def build(r):
                h = r.histogram("lat_seconds", "h", buckets=(0.1, 1.0))
                for x in xs:
                    h.observe(x)
            return build
        roll = fleet_mod.federate({"a": self._snap(mk([0.05, 0.5])),
                                   "b": self._snap(mk([5.0]))})
        snap = roll.snapshot()["lat_seconds"]
        assert snap["labels"] == ["replica"]
        by = {s["labels"]["replica"]: s for s in snap["series"]}
        assert by["a"]["count"] == 2 and by["a"]["counts"] == [1, 1, 0]
        assert by["b"]["count"] == 1 and by["b"]["counts"] == [0, 0, 1]

    def test_existing_replica_label_value_overridden(self):
        # a family that already splits by replica keeps its label set;
        # the value is stamped with the SCRAPING replica's name
        def build(r):
            g = r.gauge("pinned", "h", labels=("replica",))
            g.labels(replica="stale").set(3.0)
        roll = fleet_mod.federate({"dec1": self._snap(build)})
        vals = obs.sample_values(reg=roll)
        assert vals == {'pinned{replica="dec1"}': 3.0}

    def test_rollup_is_a_plain_registry(self):
        roll = fleet_mod.federate(
            {"a": self._snap(lambda r: r.counter("c_total").inc(1))})
        text = obs.to_prometheus(roll)
        assert "c_total 1" in text
        assert obs.parse_prometheus(text)["c_total"] == 1.0


# ---------------------------------------------------------------------------
# fleet SLO histograms + phase attribution (no model)
# ---------------------------------------------------------------------------

class TestFleetSLO:
    def test_observes_gated_by_metrics_flag(self):
        before = obs.snapshot()["serving.fleet.ttft_seconds"]
        obs.set_enabled(False)
        try:
            fleet_mod.observe_ttft(0.2)
            fleet_mod.observe_e2e(1.0)
            fleet_mod.observe_handoff(0.01)
        finally:
            obs.set_enabled(True)
        after = obs.snapshot()["serving.fleet.ttft_seconds"]
        assert after["series"][0]["count"] == before["series"][0]["count"]

    def test_summary_covers_the_three_metrics(self):
        fleet_mod.observe_ttft(0.2)
        s = fleet_mod.fleet_slo_summary()
        assert set(s) == set(fleet_mod.FLEET_SLO_METRICS)
        assert s["serving.fleet.ttft_seconds"]["count"] >= 1
        for row in s.values():
            assert {"count", "mean", "p50", "p90", "p99"} <= set(row)

    def test_phase_attribution_from_a_synthetic_timeline(self):
        rec = tracing_mod.TraceRecorder(capacity=4)
        rid = "phase-demo"
        # a drained-mid-decode shape: the handoff window falls between
        # tokens, so decode excludes it
        grid = (("enqueue", 0), ("admit", 1), ("handoff_ready", 2),
                ("token", 3), ("handoff_export", 4),
                ("handoff_import", 6), ("token", 9))
        rec.begin(rid)
        for name, _ in grid:
            rec.stamp(rid, name)
        tr = rec.live()[0]
        t0 = tr.timeline()[0].t_us
        for e, (_, ms) in zip(tr.timeline(), grid):
            e.t_us = t0 + ms * 1000
        out = fleet_mod.phase_attribution(tr)
        assert out == pytest.approx({"router_queue": 1e-3,
                                     "prefill": 1e-3,   # admit -> ready
                                     "handoff": 2e-3,
                                     "decode": 4e-3})   # 6ms minus handoff

    def test_phase_attribution_handles_missing_events(self):
        assert fleet_mod.phase_attribution(None) == {}
        rec = tracing_mod.TraceRecorder(capacity=4)
        rec.begin("lonely")
        rec.stamp("lonely", "enqueue")
        out = fleet_mod.phase_attribution(rec.live()[0])
        assert out == {}  # no admit, no token, no handoff yet


# ---------------------------------------------------------------------------
# cross-replica stitching + router SLO acceptance (two-replica fleet)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def two_replica_run(model):
    """A seeded prefill+decode fleet run: every request pays exactly one
    handoff. Returns (router, results, ttft_series_before/after)."""
    obs.set_enabled(True)
    tracing_mod.set_enabled(True)
    tracing_mod.recorder().clear()
    before = _ttft_snapshot()
    pf = ServingEngine(model, role="prefill", replica="pf0", **_fleet_kw())
    dec = ServingEngine(model, role="decode", replica="dec0", **_fleet_kw())
    router = FleetRouter({"pf0": pf, "dec0": dec})
    rng = np.random.RandomState(7)
    V = model.config.vocab_size
    for i in range(4):
        router.submit(rng.randint(1, V, rng.randint(5, 9)).astype(np.int32),
                      int(rng.randint(3, 6)), request_id=f"fleet-{i}")
    results = router.run_to_completion()
    return router, results, before, _ttft_snapshot()


class TestStitching:
    def test_one_merged_trace_single_flow_across_lanes(
            self, two_replica_run, tmp_path):
        """Acceptance: ONE chrome trace, one process lane per replica, a
        handed-off request drawn as a single flow crossing both lanes."""
        router, results, _, _ = two_replica_run
        assert len(results) == 4 and router.handoff_count >= 4
        path = str(tmp_path / "fleet.json")
        n = fleet_mod.stitch_chrome_trace(path)
        data = json.load(open(path))
        events = data["traceEvents"]
        assert n == len(events)
        lanes = {e["args"]["name"]: e["pid"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert {"replica:pf0", "replica:dec0", "fleet"} <= set(lanes)
        assert lanes["fleet"] == 0
        # flow arrows: every s has a matching f, at least one crosses pids
        flows = {}
        for e in events:
            if e.get("name") == "kv_handoff":
                flows.setdefault(e["id"], {})[e["ph"]] = e
        assert flows
        for pair in flows.values():
            assert set(pair) == {"s", "f"}
        crossing = [p for p in flows.values()
                    if p["s"]["pid"] != p["f"]["pid"]]
        assert crossing, "no handoff flow crossed replica lanes"
        # the handed-off request is ONE timeline: same tid on both lanes
        p = crossing[0]
        assert p["s"]["tid"] == p["f"]["tid"]
        assert p["s"]["pid"] == lanes["replica:pf0"]
        assert p["f"]["pid"] == lanes["replica:dec0"]
        # and its lifetime spans exist in both lanes under that tid
        spans = [e for e in events if e["ph"] == "X"
                 and e["tid"] == p["s"]["tid"]]
        assert {e["pid"] for e in spans} == {lanes["replica:pf0"],
                                             lanes["replica:dec0"]}

    def test_router_ttft_within_one_bucket_of_traces(self, two_replica_run):
        """Acceptance: the router-measured serving.fleet.ttft_seconds
        distribution matches per-request trace-derived TTFTs to within
        one histogram bucket."""
        _, results, before, after = two_replica_run
        counts = list(after["counts"])
        total = after["count"]
        if before is not None:
            counts = [a - b for a, b in zip(counts, before["counts"])]
            total -= before["count"]
        fins = {t.request_id: t for t in tracing_mod.recorder().finished()}
        ttfts = [fins[rid].ttft_s() for rid in results if rid in fins]
        ttfts = [t for t in ttfts if t is not None]
        assert len(ttfts) == len(results) == total
        for t in ttfts:   # greedy match, each ttft consumes one delta
            i = bisect.bisect_left(DEFAULT_BUCKETS, t)
            for j in (i, i - 1, i + 1):
                if 0 <= j < len(counts) and counts[j] > 0:
                    counts[j] -= 1
                    break
            else:
                raise AssertionError(
                    f"trace ttft {t:.4f}s has no router observation "
                    f"within one bucket (remaining deltas {counts})")

    def test_router_scrape_federates_replica_truth(self, two_replica_run):
        router, _, _, _ = two_replica_run
        rollup = router.scrape()
        vals = obs.sample_values(reg=rollup)
        assert vals['serving.replica.info{replica="pf0",role="prefill"}'] \
            == 1.0
        assert vals['serving.replica.info{replica="dec0",role="decode"}'] \
            == 1.0
        # engine-local handoff truth, counters summed to the fleet total
        assert vals['serving.replica.handoffs{direction="export"}'] >= 4
        assert vals['serving.replica.handoffs{direction="import"}'] >= 4
        # the fleet SLO histograms ride along in the rollup
        s = fleet_mod.fleet_slo_summary(reg=rollup)
        assert s["serving.fleet.ttft_seconds"]["count"] >= 4
        assert s["serving.fleet.handoff_latency_seconds"]["count"] >= 4
        assert router.slo_summary()["serving.fleet.e2e_seconds"]["count"] \
            >= 4

    def test_phase_attribution_reconstructs_handoff_path(
            self, two_replica_run):
        _, results, _, _ = two_replica_run
        fins = {t.request_id: t for t in tracing_mod.recorder().finished()}
        tr = fins[next(iter(results))]
        out = fleet_mod.phase_attribution(tr)
        assert set(out) == set(fleet_mod.FLEET_PHASES)
        assert all(v >= 0.0 for v in out.values())
        assert out["handoff"] > 0.0
        # phases tile the e2e up to a small overlap: the prefill replica
        # emits the first token just before it stamps handoff_ready
        assert sum(out.values()) <= tr.e2e_s() + 0.01


# ---------------------------------------------------------------------------
# the single-timeline contract under chaos (satellite of ISSUE 16)
# ---------------------------------------------------------------------------

class TestChaosTimeline:
    def test_drain_midstream_keeps_one_ordered_timeline(self, model):
        """PR-15 contract under chaos: a request routed -> prefilled ->
        handed off -> whose decode replica is then drained mid-stream ->
        re-imported -> resumed keeps ONE trace whose events stay
        monotonically ordered, with both handoffs paired and the lanes
        changing across the second hop."""
        tracing_mod.recorder().clear()
        row = workloads.run_scenario("replica_kill", model)
        assert row["zero_loss"] == 1
        assert row["completed"] == row["requests"]
        assert row["handoffs"] > row["requests"]  # the drain re-export
        fins = [t for t in tracing_mod.recorder().finished()
                if str(t.request_id).startswith("kill")]
        assert len({t.request_id for t in fins}) == len(fins) \
            == row["requests"]
        moved = [t for t in fins
                 if sum(e.name == "handoff_export"
                        for e in t.timeline()) >= 2]
        assert moved, "drain did not re-export an in-flight decode"
        for tr in moved:
            evs = tr.timeline()
            names = [e.name for e in evs]
            ts = [e.t_us for e in evs]
            assert ts == sorted(ts)
            exp = [i for i, n in enumerate(names) if n == "handoff_export"]
            imp = [i for i, n in enumerate(names) if n == "handoff_import"]
            assert len(exp) == len(imp) >= 2
            assert names.index("routed") < names.index("admit") < exp[0]
            for a, b in zip(exp, imp):
                assert a < b              # every export pairs an import
            assert any(i > imp[-1] for i, n in enumerate(names)
                       if n == "resumed"), \
                "request never resumed after the drain re-import"
            assert names[-1] in ("finish", "token")
            # the second hop changes replicas: export stamped on the
            # drained source, import on the survivor
            src = (evs[exp[-1]].meta or {}).get("replica")
            dst = (evs[imp[-1]].meta or {}).get("replica")
            assert src and dst and src != dst
            assert tr.outcome == "finish"


# ---------------------------------------------------------------------------
# the hostile-traffic workload harness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def all_rows(model):
    obs.set_enabled(True)
    tracing_mod.set_enabled(True)
    return workloads.run_all(model, seed=0)


class TestWorkloads:
    def test_all_scenarios_complete_with_zero_loss(self, all_rows):
        assert list(all_rows) == list(workloads.SCENARIOS)
        for name, row in all_rows.items():
            assert row["zero_loss"] == 1, name
            assert row["completed"] == row["requests"] > 0, name
            assert row["output_checksum"] > 0, name
            assert row["handoffs"] >= row["requests"], name
            assert row["ttft_p50_ms"] is not None, name
            assert row["e2e_p90_ms"] is not None, name

    def test_shared_prefix_scenarios_skip_prefill(self, all_rows):
        # agentic chains rebuild the whole conversation each turn — the
        # radix trie must be turning that into prefill skips
        assert all_rows["agentic"]["prefill_skip_rate"] > 0.2
        # the good tenant survives the adversary's cache thrash
        assert all_rows["thrash"]["prefill_skip_rate"] > 0.0

    def test_deterministic_fields_replay_bit_exactly(self, model, all_rows):
        again = workloads.run_scenario("burst", model, seed=0)
        for f in workloads.ROW_DETERMINISTIC:
            assert again[f] == all_rows["burst"][f], f

    def test_rows_match_committed_artifact(self, all_rows):
        """The replay gate fleetboard --selftest runs, as a tier-1 test:
        this machine + seed 0 must reproduce docs/FLEET_BENCH.json on
        every deterministic field."""
        with open(os.path.join(REPO, "docs", "FLEET_BENCH.json")) as f:
            art = json.load(f)
        assert art["seed"] == 0
        for name, row in all_rows.items():
            ref = art["scenarios"][name]
            for field in workloads.ROW_DETERMINISTIC:
                assert row[field] == ref[field], f"{name}.{field}"

    def test_rows_clear_perf_gate_bands(self, all_rows):
        import perf_gate
        bands = perf_gate.fleet_rows(REPO)
        assert bands
        cand = {f"fleet.{n}.{f}": float(r[f]) for n, r in all_rows.items()
                for f in workloads.ROW_DETERMINISTIC}
        judged = perf_gate.check_candidate(cand, bands)
        bad = [r for r in judged if not r["ok"]]
        assert not bad, bad

    def test_unknown_scenario_rejected(self):
        with pytest.raises(ValueError):
            workloads.make_plan("nope")


# ---------------------------------------------------------------------------
# perf_gate fleet bands + the --check skip summary
# ---------------------------------------------------------------------------

class TestPerfGateFleet:
    def test_deterministic_fields_pin_exact_bands(self):
        import perf_gate
        rows = {r["key"]: r for r in perf_gate.fleet_rows(REPO)}
        for name in workloads.SCENARIOS:
            r = rows[f"fleet.{name}.output_checksum"]
            assert r["band"][0] == r["band"][1] == r["value"]
            assert r["direction"] == "both"
            lat = rows.get(f"fleet.{name}.handoff_latency_ms")
            if lat is not None:
                assert lat["direction"] == "lower"

    def test_check_reports_per_artifact_skip_summary(self, capsys):
        import perf_gate
        assert perf_gate.main(["--repo", REPO]) == 0
        out = capsys.readouterr().out
        assert "docs/FLEET_BENCH.json" in out
        assert "rows checked" in out
        assert "predates_megadecode" in out   # skipped, and says why

    def test_json_report_lists_skips(self, capsys):
        import perf_gate
        assert perf_gate.main(["--repo", REPO, "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert "skipped" in rep
        assert all({"source", "key", "why"} <= set(s)
                   for s in rep["skipped"])


# ---------------------------------------------------------------------------
# metric-doc drift gate (satellite of ISSUE 16)
# ---------------------------------------------------------------------------

class TestMetricDocDrift:
    def test_every_live_family_is_documented(self):
        """Import the WHOLE production package, then walk the live
        default registry and require every metric family name to appear
        literally in docs/OBSERVABILITY.md — new instrumentation must
        land with its documentation. (Importing everything here makes
        the gate independent of which other tests ran first.)"""
        import importlib
        import pkgutil
        for mod in pkgutil.walk_packages(paddle.__path__,
                                         prefix="paddle_tpu."):
            if mod.name.endswith(("__main__", ".launch")):
                continue    # CLI entry points parse argv at import
            try:
                importlib.import_module(mod.name)
            except ImportError:
                pass        # optional native extensions
        prefixes = ("pt_", "serving.", "watchdog.", "resilience.")
        names = [n for n in obs.snapshot()
                 if n.startswith(prefixes)]
        assert len(names) >= 80   # the plane is actually instrumented
        with open(os.path.join(REPO, "docs", "OBSERVABILITY.md"),
                  encoding="utf-8") as f:
            text = f.read()
        missing = sorted(n for n in names if n not in text)
        assert not missing, (
            f"{len(missing)} metric families missing from "
            f"docs/OBSERVABILITY.md: {missing}")


# ---------------------------------------------------------------------------
# fleetboard units (the selftest itself is verify-recipe wiring)
# ---------------------------------------------------------------------------

class TestFleetboard:
    def test_render_table(self):
        import fleetboard
        rows = {"burst": {"scenario": "burst", "requests": 12,
                          "completed": 12, "zero_loss": 1, "handoffs": 12,
                          "fleet_tokens_per_s": 123.456,
                          "ttft_p50_ms": 10.0, "ttft_p90_ms": 20.0,
                          "e2e_p90_ms": 99.0, "handoff_latency_ms": 1.5,
                          "prefill_skip_rate": 0.25}}
        table = fleetboard.render_table(rows)
        lines = table.splitlines()
        assert len(lines) == 2
        assert "scenario" in lines[0] and "tok/s" in lines[0]
        assert "burst" in lines[1] and "123.46" in lines[1]

    def test_federate_files(self, tmp_path):
        import fleetboard
        r = Registry()
        r.gauge("kv_util", "h").set(0.5)
        for name in ("pf0", "dec0"):
            with open(tmp_path / f"{name}.json", "w") as f:
                json.dump(r.snapshot(), f)
        text = fleetboard.federate_files(
            [str(tmp_path / "pf0.json"), str(tmp_path / "dec0.json")])
        vals = obs.parse_prometheus(text)
        assert vals['kv_util{replica="pf0"}'] == 0.5
        assert vals['kv_util{replica="dec0"}'] == 0.5
