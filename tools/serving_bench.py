"""Serving-path benchmark: KV-cache decode throughput on the local chip
(VERDICT r2 item 7; ref capability: the reference's inference engine is a
perf product — paddle/fluid/inference/ + the masked/block decode attention
kernel set, paddle/phi/kernels/fusion/gpu/block_multi_head_attention*).

Measures, on the real device:
  1. generate_compiled (one-XLA-program prefill + lax.scan decode loop)
     on the per-chip shard of the mp=8 x pp=4 partitioned Llama-3-8B —
     the same per-chip model the training bench measures, so the two
     numbers compose the same way (multiply by chips, subtract the
     collective terms accounted in docs/FLAGSHIP.md).
  2. The paged-attention decode kernel vs the dense masked-cache
     attention at serving shapes (microbench of the O(1)-per-step op).

Writes docs/SERVING_BENCH.json and prints a summary. Roofline note: at
batch B with per-chip weight bytes W and per-sequence KV-cache bytes C(s),
one decode step must read >= W + B*C(s) from HBM; tokens/s/chip is
bounded by B * BW / (W + B*C(s)). The report records achieved vs that
bound.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

HBM_BW = {"v5e": 819e9, "v5p": 2765e9, "v4": 1228e9, "v6e": 1640e9}


def _bw() -> float:
    import jax
    kind = jax.devices()[0].device_kind.lower()
    for k, v in HBM_BW.items():
        if k in kind or ("v5 lite" in kind and k == "v5e"):
            return v
    return 819e9


def _tree_bytes(p) -> int:
    import jax
    skip = {"cfg", "family", "moe_static"}
    leaves = jax.tree_util.tree_leaves(
        {k: v for k, v in p.items() if k not in skip})
    return sum(v.size * v.dtype.itemsize for v in leaves
               if hasattr(v, "size"))


def _roofline(family, *, B, S0, new, n_layers, w_bytes, decode_tok_s,
              kv_heads=0, head_dim=0, kv_latent_dim=0):
    """Roofline fields for one bench row, derived from the shared
    `observability.costmodel` registry (ISSUE 11: every roofline in this
    report comes from `decode_step_budget`, never hand-inlined byte
    math). The average KV length over the decode phase is ~S0 + new/2.
    ``bytes_per_token_measured`` is the HBM traffic per token the
    achieved rate implies at full bandwidth (= model / roofline
    fraction) — the instrumented-HBM counterpart lives in the serving
    engine's `hbm_accounting()` ledger."""
    from paddle_tpu.observability import costmodel
    budget = costmodel.decode_step_budget(
        family, batch=B, context=S0 + new / 2, layers=n_layers,
        weight_bytes=w_bytes, kv_heads=kv_heads, head_dim=head_dim,
        kv_latent_dim=kv_latent_dim)
    bw = _bw()
    bound_tok_s = costmodel.roofline_tokens_per_s(budget, bw)
    return dict(
        roofline_tokens_per_s=round(bound_tok_s, 1),
        roofline_fraction=round(decode_tok_s / bound_tok_s, 3),
        bytes_per_token_model=round(budget["bytes_per_token"], 1),
        bytes_per_token_measured=round(bw / decode_tok_s, 1))


def _log(msg):
    print(f"[serving_bench +{time.time() - _T0:.0f}s] {msg}", file=sys.stderr,
          flush=True)


_T0 = time.time()


def _llama_bench_raw_model(total, dtype="bfloat16"):
    """The ONE llama bench config (decode rows, the long-prefill row and
    the serving-engine row must measure the same 8B mp=8 x pp=4 shard —
    only cache capacity and quant mode differ). Returns (cfg, model)."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import (LlamaForCausalLM,
                                         llama3_8b_shard_config)
    import paddle_tpu as paddle
    cfg = llama3_8b_shard_config(mp=8, pp=4,
                                 max_position_embeddings=total)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if dtype == "bfloat16":
        for prm in model.parameters():
            prm._data = prm._data.astype(jnp.bfloat16)
    return cfg, model


def _llama_bench_model(total, dtype="bfloat16", weight_only_int8=False,
                       weight_only_quant=None):
    from paddle_tpu.generation import _llama_decode_params
    cfg, model = _llama_bench_raw_model(total, dtype)
    return cfg, _llama_decode_params(
        model, weight_only_int8=weight_only_int8,
        weight_only_quant=weight_only_quant)


def bench_decode(B=8, S0=1024, new=512, dtype="bfloat16",
                 weight_only_int8=False, weight_only_quant=None):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.generation import _make_decode_loop

    total = S0 + new
    _log(f"init model B={B} S0={S0} new={new} int8={weight_only_int8}")
    cfg, p = _llama_bench_model(total, dtype, weight_only_int8,
                                weight_only_quant)
    _log("model built")
    w_bytes = _tree_bytes(p)
    KV, D = cfg.num_key_value_heads, cfg.head_dim
    cache_bytes_full = 2 * total * KV * D * 2 * len(p["layers"])  # bf16

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S0)), jnp.int32)

    run = _make_decode_loop(p, S0, new, "greedy_search", None, None,
                                  1.0, None, 0)
    key = jax.random.PRNGKey(0)
    _log("compiling decode loop")
    t0 = time.time()
    toks, _ = run(ids, key)
    np.asarray(toks)   # block_until_ready is a no-op on the axon tunnel;
                       # a host fetch is the only honest barrier
    _log("decode loop compiled+run")
    compile_and_first = time.time() - t0
    reps = 3
    t0 = time.time()
    for _ in range(reps):
        toks, _ = run(ids, key)
    np.asarray(toks)
    dt = (time.time() - t0) / reps

    # split prefill from decode: a 1-token decode loop isolates prefill
    run_pf = _make_decode_loop(p, S0, 1, "greedy_search", None, None,
                                     1.0, None, 0)
    _log("compiling prefill-only loop")
    toks_pf, _ = run_pf(ids, key)
    np.asarray(toks_pf)
    _log("prefill-only compiled+run")
    t0 = time.time()
    for _ in range(reps):
        toks_pf, _ = run_pf(ids, key)
    np.asarray(toks_pf)
    t_prefill = (time.time() - t0) / reps

    t_decode = max(dt - t_prefill, 1e-9)
    decode_tok_s = B * new / t_decode
    per_token_ms = t_decode / new * 1e3
    prefill_tok_s = B * S0 / max(t_prefill, 1e-9)

    roof = _roofline("llama", B=B, S0=S0, new=new,
                     n_layers=len(p["layers"]), w_bytes=w_bytes,
                     decode_tok_s=decode_tok_s, kv_heads=KV, head_dim=D)
    wo_tag = ("int4" if weight_only_quant == "int4"
              else "int8" if (weight_only_int8 or weight_only_quant)
              else None)
    extra = {}
    if wo_tag == "int4":
        extra["int4_note"] = (
            "int4 decode runs AT OR SLIGHTLY BELOW int8 throughput "
            "(~5-10% behind on recorded runs — compare the decode_int8 "
            "row measured the same day) rather than beating it: the "
            "in-kernel nibble unpack is VPU-bound at int32 width "
            "(Mosaic has no int8 vector shifts), spending roughly what "
            "the halved HBM reads save. The win is the 2x smaller "
            "weight footprint (serving density / headroom)")
    return dict(
        **extra,
        config="llama3_8b_shard mp=8 pp=4 (8 layers, 4 q-heads/1 kv-head "
               "d128, ffn 1792, vocab 16032)"
               + (f" [weight-only {wo_tag}]" if wo_tag else ""),
        dtype=f"{wo_tag}-weights" if wo_tag else dtype,
        batch=B, prefill_len=S0, new_tokens=new,
        weight_bytes=int(w_bytes), kv_cache_bytes_full=int(cache_bytes_full),
        compile_plus_first_s=round(compile_and_first, 2),
        prefill_tokens_per_s=round(prefill_tok_s),
        decode_tokens_per_s_per_chip=round(decode_tok_s, 1),
        decode_ms_per_token_per_seq=round(per_token_ms, 3),
        **roof)


def bench_moe_decode(B=8, S0=512, new=256, dtype="bfloat16",
                     weight_only_int8=False):
    """MoE-LM shard decode (VERDICT r3 item 6): routed experts inside the
    scanned decode step via the grouped-GEMM dropless path. int8 halves
    the expert-stack HBM reads that dominate the weight traffic (r5)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.moe_llm import MoEForCausalLM, MoEConfig
    from paddle_tpu.generation import _decode_params, _make_decode_loop
    import paddle_tpu as paddle

    total = S0 + new
    # a per-chip MoE shard at Qwen2-MoE-A14B-ish layer geometry: 8 routed
    # experts (the ep=8 shard of 64), top-2, shared expert, dense layer 0
    cfg = MoEConfig(vocab_size=16032, hidden_size=2048,
                    intermediate_size=5632, num_hidden_layers=8,
                    num_attention_heads=16, num_key_value_heads=4,
                    max_position_embeddings=total, num_experts=8, top_k=2,
                    moe_intermediate_size=1408,
                    shared_expert_intermediate_size=1408,
                    moe_dropless=True, first_k_dense_replace=1)
    _log(f"init MoE model B={B} S0={S0} new={new} int8={weight_only_int8}")
    paddle.seed(0)
    model = MoEForCausalLM(cfg)
    model.eval()
    if dtype == "bfloat16":
        for prm in model.parameters():
            prm._data = prm._data.astype(jnp.bfloat16)
    p = _decode_params(model, weight_only_int8=weight_only_int8)
    w_bytes = _tree_bytes(p)
    KV, D = cfg.num_key_value_heads, cfg.head_dim
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S0)), jnp.int32)
    run = _make_decode_loop(p, S0, new, "greedy_search", None, None,
                            1.0, None, 0)
    key = jax.random.PRNGKey(0)
    _log("compiling MoE decode loop")
    t0 = time.time()
    toks, _ = run(ids, key)
    np.asarray(toks)
    compile_and_first = time.time() - t0
    _log("MoE decode loop compiled+run")
    reps = 3
    t0 = time.time()
    for _ in range(reps):
        toks, _ = run(ids, key)
    np.asarray(toks)
    dt = (time.time() - t0) / reps
    run_pf = _make_decode_loop(p, S0, 1, "greedy_search", None, None,
                               1.0, None, 0)
    toks_pf, _ = run_pf(ids, key)
    np.asarray(toks_pf)
    t0 = time.time()
    for _ in range(reps):
        toks_pf, _ = run_pf(ids, key)
    np.asarray(toks_pf)
    t_prefill = (time.time() - t0) / reps
    t_decode = max(dt - t_prefill, 1e-9)
    decode_tok_s = B * new / t_decode
    # roofline: weights + avg KV reads; top-2-of-8 experts mean only
    # ~2/8 of routed expert weight bytes are LIVE per token, but a whole
    # decode step at small B still reads every routed expert touched by
    # ANY token — report the conservative all-weights bound
    roof = _roofline("moe", B=B, S0=S0, new=new,
                     n_layers=len(p["layers"]), w_bytes=w_bytes,
                     decode_tok_s=decode_tok_s, kv_heads=KV, head_dim=D)
    return dict(
        config="moe_shard 8L h2048 E8 top2 mi1408 shared1408 (dropless "
               + ("[weight-only int8] " if weight_only_int8 else "")
               + "grouped-GEMM routing in the scanned decode step)",
        dtype="int8-weights" if weight_only_int8 else dtype,
        batch=B, prefill_len=S0, new_tokens=new,
        weight_bytes=int(w_bytes),
        compile_plus_first_s=round(compile_and_first, 2),
        decode_tokens_per_s_per_chip=round(decode_tok_s, 1),
        decode_ms_per_token_per_seq=round(t_decode / new * 1e3, 3),
        **roof)


def _mla_bench_model(total, dtype="bfloat16", weight_only_int8=False):
    """The ONE mla_shard bench config (both the headline decode bench and
    the context sweep must measure the same model — only the cache
    capacity differs)."""
    import jax.numpy as jnp
    from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                            DeepSeekV2Config)
    from paddle_tpu.generation import _decode_params
    import paddle_tpu as paddle
    cfg = DeepSeekV2Config(
        vocab_size=16032, hidden_size=2048, num_hidden_layers=8,
        num_attention_heads=16, num_key_value_heads=16,
        intermediate_size=5632, max_position_embeddings=total,
        q_lora_rank=768, kv_lora_rank=512, qk_nope_head_dim=128,
        qk_rope_head_dim=64, v_head_dim=128, num_experts=8, top_k=2,
        moe_intermediate_size=1408, shared_expert_intermediate_size=1408,
        moe_dropless=True, first_k_dense_replace=1)
    paddle.seed(0)
    model = DeepSeekV2ForCausalLM(cfg)
    model.eval()
    if dtype == "bfloat16":
        for prm in model.parameters():
            prm._data = prm._data.astype(jnp.bfloat16)
    return cfg, _decode_params(model, weight_only_int8=weight_only_int8)


def bench_mla_decode(B=8, S0=512, new=256, dtype="bfloat16",
                     weight_only_int8=False):
    """DeepSeek-V2 MLA shard decode: absorbed latent-KV cache (r+dr per
    token) through the scanned decode loop."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.generation import _make_decode_loop

    total = S0 + new
    _log(f"init MLA model B={B} S0={S0} new={new} int8={weight_only_int8}")
    cfg, p = _mla_bench_model(total, dtype, weight_only_int8)
    w_bytes = _tree_bytes(p)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S0)), jnp.int32)
    from paddle_tpu.flags import flags_guard
    key = jax.random.PRNGKey(0)
    _log("compiling MLA decode loop (fused kernel path)")
    with flags_guard(mla_decode_impl="fused"):
        run = _make_decode_loop(p, S0, new, "greedy_search", None, None,
                                1.0, None, 0)
        t0 = time.time()
        toks, _ = run(ids, key)
        np.asarray(toks)
        compile_and_first = time.time() - t0
    _log("compiling MLA decode loop (einsum composite, A/B contender)")
    with flags_guard(mla_decode_impl="xla"):
        run_x = _make_decode_loop(p, S0, new, "greedy_search", None, None,
                                  1.0, None, 0)
        toks_x, _ = run_x(ids, key)
        np.asarray(toks_x)
    # low-bit rounding differs between impls (f32-tile vs bf16-aw), so a
    # near-tie argmax may flip and diverge the sequence: RECORD the
    # disagreement instead of asserting (exact parity is a test-suite
    # contract at short horizons, tests/test_pallas_mla.py)
    tok_disagree = int((np.asarray(toks) != np.asarray(toks_x)).sum())
    # same-run interleaved rounds (VERDICT r4 weak #3 comparison shape).
    # One untimed call of EACH contender after ALL compiles, drained via
    # fetch(): the timed rounds fetch one element, and that slice
    # executable remote-compiles on first use — warming through
    # np.asarray left round 0 of the first contender paying a ~0.77 s
    # compile (the phantom "fused spike" chased in r5)
    reps = 3
    from bench_util import ab_rounds, band, ratio_band, fetch
    for f in (run, run_x):
        fetch(f(ids, key)[0])
    runs = ab_rounds({"fused": (lambda: run(ids, key)[0], ()),
                      "xla": (lambda: run_x(ids, key)[0], ())},
                     rounds=reps, reps=1, warmup=False)
    t_fused, t_xla = runs["fused"], runs["xla"]
    run_pf = _make_decode_loop(p, S0, 1, "greedy_search", None, None,
                               1.0, None, 0)
    toks_pf, _ = run_pf(ids, key)
    np.asarray(toks_pf)
    t0 = time.time()
    for _ in range(reps):
        toks_pf, _ = run_pf(ids, key)
    np.asarray(toks_pf)
    t_prefill = (time.time() - t0) / reps
    # headline = the impl the shipped default routes to (auto -> fused at
    # this lane-aligned rank) — never a silent best-of-both (review r5)
    t_decode = max(sum(t_fused) / reps - t_prefill, 1e-9)
    decode_tok_s = B * new / t_decode
    # latent cache: (r + dr) bf16 per token per layer — the MLA win
    roof = _roofline(
        "mla", B=B, S0=S0, new=new, n_layers=len(p["layers"]),
        w_bytes=w_bytes, decode_tok_s=decode_tok_s,
        kv_latent_dim=cfg.kv_lora_rank + cfg.qk_rope_head_dim)
    return dict(
        config="mla_shard 8L h2048 16h q768/kv512 nope128 rope64 v128 "
               + ("E8 top2 [weight-only int8] (absorbed latent-KV decode)"
                  if weight_only_int8
                  else "E8 top2 (absorbed latent-KV decode)"),
        dtype="int8-weights" if weight_only_int8 else dtype,
        batch=B, prefill_len=S0, new_tokens=new,
        weight_bytes=int(w_bytes),
        latent_cache_bytes_per_token_layer=(cfg.kv_lora_rank
                                            + cfg.qk_rope_head_dim) * 2,
        compile_plus_first_s=round(compile_and_first, 2),
        headline_impl="fused (the auto route at kv_lora_rank=512)",
        decode_tokens_per_s_per_chip=round(decode_tok_s, 1),
        decode_ms_per_token_per_seq=round(t_decode / new * 1e3, 3),
        **roof,
        impl_ab=dict(
            note="same-run interleaved whole-loop rounds (prefill "
                 "included in both, subtracted from the headline); "
                 "fused = ops/pallas_mla.py single-cache-read kernel, "
                 "xla = two-einsum composite; compile_plus_first_s "
                 "covers the fused program only",
            greedy_token_disagreements=tok_disagree,
            disagreement_note="bf16 near-tie argmax flips cascade: after "
                              "the first divergent token the sequences "
                              "differ, so every later token counts; "
                              "short-horizon exact-match is the test "
                              "contract (tests/test_pallas_mla.py)",
            fused_loop=band(t_fused),
            xla_loop=band(t_xla),
            xla_over_fused=ratio_band(t_xla, t_fused)))


def bench_mla_context_sweep(S0s=(512, 4096, 12288), B=8, new=128,
                            dtype="bfloat16"):
    """Where the fused MLA kernel earns its keep: decode-PHASE A/B
    (random pre-filled caches, scan of decode steps — no prefill, so long
    contexts fit without the dense [B,nh,S,T] prefill score tensor) at
    growing context. At T~768 the latent cache is ~3% of step traffic and
    fused==einsum within noise; by 12k context the einsum's double read
    of the cache is the dominant waste and the kernel's single pass wins
    outright. Same-run interleaved rounds per context."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.generation import _mla_cached_step_body, _llama_weights
    from paddle_tpu.flags import flags_guard
    from bench_util import ab_rounds, band, ratio_band, fetch

    # ONE model at the max context (rope table covers every S0; only the
    # cache capacity and step-body max_len vary per context)
    _log("mla ctx sweep: init model")
    cfg, p = _mla_bench_model(max(S0s) + new, dtype)
    wa = _llama_weights(p)
    rows = []
    for S0 in S0s:
        total = S0 + new
        rng = np.random.RandomState(0)
        caches0 = [
            (jnp.asarray(rng.randn(B, total, cfg.kv_lora_rank) * 0.1,
                         jnp.bfloat16),
             jnp.asarray(rng.randn(B, total, cfg.qk_rope_head_dim) * 0.1,
                         jnp.bfloat16))
            for _ in range(cfg.num_hidden_layers)]
        tok0 = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, 1)),
                           jnp.int32)
        loops = {}
        for impl in ("fused", "xla"):
            with flags_guard(mla_decode_impl=impl):
                body = _mla_cached_step_body(p["cfg"], total,
                                             p.get("moe_static"))

                @jax.jit
                def loop(w, tok0, caches, body=body):
                    def step(carry, i):
                        tok, caches = carry
                        logits, caches = body(w, tok, caches, S0 + i)
                        nxt = jnp.argmax(logits, -1)[:, None]
                        return (nxt.astype(jnp.int32), caches), ()
                    (tok, _), _ = jax.lax.scan(
                        step, (tok0, caches), jnp.arange(new))
                    return tok
                out = loop(wa, tok0, caches0)
                np.asarray(out)
                loops[impl] = loop
        for f in loops.values():
            # warm each after all compiles, drained via fetch() so the
            # one-element slice program also compiles untimed
            fetch(f(wa, tok0, caches0))
        t = ab_rounds(
            {name: (f, (wa, tok0, caches0)) for name, f in loops.items()},
            rounds=3, reps=1, warmup=False)
        _log(f"mla ctx sweep S0={S0}: fused {min(t['fused']):.3f}s "
             f"xla {min(t['xla']):.3f}s")
        rows.append(dict(
            context=S0, batch=B, decode_steps=new,
            fused_per_token=band([x / new for x in t["fused"]]),
            xla_per_token=band([x / new for x in t["xla"]]),
            xla_over_fused=ratio_band(t["xla"], t["fused"])))
    return dict(
        note="decode-phase only (no prefill term): scan of greedy decode "
             "steps over pre-filled caches; per-token bands in us; the "
             "fused kernel must never lose at short context and win at "
             "long (paged-kernel-style crossover record)",
        rows=rows)


def bench_paged_kernel(B=8, ctx=4096, page_size=16):
    """Decode-attention op microbench: the grouped-DMA in-tree kernel (v2)
    vs the per-page v1, the bundled kernel, and dense masked-cache
    attention at serving shapes (per-chip shard heads)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.paged_attention import paged_attention

    H, KV, D = 4, 1, 128           # the mp=8 shard's head layout
    layers = 8
    rng = np.random.RandomState(0)
    pages_per_seq = ctx // page_size
    total_pages = B * pages_per_seq
    q = jnp.asarray(rng.randn(B, H, D), jnp.bfloat16)
    kp = jnp.asarray(rng.randn(KV, total_pages, page_size, D), jnp.bfloat16)
    vp = jnp.asarray(rng.randn(KV, total_pages, page_size, D), jnp.bfloat16)
    lengths = jnp.full((B,), ctx, jnp.int32)
    page_idx = jnp.arange(total_pages, dtype=jnp.int32).reshape(
        B, pages_per_seq)

    CHAIN = 50

    def chain(fn):
        # run the op CHAIN times inside ONE program (output feeds the
        # next query) so per-call tunnel RTT doesn't dominate the time
        def chained(q, *args):
            def it(carry, _):
                o = fn(carry, *args)
                return o.astype(carry.dtype), ()
            out, _ = jax.lax.scan(it, q, None, length=CHAIN)
            return out
        return jax.jit(chained)

    from paddle_tpu.ops.pallas_paged import (paged_decode_attention,
                                             paged_decode_attention_v2)
    from paddle_tpu.flags import flags_guard
    paged_v2 = chain(lambda q, kp, vp: paged_decode_attention_v2(
        q, kp, vp, lengths, page_idx))
    paged_v1 = chain(lambda q, kp, vp: paged_decode_attention(
        q, kp, vp, lengths, page_idx))

    def _bundled(q, kp, vp):
        with flags_guard(paged_impl="bundled"):
            return paged_attention(q, kp, vp, lengths, page_idx)
    paged_bundled = chain(_bundled)

    def dense_fn(q, k, v):
        s = jnp.einsum("bhd,bthd->bht", q, k) * (D ** -0.5)
        pos = jnp.arange(ctx)
        s = jnp.where(pos[None, None, :] < lengths[:, None, None],
                      s.astype(jnp.float32), -1e30)
        w = jax.nn.softmax(s, -1).astype(v.dtype)
        return jnp.einsum("bht,bthd->bhd", w, v)

    k_dense = jnp.asarray(rng.randn(B, ctx, H, D), jnp.bfloat16)
    v_dense = jnp.asarray(rng.randn(B, ctx, H, D), jnp.bfloat16)
    dense = chain(dense_fn)

    from bench_util import ab_rounds, band, ratio_band

    # same-run interleaved A/B (VERDICT r4 item 3): every round times all
    # four kernels back-to-back, ratios carry their per-round band
    runs = ab_rounds({
        "intree_v2": (paged_v2, (q, kp, vp)),
        "intree_v1": (paged_v1, (q, kp, vp)),
        "bundled": (paged_bundled, (q, kp, vp)),
        "dense": (dense, (q, k_dense, v_dense)),
    }, rounds=3, reps=4)
    runs = {k: [t / CHAIN for t in v] for k, v in runs.items()}
    # per-layer op; a full decode step runs `layers` of these
    return dict(batch=B, context=ctx, page_size=page_size,
                heads=f"{H}q/{KV}kv d{D}", layers_note=f"x{layers}/step",
                rounds=3,
                paged_intree=band(runs["intree_v2"]),
                paged_intree_v1=band(runs["intree_v1"]),
                paged_bundled=band(runs["bundled"]),
                dense=band(runs["dense"]),
                intree_vs_dense=ratio_band(runs["dense"],
                                           runs["intree_v2"]),
                intree_vs_bundled=ratio_band(runs["bundled"],
                                             runs["intree_v2"]))


def _sweep_note(sweep):
    """Conclusion derived from THIS run's sweep (never a baked narrative
    that can contradict the numbers beside it). Ratios are same-run
    interleaved bands: a claim only counts where the whole band clears 1."""
    vs_b_lo = min(r["intree_vs_bundled"]["min"] for r in sweep)
    vs_b_hi = max(r["intree_vs_bundled"]["max"] for r in sweep)
    dense_8k = [r["intree_vs_dense"] for r in sweep if r["context"] >= 8192]
    beats_dense = all(v["min"] >= 1.0 for v in dense_8k)
    verdict = ("beats (entire band >= 1)" if beats_dense
               else "does NOT beat beyond noise")
    # v1-vs-v2 from THIS run's rounds, like every other claim here
    v1_ratios = [round(r["paged_intree_v1"]["mean_us"]
                       / r["paged_intree"]["mean_us"], 1) for r in sweep]
    return (f"this run, same-run interleaved x3: in-tree v2 vs bundled "
            f"ratio bands span {vs_b_lo}-{vs_b_hi} across the sweep; v2 "
            f"{verdict} dense at every >=8k shape "
            f"(bands {[(v['min'], v['max']) for v in dense_8k]}). intree "
            "stays the default while its band overlaps the bundled "
            "kernel's (it is in-tree tunable); the v1 per-page kernel it "
            f"replaced is {min(v1_ratios)}-{max(v1_ratios)}x slower in "
            "the same rounds.")


def bench_prefill_long(family="llama", S0=8192, B=4, dtype="bfloat16"):
    """Long-context PREFILL throughput — the r5 flash-prefill record.
    Before r5 every cached body materialized [*, S, max_len] f32 scores
    at prefill: a 12k-token B=8 MLA prefill OOM'd the 16 GB chip and the
    masked (max_len - S) columns were wasted even when it fit. The
    prefill-from-zero flash route makes these shapes runnable; this row
    records the achieved prefill tok/s at 8k context (new=1 decode loop
    isolates prefill + one step, matching the subtraction method the
    decode rows use)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.generation import _make_decode_loop
    from bench_util import fetch
    import paddle_tpu as paddle

    total = S0 + 16
    if family == "llama":
        _log(f"prefill_long llama: init S0={S0} B={B}")
        cfg, p = _llama_bench_model(total, dtype)
    else:
        _log(f"prefill_long mla: init S0={S0} B={B}")
        cfg, p = _mla_bench_model(total, dtype)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S0)), jnp.int32)
    key = jax.random.PRNGKey(0)
    run = _make_decode_loop(p, S0, 1, "greedy_search", None, None,
                            1.0, None, 0)
    t0 = time.time()
    toks, _ = run(ids, key)
    np.asarray(toks)
    compile_and_first = time.time() - t0
    fetch(run(ids, key)[0])          # warm incl. the fetch-slice program
    reps = 3
    ts = []
    for _ in range(reps):
        t0 = time.time()
        fetch(run(ids, key)[0])
        ts.append(time.time() - t0)
    from bench_util import band
    mean = sum(ts) / len(ts)    # mean over reps, matching bench_decode's
                                # identically-named field
    return dict(
        family=family, batch=B, prefill_len=S0, dtype=dtype,
        compile_plus_first_s=round(compile_and_first, 2),
        prefill_tokens_per_s=round(B * S0 / mean),
        loop_band=band(ts),
        note="runnable at all only since the r5 flash prefill (the "
             "dense [S, max_len] f32 score path OOMs these shapes); "
             "includes one decode step")


def _static_batches(model, reqs, max_slots):
    """Static whole-batch baseline: batches of `max_slots` in arrival
    order, prompts right-padded to the batch max, every row decoded until
    the LAST row's token budget — the padded prefill work and dead decode
    steps continuous batching exists to avoid. Uses generate_compiled
    (the serving-grade static API): its programs persist in
    _DECODE_LOOP_CACHE across calls, so after warmup the baseline pays
    zero compile time — the comparison measures scheduling, not jit."""
    import paddle_tpu as paddle
    from paddle_tpu.generation import generate_compiled
    for i in range(0, len(reqs), max_slots):
        chunk = reqs[i:i + max_slots]
        S = max(p.size for p, _ in chunk)
        ids = np.zeros((len(chunk), S), dtype=np.int32)
        for r, (p, _) in enumerate(chunk):
            ids[r, :p.size] = p
        generate_compiled(model, paddle.to_tensor(ids),
                          max_new_tokens=max(m for _, m in chunk),
                          decode_strategy="greedy_search")


def _serving_engine_row(model, cfg, reqs, max_slots, page_size, rounds):
    import tempfile
    import jax
    from bench_util import ratio_band, write_serving_report
    from paddle_tpu.serving import ServingEngine

    eng = ServingEngine(model, max_slots=max_slots, page_size=page_size)

    def run_engine():
        for p, m in reqs:
            eng.add_request(p, max_new_tokens=m)
        eng.run_to_completion()

    useful = sum(m for _, m in reqs)
    # warmup: the engine compiles once per (model, slot-count); the
    # static loop compiles one decode program per batch shape
    run_engine()
    _static_batches(model, reqs, max_slots)
    eng_ts, sta_ts = [], []
    for _ in range(rounds):            # same-run interleaved A/B
        t0 = time.time()
        run_engine()
        eng_ts.append(time.time() - t0)
        t0 = time.time()
        _static_batches(model, reqs, max_slots)
        sta_ts.append(time.time() - t0)
    on_tpu = jax.default_backend() == "tpu"
    # full serving.engine.* slice next to the artifact (TPU only — a
    # CPU-host run must leave docs/ untouched, same rule as main())
    rep_path = os.path.join(os.path.dirname(__file__), "..", "docs",
                            "SERVING_ENGINE_REPORT.json") if on_tpu \
        else os.path.join(tempfile.mkdtemp(), "SERVING_ENGINE_REPORT.json")
    row = dict(
        requests=len(reqs), max_slots=max_slots, page_size=page_size,
        prompt_tokens=int(sum(p.size for p, _ in reqs)),
        useful_new_tokens=int(useful),
        inflight_tokens_per_s=round(useful * rounds / sum(eng_ts), 1),
        static_tokens_per_s=round(useful * rounds / sum(sta_ts), 1),
        # per-round static_time/engine_time: >1 means in-flight wins
        inflight_vs_static=ratio_band(sta_ts, eng_ts),
        # {program_name: cache_size} — every value must stay 1 (the
        # engine's PT002 contract); ragged engines expose "unified",
        # split engines "decode"/"prefill"
        programs_compiled=eng.program_cache_sizes(),
        note="same mixed-length trace both ways; tokens/s counts only "
             "the REQUESTED new tokens, so static batching pays for its "
             "padded rows and dead decode steps. The engine decodes via "
             "a per-step host loop vs the baseline's fused scan: on a "
             "CPU host the dispatch overhead dominates a tiny step and "
             "the ratio inverts — only on-chip bands (weight-read-bound "
             "steps) are the record")
    report = write_serving_report(rep_path, extra=dict(throughput=row))
    row["engine_totals"] = report["totals"]
    return row


def bench_serving_engine_ragged(n=16, max_slots=8, page_size=16, rounds=3,
                                smin=64, smax=513, mmin=32, mmax=257,
                                seed=0, dtype="bfloat16"):
    """Unified ragged dispatch vs the legacy split prefill/decode
    dispatch on the SAME mixed-length trace and engine geometry: the
    ragged path launches ONE fused program per engine step (the ragged
    paged-attention kernel covers the prefill chunk and every decode row
    in a single pallas_call) where the split path launches a prefill
    program AND a decode program whenever both phases are in flight."""
    from bench_util import ratio_band
    from paddle_tpu.serving import ServingEngine

    total = 1024
    _log(f"serving_engine_ragged: init model n={n} slots={max_slots}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(seed)
    reqs = [(rng.randint(0, cfg.vocab_size,
                         int(rng.randint(smin, smax))).astype(np.int32),
             int(rng.randint(mmin, mmax)))
            for _ in range(n)]
    engines = {"ragged": ServingEngine(model, max_slots=max_slots,
                                       page_size=page_size, ragged=True),
               "split": ServingEngine(model, max_slots=max_slots,
                                      page_size=page_size, ragged=False)}

    def run(eng):
        for p, m in reqs:
            eng.add_request(p, max_new_tokens=m)
        eng.run_to_completion()

    useful = sum(m for _, m in reqs)
    launches = {}
    for name, eng in engines.items():
        _log(f"serving_engine_ragged: warm {name}")
        run(eng)                       # compiles the path's programs
        eng.launches = 0
        run(eng)
        launches[name] = eng.launches  # steady-state launches per trace
    ts = {"ragged": [], "split": []}
    for _ in range(rounds):            # same-run interleaved A/B
        for name, eng in engines.items():
            t0 = time.time()
            run(eng)
            ts[name].append(time.time() - t0)
    return dict(
        requests=len(reqs), max_slots=max_slots, page_size=page_size,
        prompt_tokens=int(sum(p.size for p, _ in reqs)),
        useful_new_tokens=int(useful),
        ragged_tokens_per_s=round(useful * rounds / sum(ts["ragged"]), 1),
        split_tokens_per_s=round(useful * rounds / sum(ts["split"]), 1),
        # per-round split_time/ragged_time: >1 means unified dispatch wins
        ragged_vs_split=ratio_band(ts["split"], ts["ragged"]),
        launches_per_trace=launches,
        programs_compiled={name: eng.program_cache_sizes()
                           for name, eng in engines.items()},
        note="same trace, same model, same slots both ways; "
             "launches_per_trace records the dispatch-count gap the "
             "fusion removes (the unified step also skips the dead "
             "launch a phase-empty step would pay). tokens/s counts "
             "only the requested new tokens. CPU-host numbers are not "
             "the record — the host-side step loop dominates tiny steps")


def bench_megadecode(n=12, max_slots=8, page_size=16, rounds=3,
                     smin=64, smax=257, mmin=32, mmax=129, seed=0,
                     dtype="bfloat16", hbm_gb=16):
    """Mega-kernel fused back half (ISSUE 14) vs the split chain on the
    SAME ragged trace and engine geometry: megadecode=True runs o-proj
    + residual + norm + FFN in TWO pallas_calls per layer after
    attention (fused_oproj_norm -> fused_ffn, 5 launches/layer total
    with the ISSUE-20 fused front both engines keep, so the A/B
    isolates the back half); megadecode=False keeps the six-dispatch
    split back half (8/layer). Also records the int4 density pairing:
    slots-per-chip at the shard shapes, because int4's recorded win is
    capacity, not tok/s (see int4_note on the decode_int4 row)."""
    from bench_util import ratio_band
    from paddle_tpu.observability import costmodel as cm
    from paddle_tpu.serving import ServingEngine

    total = 1024
    _log(f"megadecode: init model n={n} slots={max_slots}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(seed)
    reqs = [(rng.randint(0, cfg.vocab_size,
                         int(rng.randint(smin, smax))).astype(np.int32),
             int(rng.randint(mmin, mmax)))
            for _ in range(n)]
    engines = {"mega": ServingEngine(model, max_slots=max_slots,
                                     page_size=page_size, ragged=True),
               "split_back_half": ServingEngine(
                   model, max_slots=max_slots, page_size=page_size,
                   ragged=True, megadecode=False)}
    assert engines["mega"].megadecode
    assert not engines["split_back_half"].megadecode

    def run(eng):
        for p, m in reqs:
            eng.add_request(p, max_new_tokens=m)
        eng.run_to_completion()

    useful = sum(m for _, m in reqs)
    for name, eng in engines.items():
        _log(f"megadecode: warm {name}")
        run(eng)                       # compiles the path's programs
    ts = {name: [] for name in engines}
    for _ in range(rounds):            # same-run interleaved A/B
        for name, eng in engines.items():
            t0 = time.time()
            run(eng)
            ts[name].append(time.time() - t0)
    acct = engines["mega"].hbm_accounting()

    # model-side launch/byte ledger at the engine's own geometry
    n_layers = cfg.num_hidden_layers
    w_layer = acct["weights_bytes"] / n_layers
    kw = dict(batch=max_slots, context=total // 2,
              hidden=cfg.hidden_size, heads=cfg.num_attention_heads,
              kv_heads=cfg.num_key_value_heads, head_dim=cfg.head_dim,
              intermediate=cfg.intermediate_size, page_size=page_size,
              weight_bytes_per_layer=int(w_layer))
    mega_m = cm.decode_layer_kernels(**kw)
    split_m = cm.decode_layer_kernels(megadecode=False, **kw)

    def _layer_bytes(d):
        return sum(c.hbm_bytes * k for k, c in d["kernels"].values())

    # density pairing: KV slots that fit beside the weights on one chip
    kv_slot = (2 * total * cfg.num_key_value_heads * cfg.head_dim
               * 2 * n_layers)
    wb = acct["weights_bytes"]
    hbm = hbm_gb * 1024 ** 3
    _, p4 = _llama_bench_model(total, dtype, weight_only_quant="int4")
    wb4 = _tree_bytes(p4)
    return dict(
        requests=len(reqs), max_slots=max_slots, page_size=page_size,
        useful_new_tokens=int(useful),
        mega_tokens_per_s=round(useful * rounds / sum(ts["mega"]), 1),
        split_tokens_per_s=round(
            useful * rounds / sum(ts["split_back_half"]), 1),
        # per-round split_time/mega_time: >1 means the fusion wins
        mega_vs_split=ratio_band(ts["split_back_half"], ts["mega"]),
        launches_per_layer={"mega": mega_m["launches_per_layer"],
                            "split": split_m["launches_per_layer"]},
        back_half_launches={
            name: eng.back_half_launches
            for name, eng in engines.items()},
        model_layer_hbm_bytes={"mega": int(_layer_bytes(mega_m)),
                               "split": int(_layer_bytes(split_m))},
        bytes_per_token_measured=round(
            acct["bytes_per_token_measured"]),
        bytes_per_token_model=round(acct["bytes_per_token_model"]),
        int4_slots_per_chip={
            "weight_bytes_bf16": int(wb),
            "weight_bytes_int4": int(wb4),
            "kv_bytes_per_slot": int(kv_slot),
            "slots_bf16": int(max(0, hbm - wb) // kv_slot),
            "slots_int4": int(max(0, hbm - wb4) // kv_slot),
            "note": f"KV slots at {total}-token context beside the "
                    f"resident weights on a {hbm_gb} GiB chip — int4's "
                    "win is this density column, not the tok/s column"},
        note="same trace, same model, same slots both ways; both "
             "engines keep the ISSUE-20 fused front half, so the A/B "
             "isolates the back half. launches_per_layer is the "
             "costmodel ledger at the engine's geometry (5 fused vs 8 "
             "split back half), back_half_launches the engine's own "
             "count of pallas_calls after attention (2 vs 6). CPU-host "
             "tok/s is not the record — the host step loop dominates "
             "tiny steps; the committed record pairs this row with the "
             "measured roofline fractions")


def bench_front_half(n=12, max_slots=8, page_size=16, rounds=3,
                     smin=64, smax=257, mmin=32, mmax=129, seed=0,
                     dtype="bfloat16"):
    """Megafront fused front half (ISSUE 20) vs the split front on the
    SAME ragged trace and engine geometry: megafront=True runs
    norm -> fused_qkv_rope_append in TWO pallas_calls before attention
    (the single fused launch covers the qkv projection with in-kernel
    dequant, rope, and the paged K/V append scatter); megafront=False
    keeps the five-dispatch split front. Both engines keep
    megadecode=True, so the A/B isolates the front half: layer body 5
    launches fused vs 8 split. Greedy-output exactness between the two
    paths is the test-suite contract
    (tests/test_megafront.py::TestEngineMegafront)."""
    from bench_util import ratio_band
    from paddle_tpu.observability import costmodel as cm
    from paddle_tpu.serving import ServingEngine

    total = 1024
    _log(f"front_half: init model n={n} slots={max_slots}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(seed)
    reqs = [(rng.randint(0, cfg.vocab_size,
                         int(rng.randint(smin, smax))).astype(np.int32),
             int(rng.randint(mmin, mmax)))
            for _ in range(n)]
    engines = {"megafront": ServingEngine(model, max_slots=max_slots,
                                          page_size=page_size,
                                          ragged=True),
               "split_front": ServingEngine(
                   model, max_slots=max_slots, page_size=page_size,
                   ragged=True, megafront=False)}
    assert engines["megafront"].megafront
    assert not engines["split_front"].megafront

    def run(eng):
        for p, m in reqs:
            eng.add_request(p, max_new_tokens=m)
        eng.run_to_completion()

    useful = sum(m for _, m in reqs)
    for name, eng in engines.items():
        _log(f"front_half: warm {name}")
        run(eng)                       # compiles the path's programs
    ts = {name: [] for name in engines}
    for _ in range(rounds):            # same-run interleaved A/B
        for name, eng in engines.items():
            t0 = time.time()
            run(eng)
            ts[name].append(time.time() - t0)
    acct = engines["megafront"].hbm_accounting()

    # model-side launch ledger at the engine's own geometry
    n_layers = cfg.num_hidden_layers
    kw = dict(batch=max_slots, context=total // 2,
              hidden=cfg.hidden_size, heads=cfg.num_attention_heads,
              kv_heads=cfg.num_key_value_heads, head_dim=cfg.head_dim,
              intermediate=cfg.intermediate_size, page_size=page_size,
              weight_bytes_per_layer=int(
                  acct["weights_bytes"] / n_layers))
    mega_m = cm.decode_layer_kernels(**kw)
    split_m = cm.decode_layer_kernels(megafront=False, **kw)
    return dict(
        requests=len(reqs), max_slots=max_slots, page_size=page_size,
        useful_new_tokens=int(useful),
        fused_tokens_per_s=round(
            useful * rounds / sum(ts["megafront"]), 1),
        split_tokens_per_s=round(
            useful * rounds / sum(ts["split_front"]), 1),
        # per-round split_time/fused_time: >1 means the fusion wins
        fused_vs_split=ratio_band(ts["split_front"], ts["megafront"]),
        launches_per_layer={"megafront": mega_m["launches_per_layer"],
                            "split_front": split_m["launches_per_layer"]},
        front_half_launches={
            name: eng.front_half_launches
            for name, eng in engines.items()},
        layer_body_launches={
            name: eng.front_half_launches + 1 + eng.back_half_launches
            for name, eng in engines.items()},
        bytes_per_token_measured=round(
            acct["bytes_per_token_measured"]),
        bytes_per_token_model=round(acct["bytes_per_token_model"]),
        programs_compiled={name: eng.program_cache_sizes()
                           for name, eng in engines.items()},
        note="same trace, same model, same slots both ways; both "
             "engines keep the ISSUE-14 fused back half, so the A/B "
             "isolates the front half. launches_per_layer is the "
             "costmodel ledger at the engine's geometry (5 fused vs 8 "
             "split front), front_half_launches the engine's own count "
             "of pallas_calls before attention (2 vs 5). The byte "
             "ledger is fusion-invariant by construction — the fused "
             "kernel reads the same weight slabs and writes the same "
             "pages. CPU-host tok/s is not the record — the host step "
             "loop dominates tiny steps")


def bench_serving_engine(n=16, max_slots=8, page_size=16, rounds=3,
                         smin=64, smax=513, mmin=32, mmax=257, seed=0,
                         dtype="bfloat16"):
    """In-flight continuous batching (ServingEngine) vs static whole-batch
    generate_cached on the SAME mixed-length request trace, same run: the
    engine retires each row the step it finishes and backfills the slot
    from the queue; static batching decodes every batch until its slowest
    row finishes."""
    total = 1024
    _log(f"serving_engine: init model n={n} slots={max_slots}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(seed)
    reqs = [(rng.randint(0, cfg.vocab_size,
                         int(rng.randint(smin, smax))).astype(np.int32),
             int(rng.randint(mmin, mmax)))
            for _ in range(n)]
    _log("model built; running trace")
    return _serving_engine_row(model, cfg, reqs, max_slots, page_size,
                               rounds)


def _srv_metric(name):
    from paddle_tpu import serving as srv
    fam = srv.metrics().get(name)
    if not fam or not fam["series"]:
        return 0.0
    return fam["series"][0]["value"]


def bench_prefix_cache_multitenant(n_tenants=16, sys_len=256, tail_len=16,
                                   new=32, max_slots=4, page_size=16,
                                   dtype="bfloat16"):
    """Global radix prefix cache A/B (same model, same trace both ways):
    N tenants share one system prompt. Cache-ON admits every later
    tenant with the cached prefix pages adopted from the trie — only the
    per-tenant tail prefills; cache-OFF pays the full prompt prefill per
    tenant. Records the prompt-token hit rate and per-request TTFT both
    ways. Exactness under sharing is the test-suite contract
    (tests/test_prefix_cache.py)."""
    from paddle_tpu.serving import ServingEngine
    from bench_util import band, ratio_band

    total = 1024
    _log(f"prefix_cache_multitenant: init model tenants={n_tenants}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(0)
    system = rng.randint(0, cfg.vocab_size, sys_len).astype(np.int32)
    prompts = [np.concatenate([system,
                               rng.randint(0, cfg.vocab_size,
                                           tail_len).astype(np.int32)])
               for _ in range(n_tenants)]
    warm = rng.randint(0, cfg.vocab_size,
                       sys_len + tail_len).astype(np.int32)

    def run(enable):
        eng = ServingEngine(model, max_slots=max_slots,
                            page_size=page_size, prefix_sharing=False,
                            enable_prefix_cache=enable)
        eng.add_request(warm, max_new_tokens=4)   # compile untimed
        eng.run_to_completion()
        ttfts, shared, total_prompt = [], 0, 0
        t_all = time.time()
        for t, prompt in enumerate(prompts):
            r = eng.add_request(prompt, max_new_tokens=new,
                                tenant=f"tenant{t}")
            t0 = time.time()
            first = None
            while eng.has_work():
                if eng.step().get("decoded"):
                    first = time.time() - t0   # first token emitted
                    break
            eng.run_to_completion()
            ttfts.append(first if first is not None
                         else time.time() - t0)
            shared += r.shared_tokens
            total_prompt += prompt.size
        return ttfts, shared, total_prompt, time.time() - t_all, eng

    _log("prefix_cache_multitenant: cache ON trace")
    ttft_on, shared, total_prompt, wall_on, eng_on = run(True)
    _log("prefix_cache_multitenant: cache OFF trace")
    ttft_off, shared_off, _, wall_off, _ = run(False)
    useful = n_tenants * new
    return dict(
        tenants=n_tenants, system_prompt_tokens=sys_len,
        tail_tokens=tail_len, new_tokens_per_request=new,
        max_slots=max_slots, page_size=page_size,
        prompt_tokens=int(total_prompt),
        shared_prompt_tokens=int(shared),
        prefix_hit_rate=round(shared / total_prompt, 3),
        ttft_cache_on=band(ttft_on),
        ttft_cache_off=band(ttft_off),
        # per-request ttft_off/ttft_on: >1 means the cache cuts TTFT
        ttft_speedup=ratio_band(ttft_off, ttft_on),
        cache_on_tokens_per_s=round(useful / wall_on, 1),
        cache_off_tokens_per_s=round(useful / wall_off, 1),
        cache_off_shared_tokens=int(shared_off),
        programs_compiled=eng_on.program_cache_sizes(),
        note="sequential per-tenant requests so TTFT isolates the "
             "prefill each request actually paid; tenant 0 is the cold "
             "miss that populates the trie, tenants 1.. adopt its pages "
             "and prefill only the tail. CPU-host numbers are not the "
             "record — the host step loop dominates tiny steps")


def bench_spec_decode_b1(k=4, new=128, rounds=3, dtype="bfloat16"):
    """N-gram self-drafting speculative decode at B=1 (the latency
    shape): a repetitive-text prompt (seed extended with its own greedy
    continuation, the drafter's favorable regime), spec engine (k drafts
    verified in ONE ragged launch) vs plain token-at-a-time decode on
    the same model, same-run interleaved rounds. Records mean accepted
    tokens per verify step and tokens/s both ways — output exactness is
    the test-suite contract (tests/test_spec_decode.py)."""
    import paddle_tpu as paddle
    from paddle_tpu.generation import generate_cached
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.spec_decode import accept_length, ngram_draft
    from bench_util import ratio_band

    total = 1024
    _log(f"spec_decode_b1: init model k={k} new={new}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(0)
    seed = np.tile(rng.randint(0, cfg.vocab_size, 8).astype(np.int32), 3)
    cont, _ = generate_cached(model, paddle.to_tensor(seed[None]),
                              max_new_tokens=new + 48,
                              decode_strategy="greedy_search")
    c = [int(t) for t in cont.numpy()[0]]
    base = [int(t) for t in seed]
    # cut the prompt where its own greedy continuation is repetitive
    # (the repetitive-text trace this row measures): score each
    # candidate cut by the drafter's one-shot agreement with the known
    # greedy truth and take the best 16-step window — greedy
    # determinism makes the engine decode from seed+c[:cut] replay
    # c[cut:] exactly, so the score predicts the measured acceptance
    scores = [accept_length(ngram_draft(base + c[:p], k), c[p:p + k])
              for p in range(8, 49)]
    cut = 8 + max(range(len(scores) - 15),
                  key=lambda i: sum(scores[i:i + 16]))
    prompt = np.asarray(base + c[:cut], np.int32)

    engines = {"spec": ServingEngine(model, max_slots=1, page_size=16,
                                     spec_decode=k),
               "plain": ServingEngine(model, max_slots=1, page_size=16,
                                      spec_decode=0)}

    def run(eng):
        eng.add_request(prompt, max_new_tokens=new)
        eng.run_to_completion()

    for name, eng in engines.items():   # compile + warm the prefix trie
        _log(f"spec_decode_b1: warm {name}")
        run(eng)
    m0 = {kk: _srv_metric(f"serving.spec_decode.{kk}")
          for kk in ("draft_tokens", "accepted_tokens", "verify_steps")}
    ts = {"spec": [], "plain": []}
    for _ in range(rounds):             # same-run interleaved A/B
        for name, eng in engines.items():
            t0 = time.time()
            run(eng)
            ts[name].append(time.time() - t0)
    d = {kk: _srv_metric(f"serving.spec_decode.{kk}") - m0[kk]
         for kk in m0}
    vsteps = max(d["verify_steps"], 1.0)
    return dict(
        batch=1, draft_k=k, prompt_tokens=int(prompt.size),
        new_tokens=new, rounds=rounds,
        # the acceptance-bar stat: > 1 means each verify launch emits
        # more than one token on average (the speculative win)
        accepted_tokens_per_verify_step=round(
            d["accepted_tokens"] / vsteps, 2),
        draft_acceptance_rate=round(
            d["accepted_tokens"] / max(d["draft_tokens"], 1.0), 3),
        spec_tokens_per_s=round(new * rounds / sum(ts["spec"]), 1),
        plain_tokens_per_s=round(new * rounds / sum(ts["plain"]), 1),
        # per-round plain_time/spec_time: >1 means speculation wins
        spec_vs_plain=ratio_band(ts["plain"], ts["spec"]),
        programs_compiled=engines["spec"].program_cache_sizes(),
        note="metric deltas cover only the timed interleaved rounds "
             "(the plain engine drafts nothing, so the spec_decode.* "
             "movement is the spec engine's alone); tokens/s counts the "
             "requested new tokens. CPU-host numbers are not the record")


def bench_disaggregated(n_tenants=8, sys_len=128, tail_len=16, new=32,
                        max_slots=4, page_size=16, dtype="bfloat16"):
    """Disaggregated prefill/decode A/B (same model, same multitenant
    trace both ways): a 1-prefill + 1-decode replica fleet behind the
    FleetRouter — every request prefills on the prefill replica and
    crosses a KV-page handoff before its first decode step — vs ONE
    colocated engine. Tenants share a system prompt so the row also
    measures whether the prefill replica's radix trie keeps its
    prefill-skip rate under disaggregation. Records tokens/s, TTFT, and
    prefill-skip both ways plus the handoff count and mean latency.
    Output exactness across the handoff is the test-suite contract
    (tests/test_serving_engine.py::TestDisaggregated)."""
    from paddle_tpu.serving import FleetRouter, ServingEngine
    from bench_util import band, ratio_band

    total = 1024
    _log(f"disaggregated: init model tenants={n_tenants}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rng = np.random.RandomState(0)
    system = rng.randint(0, cfg.vocab_size, sys_len).astype(np.int32)
    prompts = [np.concatenate([system,
                               rng.randint(0, cfg.vocab_size,
                                           tail_len).astype(np.int32)])
               for _ in range(n_tenants)]
    warm = rng.randint(0, cfg.vocab_size,
                       sys_len + tail_len).astype(np.int32)

    def run(submit, step, drain, warmup):
        warmup()                           # compile untimed
        ttfts, shared, prompt_toks = [], 0, 0
        t_all = time.time()
        for t, prompt in enumerate(prompts):
            r = submit(prompt, t)
            t0 = time.time()
            first = None
            while first is None:
                if step().get("decoded"):
                    first = time.time() - t0   # first token emitted
            drain()
            ttfts.append(first)
            shared += r.shared_tokens
            prompt_toks += prompt.size
        return ttfts, shared, prompt_toks, time.time() - t_all

    _log("disaggregated: colocated trace")
    eng = ServingEngine(model, max_slots=max_slots, page_size=page_size)

    def _coloc_warm():
        eng.add_request(warm, max_new_tokens=4)
        eng.run_to_completion()
    ttft_c, shared_c, ptoks, wall_c = run(
        lambda p, t: eng.add_request(p, max_new_tokens=new,
                                     tenant=f"tenant{t}"),
        eng.step, eng.run_to_completion, _coloc_warm)

    _log("disaggregated: prefill+decode fleet trace")
    pf = ServingEngine(model, max_slots=max_slots, page_size=page_size,
                       role="prefill")
    dec = ServingEngine(model, max_slots=max_slots, page_size=page_size,
                        role="decode")
    router = FleetRouter({"prefill0": pf, "decode0": dec})

    def _fleet_warm():
        router.submit(warm, max_new_tokens=4)
        router.run_to_completion()
    ttft_d, shared_d, _, wall_d = run(
        lambda p, t: router.submit(p, max_new_tokens=new,
                                   tenant=f"tenant{t}"),
        router.step, router.run_to_completion, _fleet_warm)

    st = router.stats()
    useful = n_tenants * new
    return dict(
        tenants=n_tenants, system_prompt_tokens=sys_len,
        tail_tokens=tail_len, new_tokens_per_request=new,
        max_slots=max_slots, page_size=page_size,
        disagg_tokens_per_s=round(useful / wall_d, 1),
        colocated_tokens_per_s=round(useful / wall_c, 1),
        ttft_disagg=band(ttft_d),
        ttft_colocated=band(ttft_c),
        # per-request ttft_colocated/ttft_disagg: < 1 is the handoff tax
        ttft_ratio=ratio_band(ttft_c, ttft_d),
        prefill_skip_rate=round(shared_d / ptoks, 3),
        colocated_prefill_skip_rate=round(shared_c / ptoks, 3),
        handoffs=st["handoffs"],
        handoff_latency_ms=round(st["handoff_latency_s"] * 1e3, 2),
        programs_compiled={"prefill0": pf.program_cache_sizes(),
                           "decode0": dec.program_cache_sizes()},
        note="every fleet request pays one prefill→decode KV-page "
             "handoff before its first token; sequential per-tenant "
             "requests so TTFT isolates what each request actually "
             "paid. handoff_latency is export→import wall time "
             "(in-process host copy on CPU; DCN transfer on a real "
             "fleet). CPU-host numbers are not the record")


def bench_fleet_workloads(seed=0, dtype="bfloat16"):
    """Hostile-traffic scenario suite (ISSUE 16) on the real chip: the
    five seeded `paddle_tpu.serving.workloads` scenarios — burst,
    agentic multi-turn, long+short mix, cache-thrash, replica-kill
    chaos — each driving a fresh multi-replica fleet through the
    FleetRouter. The per-scenario rows land in the artifact verbatim
    (the tier-1 replica of this suite lives in docs/FLEET_BENCH.json
    via tools/fleetboard.py --selftest); the top-level aggregates are
    the worst case across scenarios, which is what an SLO burns down
    to."""
    from paddle_tpu.serving import workloads
    total = 1024
    _log(f"fleet_workloads: init model seed={seed}")
    cfg, model = _llama_bench_raw_model(total, dtype)
    rows = workloads.run_all(model, seed=seed)
    zero_loss = int(all(r["zero_loss"] for r in rows.values()))
    return dict(
        seed=seed, scenarios=rows,
        fleet_tokens_per_s=round(min(r["fleet_tokens_per_s"]
                                     for r in rows.values()), 2),
        fleet_zero_loss=zero_loss,
        fleet_handoffs=sum(r["handoffs"] for r in rows.values()),
        note="worst-scenario fleet throughput; per-scenario detail in "
             "'scenarios'. replica_kill asserts zero request loss and "
             "exact greedy outputs through a mid-burst drain")


def _paged_sweep_row():
    # the old single-shot paged_attention_op row is gone: it duplicated
    # sweep[0] and its pre-q-scaling-fix "bundled" number contradicted
    # the sweep (VERDICT r4 weak #2) — the sweep with bands is the record
    sweep = [bench_paged_kernel(ctx=c, page_size=p)
             for c in (4096, 8192, 16384) for p in (16, 32)]
    return dict(paged_attention_sweep=sweep,
                paged_attention_sweep_note=_sweep_note(sweep))


# One entry per artifact row. Latency point (B=1) and a fatter-batch
# point: decode tok/s scales with B until the KV reads pass the weight
# reads in the roofline denominator. int8/int4/bf16_ref use
# decode-dominated lengths (the prefill-subtraction method needs the
# decode phase to dwarf prefill noise).
ROWS = {
    "decode": lambda: bench_decode(),
    "decode_b1": lambda: bench_decode(B=1, S0=1024, new=256),
    "decode_b16": lambda: bench_decode(B=16, S0=1024, new=256),
    "decode_int8": lambda: bench_decode(B=8, S0=256, new=1024,
                                        weight_only_int8=True),
    "decode_int4": lambda: bench_decode(B=8, S0=256, new=1024,
                                        weight_only_quant="int4"),
    "decode_bf16_ref": lambda: bench_decode(B=8, S0=256, new=1024),
    "moe_decode": lambda: bench_moe_decode(),
    "moe_decode_int8": lambda: bench_moe_decode(weight_only_int8=True),
    "mla_decode": lambda: bench_mla_decode(),
    "mla_decode_int8": lambda: bench_mla_decode(weight_only_int8=True),
    "mla_context_sweep": lambda: bench_mla_context_sweep(),
    "prefill_8k_llama": lambda: bench_prefill_long("llama"),
    "prefill_8k_mla": lambda: bench_prefill_long("mla"),
    "serving_engine": lambda: bench_serving_engine(),
    "serving_engine_ragged": lambda: bench_serving_engine_ragged(),
    "megadecode": lambda: bench_megadecode(),
    "front_half": lambda: bench_front_half(),
    "prefix_cache_multitenant": lambda: bench_prefix_cache_multitenant(),
    "spec_decode_b1": lambda: bench_spec_decode_b1(),
    "disaggregated": lambda: bench_disaggregated(),
    "fleet_workloads": lambda: bench_fleet_workloads(),
    "_paged": _paged_sweep_row,
}

_ROW_MARK = "__ROW_JSON__"


def main():
    import subprocess
    if "--probe" in sys.argv:
        import jax
        print(_ROW_MARK + json.dumps(
            dict(device=str(jax.devices()[0].device_kind),
                 on_tpu=jax.devices()[0].platform != "cpu",
                 hbm_bw_used=_bw())))
        return
    if "--row" in sys.argv:
        name = sys.argv[sys.argv.index("--row") + 1]
        print(_ROW_MARK + json.dumps(ROWS[name]()))
        return
    # the parent must NEVER initialize jax: on a real chip the client
    # holds the libtpu lock and every child row would fail to attach —
    # probe device facts through a subprocess like everything else
    probe = _run_row(["--probe"])
    if probe is None:
        # a dead probe must not let a 40-minute run silently discard its
        # artifact at the end — fail NOW
        print("device probe failed — aborting before any rows run",
              file=sys.stderr)
        sys.exit(1)
    on_tpu = bool(probe.get("on_tpu"))
    if not on_tpu:
        print("WARNING: no TPU — numbers are CPU-host and not the record",
              file=sys.stderr)
    report = dict(device=probe.get("device", "unknown"),
                  hbm_bw_used=probe.get("hbm_bw_used"),
                  measurement_protocol="each row runs in its OWN process: "
                  "rows measured after unrelated models/executables "
                  "accumulated on the chip showed 2x bimodal spikes on "
                  "the fused-program side only (r5 — 74-86% spread vs "
                  "0.2% standalone); per-row isolation reproduces the "
                  "standalone conditions every time")
    failed = []
    for name in ROWS:
        _log(f"row {name}: spawning")
        val = _run_row(["--row", name])
        if val is None:
            failed.append(name)
            continue
        if name == "_paged":
            report.update(val)
        else:
            report[name] = val
    out = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "SERVING_BENCH.json")
    if failed:
        # never clobber the committed record with a partial report
        print(f"FAILED rows {failed} — artifact NOT written", file=sys.stderr)
        print(json.dumps(report, indent=2))
        sys.exit(1)
    if on_tpu:
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


def _run_row(args):
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, os.path.abspath(__file__)] + args,
                       capture_output=True, text=True, env=env)
    line = next((ln for ln in r.stdout.splitlines()
                 if ln.startswith(_ROW_MARK)), None)
    if line is None:
        _log(f"{args} FAILED:\n{r.stderr[-2000:]}")
        return None
    return json.loads(line[len(_ROW_MARK):])


if __name__ == "__main__":
    main()
