"""Llama model family (ref capability: PaddleNLP
paddlenlp/transformers/llama/modeling.py — the Llama-3-8B pretrain baseline,
SURVEY §2.4 config 2).

TPU-first design:
- weights carry Megatron-pattern sharding specs (qkv/up: column on mp;
  o/down: row on mp; embeddings: vocab on mp) — GSPMD derives the per-layer
  collectives the reference's ColumnParallelLinear/RowParallelLinear issue.
- activations get sequence-parallel constraints between blocks (P5) and a
  dp/fsdp batch constraint at the top.
- attention is GQA through scaled_dot_product_attention (flash-routable);
  rope is fused-ready (paddle_tpu.ops).
- fsdp (ZeRO-3) is a spec choice on the same weights (dim-0 on "sharding").
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.parallel_layers import MP_AXIS

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "llama3_8b_config", "llama_tiny_config", "apply_rope",
           "precompute_rope"]


class LlamaConfig:
    def __init__(self, vocab_size=128256, hidden_size=4096,
                 intermediate_size=14336, num_hidden_layers=32,
                 num_attention_heads=32, num_key_value_heads=8,
                 max_position_embeddings=8192, rope_theta=500000.0,
                 rms_norm_eps=1e-5, initializer_range=0.02,
                 tie_word_embeddings=False, use_flash_attention=True,
                 sequence_parallel=True, recompute=False,
                 context_parallel=False, fuse_attention_qkv=False,
                 fuse_attention_ffn=False, fuse_pack_groups=1,
                 head_dim=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads
        self.max_position_embeddings = max_position_embeddings
        self.rope_theta = rope_theta
        self.rms_norm_eps = rms_norm_eps
        self.initializer_range = initializer_range
        self.tie_word_embeddings = tie_word_embeddings
        self.use_flash_attention = use_flash_attention
        self.sequence_parallel = sequence_parallel
        self.recompute = recompute
        self.context_parallel = context_parallel
        # PaddleNLP parity knobs: pack q/k/v (and gate/up) into single
        # matmuls — fewer kernel launches, one MXU pass over the activations
        self.fuse_attention_qkv = fuse_attention_qkv
        self.fuse_attention_ffn = fuse_attention_ffn
        # rank-interleave group count for the packed layouts. Set it to the
        # mp degree when training with TP so the packed q|k|v (and gate|up)
        # slice boundaries stay shard-local. An EXPLICIT config knob — not
        # sniffed from the ambient mesh — so rebuilding a model from the
        # same config always reproduces the same weight layout
        # (checkpoints are layout-compatible iff fuse_pack_groups matches).
        self.fuse_pack_groups = fuse_pack_groups
        # explicit head_dim decouples attention width from hidden_size —
        # needed to model a TP shard (heads/mp heads of the ORIGINAL
        # head_dim over the full hidden residual stream)
        self.head_dim = head_dim if head_dim is not None \
            else hidden_size // num_attention_heads


def llama3_8b_config(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama3_8b_shard_config(mp: int = 8, pp: int = 4, **kw) -> LlamaConfig:
    """The per-chip model an mp×pp-partitioned Llama-3-8B places on ONE
    chip (ref: PaddleNLP llm/run_pretrain.py hybrid configs): layers/pp
    decoder layers whose attention holds heads/mp query heads (kv heads
    likewise, min 1) of the true head_dim 128, FFN width 14336/mp, and a
    vocab-parallel slice 128256/mp of the embedding/CE. Benchmarking this
    config single-chip measures the MXU efficiency of the flagship's
    per-chip computation (collectives excluded — accounted separately in
    docs/FLAGSHIP.md)."""
    full = llama3_8b_config()
    base = dict(
        vocab_size=full.vocab_size // mp,
        hidden_size=full.hidden_size,
        intermediate_size=full.intermediate_size // mp,
        num_hidden_layers=full.num_hidden_layers // pp,
        num_attention_heads=max(full.num_attention_heads // mp, 1),
        num_key_value_heads=max(full.num_key_value_heads // mp, 1),
        head_dim=full.head_dim,
        max_position_embeddings=full.max_position_embeddings,
        rope_theta=full.rope_theta)
    base.update(kw)
    return LlamaConfig(**base)


def llama_tiny_config(**kw) -> LlamaConfig:
    base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256,
                rope_theta=10000.0)
    base.update(kw)
    return LlamaConfig(**base)


def precompute_rope(head_dim: int, max_seq: int, theta: float):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [S, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin):
    """x: [B, S, H, D] raw array; fused-rope parity
    (ref: fused_rotary_position_embedding / FusedRopeKernel)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    c = cos[None, :x.shape[1], None, :].astype(x.dtype)
    s = sin[None, :x.shape[1], None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _mp_linear(in_f, out_f, spec):
    """Bias-free linear with a Megatron TP sharding spec attached."""
    l = nn.Linear(in_f, out_f, bias_attr=False)
    l.weight._sharding_spec = spec
    return l


def _init_packed_segments(weight, segments):
    """Re-initialize a packed [in, sum(widths)] weight per column segment.
    segments: [(width, logical_fan_out)] — each segment gets the Xavier std
    of the LOGICAL unfused projection it belongs to (q segments use fan
    H*D regardless of grouping), so flipping the fuse knobs is
    numerics-neutral at init (a single XavierNormal over the packed width
    would under-scale every segment)."""
    import math as _m
    in_f = weight.shape[0]
    dt = weight._data.dtype
    cols = []
    for w, fan_out in segments:
        std = _m.sqrt(2.0 / (in_f + fan_out))
        cols.append(I.Normal(0.0, std)([in_f, w], "float32"))
    weight._data = jnp.concatenate(cols, axis=1).astype(dt)


class LlamaAttention(nn.Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.c = c
        H, D = c.num_attention_heads, c.head_dim
        KV = c.num_key_value_heads
        if c.fuse_attention_qkv:
            # One packed projection, RANK-INTERLEAVED layout
            # [g blocks of (H/g q-heads | KV/g k-heads | KV/g v-heads)]
            # with g = cfg.fuse_pack_groups (set to the mp degree for TP):
            # the q|k|v slice boundaries then fall on shard boundaries, so
            # under tensor parallelism the slices stay shard-local
            # (Megatron's fused-qkv layout rationale — a column-major
            # [all-q|all-k|all-v] pack would force GSPMD to reshard
            # activations at every slice). Weights are framework-native
            # (not PaddleNLP-binary-compatible; a converter must re-pack).
            g = c.fuse_pack_groups
            if H % g or KV % g:
                raise ValueError(
                    f"fuse_attention_qkv requires heads divisible by "
                    f"fuse_pack_groups: H={H}, KV={KV}, groups={g}")
            self._qkv_groups = g
            self.qkv_proj = _mp_linear(c.hidden_size, (H + 2 * KV) * D,
                                       P(None, MP_AXIS))
            _init_packed_segments(
                self.qkv_proj.weight,
                [(H // g * D, H * D), (KV // g * D, KV * D),
                 (KV // g * D, KV * D)] * g)
        else:
            # Megatron TP: qkv column-sharded, o row-sharded on mp
            self.q_proj = _mp_linear(c.hidden_size, H * D, P(None, MP_AXIS))
            self.k_proj = _mp_linear(c.hidden_size, KV * D, P(None, MP_AXIS))
            self.v_proj = _mp_linear(c.hidden_size, KV * D, P(None, MP_AXIS))
        self.o_proj = _mp_linear(H * D, c.hidden_size, P(MP_AXIS, None))

    def forward(self, x, cos, sin, attn_mask=None):
        c = self.c
        B, S, _ = x.shape
        H, KV, D = c.num_attention_heads, c.num_key_value_heads, c.head_dim
        from ..core.dispatch import apply as _apply
        from ..core.tensor import Tensor as _T
        # mask is data (non-diff): closed over, not a tape input. Boolean
        # key-padding masks route to the fused segment-id kernel in sdpa.
        mask_arr = attn_mask._data if isinstance(attn_mask, _T) \
            else (None if attn_mask is None else jnp.asarray(attn_mask))
        if mask_arr is not None and c.context_parallel:
            raise NotImplementedError(
                "attn_mask with context_parallel ring attention: pack "
                "sequences via sdpa_segmented/flashmask instead")

        def finish(q, k, v, wo):
            """rope → attention → output projection (shared tail)."""
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            rep = H // KV
            if rep > 1:
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            from ..ops.flash_attention import sdpa, sdpa_reference
            if c.context_parallel:
                # ring attention over the sep axis (P9): seq stays sharded,
                # KV blocks rotate via collective-permute
                from ..distributed.ring_attention import ring_attention_raw
                o = ring_attention_raw(q, k, v, axis="sep", causal=True)
            elif c.use_flash_attention:
                o = sdpa(q, k, v, mask=mask_arr, causal=True)
            else:
                o = sdpa_reference(q, k, v, mask=mask_arr, causal=True)
            return o.reshape(B, S, -1) @ wo

        if c.fuse_attention_qkv:
            g = self._qkv_groups
            Hg, KVg = H // g, KV // g

            def impl(h, wqkv, wo):
                # [B,S,g,(Hg+2KVg),D]: dim 2 is the shard (rank) dim, so
                # the q|k|v slices below are shard-local under mp
                qkv = (h @ wqkv).reshape(B, S, g, Hg + 2 * KVg, D)
                q = qkv[:, :, :, :Hg].reshape(B, S, H, D)
                k = qkv[:, :, :, Hg:Hg + KVg].reshape(B, S, KV, D)
                v = qkv[:, :, :, Hg + KVg:].reshape(B, S, KV, D)
                # head order is group-major for q AND kv consistently, and
                # jnp.repeat on the flat kv axis maps q head (g_i, h_j) to
                # kv head (g_i, h_j // (Hg/KVg)) — GQA grouping preserved
                return finish(q, k, v, wo)
            return _apply("llama_attention", impl,
                          [x, self.qkv_proj.weight, self.o_proj.weight])

        def impl(h, wq, wk, wv, wo):
            q = (h @ wq).reshape(B, S, H, D)
            k = (h @ wk).reshape(B, S, KV, D)
            v = (h @ wv).reshape(B, S, KV, D)
            return finish(q, k, v, wo)
        return _apply("llama_attention", impl,
                      [x, self.q_proj.weight, self.k_proj.weight,
                       self.v_proj.weight, self.o_proj.weight])


class LlamaMLP(nn.Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.c = c
        if c.fuse_attention_ffn:
            # packed rank-interleaved [g blocks of (gate_g | up_g)] — same
            # grouping rationale as fused qkv: the silu(gate)*up elementwise
            # product pairs columns within one shard block, so no cross-
            # shard resharding of the intermediate activation under mp
            # (capability parity: PaddleNLP fuse_attention_ffn; layout is
            # framework-native)
            g = c.fuse_pack_groups
            if c.intermediate_size % g:
                raise ValueError(
                    f"fuse_attention_ffn requires intermediate_size "
                    f"divisible by fuse_pack_groups={g}")
            self._ffn_groups = g
            self.gate_up_proj = _mp_linear(c.hidden_size,
                                           2 * c.intermediate_size,
                                           P(None, MP_AXIS))
            I_ = c.intermediate_size
            _init_packed_segments(
                self.gate_up_proj.weight,
                [(I_ // g, I_), (I_ // g, I_)] * g)
        else:
            self.gate_proj = _mp_linear(c.hidden_size, c.intermediate_size,
                                        P(None, MP_AXIS))
            self.up_proj = _mp_linear(c.hidden_size, c.intermediate_size,
                                      P(None, MP_AXIS))
        self.down_proj = _mp_linear(c.intermediate_size, c.hidden_size,
                                    P(MP_AXIS, None))

    def forward(self, x):
        if self.c.fuse_attention_ffn:
            c = self.c
            g, Ig = self._ffn_groups, c.intermediate_size // self._ffn_groups
            gu = self.gate_up_proj(x)
            if g == 1:
                # single-arg swiglu splits [gate | up] internally
                return self.down_proj(F.swiglu(gu))
            # grouped layout: split per block, then flatten back to [.., I]
            shp = gu.shape[:-1]
            gu = gu.reshape(list(shp) + [g, 2 * Ig])
            gate = gu[..., :Ig].reshape(list(shp) + [c.intermediate_size])
            up = gu[..., Ig:].reshape(list(shp) + [c.intermediate_size])
            return self.down_proj(F.swiglu(gate, up))
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, c: LlamaConfig):
        super().__init__()
        self.c = c
        self.input_layernorm = nn.RMSNorm(c.hidden_size, c.rms_norm_eps)
        self.self_attn = LlamaAttention(c)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   c.rms_norm_eps)
        self.mlp = LlamaMLP(c)

    def forward(self, x, cos, sin, attn_mask=None):
        from ..distributed.parallel_layers import annotate_sequence_parallel
        h = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        if self.c.sequence_parallel:
            h = annotate_sequence_parallel(h)
        out = h + self.mlp(self.post_attention_layernorm(h))
        if self.c.sequence_parallel:
            out = annotate_sequence_parallel(out)
        return out


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.embed_tokens.weight._data = init(
            [config.vocab_size, config.hidden_size], "float32")
        self.embed_tokens.weight._sharding_spec = P(MP_AXIS, None)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        cos, sin = precompute_rope(config.head_dim,
                                   config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        cos, sin = self.rope_cos._data, self.rope_sin._data
        for layer in self.layers:
            if self.config.recompute and self.training:
                from ..distributed.recompute import recompute
                x = recompute(layer, x, cos, sin, attn_mask)
            else:
                x = layer(x, cos, sin, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
            self.lm_head.weight._sharding_spec = P(None, MP_AXIS)

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.llama(input_ids, attn_mask)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = F.linear(h, self.llama.embed_tokens.weight.T)
        if labels is not None:
            from ..distributed.parallel_layers import ParallelCrossEntropy
            loss_fn = ParallelCrossEntropy()
            tok_loss = loss_fn(logits, labels)
            return tok_loss.mean(), logits
        return logits
