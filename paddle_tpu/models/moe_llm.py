"""MoE decoder LM family (Qwen2-MoE / DeepSeekMoE pattern).

Reference capability: PaddleNLP paddlenlp/transformers/{qwen2_moe,deepseek_v2}
(SURVEY §2.4 — MoE decoder layers with expert parallel via alltoall, shared
expert, aux load-balance loss). TPU-native: the routed experts are stacked
weights sharded on the `ep` mesh axis; dispatch/combine einsums lower to
GSPMD all-to-all (see paddle_tpu.incubate.moe).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

from .. import nn
from ..incubate.moe import MoELayer
from ..distributed.parallel_layers import MP_AXIS, ParallelCrossEntropy
from .llama import (LlamaAttention, LlamaConfig, LlamaMLP, precompute_rope)
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I

__all__ = ["MoEConfig", "MoEDecoderLayer", "MoEModel", "MoEForCausalLM",
           "qwen2_moe_tiny_config"]


class MoEConfig(LlamaConfig):
    """Llama backbone + MoE FFN knobs (moe_intermediate_size per expert,
    shared_expert_intermediate_size, num_experts, top_k, router aux weight;
    dense first-k layers DeepSeek-style via first_k_dense_replace)."""

    def __init__(self, num_experts=8, top_k=2, moe_intermediate_size=None,
                 shared_expert_intermediate_size=0, capacity_factor=1.25,
                 aux_loss_weight=0.01, router_z_loss_weight=0.0,
                 first_k_dense_replace=0, moe_dropless=False, **kw):
        super().__init__(**kw)
        self.num_experts = num_experts
        self.top_k = top_k
        self.moe_intermediate_size = (moe_intermediate_size
                                      or self.intermediate_size)
        self.shared_expert_intermediate_size = shared_expert_intermediate_size
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.router_z_loss_weight = router_z_loss_weight
        self.first_k_dense_replace = first_k_dense_replace
        self.moe_dropless = moe_dropless


def qwen2_moe_tiny_config(**kw) -> MoEConfig:
    base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256,
                rope_theta=10000.0, num_experts=4, top_k=2,
                moe_intermediate_size=64,
                shared_expert_intermediate_size=64)
    base.update(kw)
    return MoEConfig(**base)


class MoEDecoderLayer(nn.Layer):
    def __init__(self, c: MoEConfig, layer_idx: int = 0):
        super().__init__()
        self.c = c
        self.input_layernorm = nn.RMSNorm(c.hidden_size, c.rms_norm_eps)
        self.self_attn = LlamaAttention(c)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   c.rms_norm_eps)
        if layer_idx < c.first_k_dense_replace:
            self.mlp = LlamaMLP(c)
        else:
            self.mlp = MoELayer(
                c.hidden_size, c.moe_intermediate_size, c.num_experts,
                top_k=c.top_k, capacity_factor=c.capacity_factor,
                activation="swiglu", dropless=c.moe_dropless,
                shared_expert_hidden=c.shared_expert_intermediate_size,
                z_loss_weight=c.router_z_loss_weight)

    def forward(self, x, cos, sin, attn_mask=None):
        from ..distributed.parallel_layers import annotate_sequence_parallel
        h = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        if self.c.sequence_parallel:
            h = annotate_sequence_parallel(h)
        out = h + self.mlp(self.post_attention_layernorm(h))
        if self.c.sequence_parallel:
            out = annotate_sequence_parallel(out)
        return out


class MoEModel(nn.Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size)
        self.embed_tokens.weight._data = init(
            [config.vocab_size, config.hidden_size], "float32")
        self.embed_tokens.weight._sharding_spec = P(MP_AXIS, None)
        self.layers = nn.LayerList(
            [MoEDecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        cos, sin = precompute_rope(config.head_dim,
                                   config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def aux_loss(self):
        """Sum of router aux losses recorded during the last forward."""
        total = None
        for layer in self.layers:
            la = getattr(layer.mlp, "l_aux", None)
            if la is not None:
                total = la if total is None else total + la
        return total

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        cos, sin = self.rope_cos._data, self.rope_sin._data
        for layer in self.layers:
            if self.config.recompute and self.training:
                from ..distributed.recompute import recompute
                x = recompute(layer, x, cos, sin, attn_mask)
            else:
                x = layer(x, cos, sin, attn_mask)
        return self.norm(x)


class MoEForCausalLM(nn.Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        self.config = config
        self.model = MoEModel(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 bias_attr=False)
        self.lm_head.weight._sharding_spec = P(None, MP_AXIS)

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.model(input_ids, attn_mask)
        logits = self.lm_head(h)
        if labels is not None:
            loss_fn = ParallelCrossEntropy()
            tok_loss = loss_fn(logits, labels)
            loss = tok_loss.mean()
            aux = self.model.aux_loss()
            if aux is not None and self.config.aux_loss_weight:
                loss = loss + aux * self.config.aux_loss_weight
            return loss, logits
        return logits
