"""PP-OCR-style detection + recognition models (SURVEY §2.4 config 4).

Reference capability: PaddleOCR PP-OCRv4 det+rec — MobileNetV3/PP-LCNet
backbones, DB (Differentiable Binarization) detection head, CTC recognition
head (SVTR-lite style), warpctc loss (here: the native extended-label
forward-lattice CTC in paddle_tpu.nn.functional.ctc_loss — an XLA scan
over the 2S+1 lattice replaces the warpctc external). These conv-heavy CNNs are the non-transformer canary for the
framework (SURVEY §7.2 item 5): NCHW user API, XLA retiles for the MXU.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..nn import functional as F
from ..tensor.manipulation import concat
from ..vision.models import MobileNetV3Small, _make_divisible

__all__ = ["DBHead", "DBFPN", "PPOCRDet", "CTCHead", "PPOCRRec",
           "db_postprocess", "db_loss"]


# ---------------------------------------------------------------------------
# detection: backbone -> FPN neck -> DB head
# ---------------------------------------------------------------------------

class DBFPN(nn.Layer):
    """Lite FPN neck (ref: PaddleOCR ppocr/modeling/necks/db_fpn.py):
    laterals 1x1 -> top-down upsample+add -> 3x3 smooth -> concat."""

    def __init__(self, in_channels: List[int], out_channels: int = 96):
        super().__init__()
        self.out_channels = out_channels
        self.lat = nn.LayerList([
            nn.Conv2D(c, out_channels, 1, bias_attr=False)
            for c in in_channels])
        self.smooth = nn.LayerList([
            nn.Conv2D(out_channels, out_channels // 4, 3, padding=1,
                      bias_attr=False)
            for _ in in_channels])

    def forward(self, feats):
        lats = [l(f) for l, f in zip(self.lat, feats)]
        # top-down pathway
        for i in range(len(lats) - 1, 0, -1):
            up = F.interpolate(lats[i], size=lats[i - 1].shape[2:],
                               mode="nearest")
            lats[i - 1] = lats[i - 1] + up
        outs = []
        target = lats[0].shape[2:]
        for s, l in zip(self.smooth, lats):
            o = s(l)
            if tuple(o.shape[2:]) != tuple(target):
                o = F.interpolate(o, size=target, mode="nearest")
            outs.append(o)
        return concat(outs, axis=1)


class DBHead(nn.Layer):
    """Differentiable Binarization head (ref: ppocr/modeling/heads/
    det_db_head.py): probability + threshold maps, fused into the binary map
    b = 1/(1+exp(-k(p-t)))."""

    def __init__(self, in_channels: int, k: int = 50):
        super().__init__()
        self.k = k
        mid = in_channels // 4

        def branch():
            return nn.Sequential(
                nn.Conv2D(in_channels, mid, 3, padding=1, bias_attr=False),
                nn.BatchNorm2D(mid), nn.ReLU(),
                nn.Conv2DTranspose(mid, mid, 2, stride=2),
                nn.BatchNorm2D(mid), nn.ReLU(),
                nn.Conv2DTranspose(mid, 1, 2, stride=2),
                nn.Sigmoid())
        self.prob = branch()
        self.thresh = branch()

    def forward(self, x):
        p = self.prob(x)
        if not self.training:
            return {"maps": p}
        t = self.thresh(x)
        from ..core.dispatch import apply

        def bin_map(pa, ta):
            return 1.0 / (1.0 + jnp.exp(-self.k * (pa - ta)))
        b = apply("db_binarize", bin_map, [p, t])
        return {"maps": concat([p, t, b], axis=1)}


class PPOCRDet(nn.Layer):
    """MobileNetV3 backbone + DBFPN + DBHead."""

    def __init__(self, in_channels: int = 3, scale: float = 0.5):
        super().__init__()
        self.backbone = MobileNetV3Small(
            num_classes=0, with_pool=False, in_channels=in_channels,
            scale=scale, feature_only=True, out_indices=(0, 3, 8, 10))
        chans = [_make_divisible(16 * scale), _make_divisible(40 * scale),
                 _make_divisible(96 * scale), _make_divisible(96 * scale)]
        self.neck = DBFPN(chans, out_channels=96)
        self.head = DBHead(96)

    def forward(self, x):
        feats = self.backbone(x)
        return self.head(self.neck(feats))


def db_loss(preds, shrink_map, shrink_mask, thresh_map=None,
            thresh_mask=None, alpha: float = 5.0, beta: float = 10.0,
            ohem_ratio: float = 3.0, eps: float = 1e-6):
    """DB training loss (ref: ppocr/losses/det_db_loss.py +
    det_basic_loss.py): hard-negative-mined BCE on the probability map
    (x alpha), masked L1 on the threshold map (x beta), dice loss on the
    differentiable binary map. `preds` is the training-mode DBHead output
    ([B, 3, H, W] prob/thresh/binary); shrink_map is the {0,1} text-region
    target, shrink_mask the valid-pixel mask, thresh_map/thresh_mask the
    border-band threshold target (both optional — without them only the
    prob + binary terms apply, as when the head runs prob-only)."""
    from ..core.dispatch import apply

    def arr(v):
        return v._data if isinstance(v, Tensor) else jnp.asarray(v)
    sm = arr(shrink_map).astype(jnp.float32)
    mk = arr(shrink_mask).astype(jnp.float32)
    tm = None if thresh_map is None else arr(thresh_map).astype(jnp.float32)
    tk = None if thresh_mask is None else arr(thresh_mask).astype(jnp.float32)

    def impl(maps):
        p = maps[:, 0].astype(jnp.float32)
        bce = -(sm * jnp.log(jnp.clip(p, eps, None))
                + (1 - sm) * jnp.log(jnp.clip(1 - p, eps, None)))
        pos = sm * mk
        neg = (1 - sm) * mk
        n_pos = pos.sum()
        # OHEM: keep the ohem_ratio*n_pos hardest negatives (jit-safe
        # rank-mask over the sorted losses — no dynamic shapes)
        k = jnp.minimum(neg.sum(), ohem_ratio * n_pos)
        neg_sorted = jnp.sort((bce * neg).reshape(-1))[::-1]
        neg_sum = jnp.where(jnp.arange(neg_sorted.size) < k,
                            neg_sorted, 0.0).sum()
        loss_prob = ((bce * pos).sum() + neg_sum) / (n_pos + k + eps)
        total = alpha * loss_prob
        if maps.shape[1] >= 3:
            b = maps[:, 2].astype(jnp.float32)
            inter = (b * sm * mk).sum()
            union = (b * mk).sum() + (sm * mk).sum()
            total = total + (1.0 - 2.0 * inter / (union + eps))
            if tm is not None:
                t = maps[:, 1].astype(jnp.float32)
                w = tk if tk is not None else jnp.ones_like(tm)
                total = total + beta * ((jnp.abs(t - tm) * w).sum()
                                        / (w.sum() + eps))
        return total
    return apply("db_loss", impl, [preds])


def db_postprocess(prob_map, thresh: float = 0.3, min_area: int = 4):
    """Minimal DB postprocess: binarize + connected-component boxes on host
    (ref: ppocr/postprocess/db_postprocess.py; the reference uses pyclipper —
    here a numpy flood-fill bounding-box pass keeps it dependency-free)."""
    import numpy as np
    pm = np.asarray(prob_map)
    if pm.ndim == 4:
        pm = pm[0, 0]
    binm = (pm > thresh).astype(np.uint8)
    H, W = binm.shape
    seen = np.zeros_like(binm, bool)
    boxes = []
    for i in range(H):
        for j in range(W):
            if binm[i, j] and not seen[i, j]:
                stack = [(i, j)]
                seen[i, j] = True
                ys, xs = [], []
                while stack:
                    y, x = stack.pop()
                    ys.append(y)
                    xs.append(x)
                    for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
                        ny, nx = y + dy, x + dx
                        if 0 <= ny < H and 0 <= nx < W and binm[ny, nx] \
                                and not seen[ny, nx]:
                            seen[ny, nx] = True
                            stack.append((ny, nx))
                if len(ys) >= min_area:
                    boxes.append((min(xs), min(ys), max(xs), max(ys)))
    return boxes


# ---------------------------------------------------------------------------
# recognition: backbone -> seq encoder -> CTC head
# ---------------------------------------------------------------------------

class CTCHead(nn.Layer):
    """ref: ppocr/modeling/heads/rec_ctc_head.py — linear projection to the
    charset, log-softmax over classes; trained with CTC."""

    def __init__(self, in_channels: int, num_classes: int, mid: int = 0):
        super().__init__()
        if mid:
            self.fc = nn.Sequential(nn.Linear(in_channels, mid), nn.ReLU(),
                                    nn.Linear(mid, num_classes))
        else:
            self.fc = nn.Linear(in_channels, num_classes)

    def forward(self, x):
        return self.fc(x)  # [B, T, num_classes] logits


class SVTRMixerBlock(nn.Layer):
    """One SVTR mixing block (ref: ppocr/modeling/necks/rn_svtr.py /
    SVTRNet blocks): pre-LN -> token mixing -> residual -> pre-LN ->
    MLP -> residual. mixing="Global" is standard MHA over the column
    sequence; mixing="Local" restricts attention to a +-window band
    (the SVTR local-mixing mask), capturing stroke-level features."""

    def __init__(self, dim: int, num_heads: int = 8,
                 mixing: str = "Global", local_k: int = 7,
                 mlp_ratio: float = 2.0):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn = nn.MultiHeadAttention(dim, num_heads)
        self.norm2 = nn.LayerNorm(dim)
        mid = int(dim * mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(dim, mid), nn.GELU(),
                                 nn.Linear(mid, dim))
        self.mixing = mixing
        self.local_k = local_k
        self._mask_cache = {}

    def _local_mask(self, T: int):
        if T not in self._mask_cache:  # static per (T, local_k)
            i = jnp.arange(T)
            band = jnp.abs(i[:, None] - i[None, :]) <= self.local_k // 2
            # additive mask, [1, 1, T, T]
            self._mask_cache[T] = Tensor(
                jnp.where(band, 0.0, -1e9)[None, None]
                .astype(jnp.float32))
        return self._mask_cache[T]

    def forward(self, x):
        T = x.shape[1]
        mask = self._local_mask(T) if self.mixing == "Local" else None
        h = self.norm1(x)
        x = x + self.attn(h, h, h, attn_mask=mask)
        return x + self.mlp(self.norm2(x))


class PPOCRRec(nn.Layer):
    """Text recognizer: conv backbone squeezing height -> per-column
    features -> SVTR mixing blocks (local + global attention) -> CTC
    head (ref: PP-OCRv4 rec = backbone + SVTR neck + CTC)."""

    def __init__(self, num_classes: int = 97, in_channels: int = 3,
                 scale: float = 0.5, hidden: int = 120,
                 mixer: tuple = ("Local", "Global"), num_heads: int = 8):
        super().__init__()
        # rec_mode: height-only downsampling in the blocks (PaddleOCR
        # rec backbone) — the CTC time axis is W/2 columns; the old
        # symmetric strides left W/32 steps, fewer than most labels
        self.backbone = MobileNetV3Small(
            num_classes=0, with_pool=False, in_channels=in_channels,
            scale=scale, feature_only=True, out_indices=(10,),
            rec_mode=True)
        cback = _make_divisible(96 * scale)
        self.squeeze = nn.Conv2D(cback, hidden, 1, bias_attr=False)
        self.mix = nn.Sequential(*[
            SVTRMixerBlock(hidden, num_heads, mixing=m) for m in mixer])
        self.head = CTCHead(hidden, num_classes)

    def forward(self, x):
        f = self.backbone(x)[0]          # [B, C, H', W']
        f = self.squeeze(f)              # [B, hid, H', W']
        f = f.mean(axis=2)               # pool height -> [B, hid, W']
        f = f.transpose([0, 2, 1])       # [B, T=W', hid]
        f = self.mix(f)
        return self.head(f)              # [B, T, classes]

    def loss(self, logits, labels, label_lengths):
        """CTC loss (ref: warpctc externals — native extended-label
        forward lattice in nn.functional.ctc_loss)."""
        B, T, C = logits.shape
        from ..core.tensor import Tensor
        input_lens = Tensor(jnp.full((B,), T, jnp.int32))
        # ctc_loss log-softmaxes internally ([T, B, C] paddle convention)
        return F.ctc_loss(logits.transpose([1, 0, 2]), labels,
                          input_lens, label_lengths, blank=0,
                          reduction="mean")
