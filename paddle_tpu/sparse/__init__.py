"""paddle.sparse parity (ref: python/paddle/sparse/ over SparseCooTensor/
SparseCsrTensor — paddle/phi/core/sparse_*_tensor; SURVEY §2.1 sparse row).

TPU-native: COO is backed by jax.experimental.sparse.BCOO (XLA-lowered
scatter/gather + dot_general); CSR keeps (crows, cols, values) and converts
through COO for compute. Dense bridges (`to_dense`) keep parity with the
reference API.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "matmul", "add", "relu", "is_sparse"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # paddle layout [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = _arr(crows).astype(jnp.int32)
        self.cols = _arr(cols).astype(jnp.int32)
        self._values = _arr(values)
        self._shape = tuple(shape)

    @property
    def shape(self):
        return self._shape

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def values(self) -> Tensor:
        return Tensor(self._values)

    def to_coo(self) -> SparseCooTensor:
        counts = jnp.diff(self.crows)
        rows = jnp.repeat(jnp.arange(len(counts)), counts,
                          total_repeat_length=self.nnz)
        idx = jnp.stack([rows, self.cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    def to_dense(self) -> Tensor:
        return self.to_coo().to_dense()

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """indices: [ndim, nnz] (paddle layout)."""
    idx = _arr(indices).T.astype(jnp.int32)
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(idx, axis=0))
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    return SparseCsrTensor(crows, cols, values, shape)


def is_sparse(x) -> bool:
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def _as_bcoo(x):
    if isinstance(x, SparseCsrTensor):
        x = x.to_coo()
    return x._bcoo


def matmul(x, y):
    """sparse @ dense (ref: paddle.sparse.matmul)."""
    if is_sparse(x):
        out = _as_bcoo(x) @ _arr(y)
        return Tensor(out)
    raise TypeError("first operand must be sparse")


def add(x, y):
    if is_sparse(x) and is_sparse(y):
        bx, by = _as_bcoo(x), _as_bcoo(y)
        idx = jnp.concatenate([bx.indices, by.indices], axis=0)
        dat = jnp.concatenate([bx.data, by.data], axis=0)
        return SparseCooTensor(
            jsparse.BCOO((dat, idx), shape=bx.shape).sum_duplicates())
    raise TypeError("both operands must be sparse")


def relu(x):
    if is_sparse(x):
        b = _as_bcoo(x)
        return SparseCooTensor(jsparse.BCOO((jax.nn.relu(b.data), b.indices),
                                            shape=b.shape))
    raise TypeError("operand must be sparse")
