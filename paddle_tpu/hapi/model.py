"""paddle.Model (ref: python/paddle/hapi/model.py — Model.prepare/fit/
evaluate/predict/save/load). Runs the eager train loop over paddle_tpu.io
DataLoaders; metrics from paddle_tpu.metric."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..framework import io as fio
from .callbacks import Callback, ProgBarLogger

__all__ = ["Model"]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(np.asarray(x)))


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics = []

    def prepare(self, optimizer=None, loss=None, metrics=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = list(metrics) if metrics is not None else []

    # -- steps ---------------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outs = self.network(*[_to_tensor(i) for i in ins])
        loss = self._compute_loss(outs, labels)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return float(loss)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core import autograd as ag
        with ag.no_grad():
            outs = self.network(*[_to_tensor(i) for i in ins])
            loss = self._compute_loss(outs, labels)
            for m in self._metrics:
                r = m.compute(outs, _to_tensor(labels))
                m.update(*r) if isinstance(r, tuple) else m.update(r)
        return float(loss), outs

    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core import autograd as ag
        with ag.no_grad():
            return self.network(*[_to_tensor(i) for i in ins])

    def _compute_loss(self, outs, labels):
        if labels is None:
            return outs if isinstance(outs, Tensor) else outs[0]
        return self._loss(outs, _to_tensor(labels))

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=1, callbacks: Optional[Sequence[Callback]] = None,
            shuffle=True, num_workers=0):
        from ..io import DataLoader, Dataset
        if isinstance(train_data, Dataset):
            train_data = DataLoader(train_data, batch_size=batch_size,
                                    shuffle=shuffle,
                                    num_workers=num_workers)
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(ProgBarLogger(log_freq, verbose))
        for cb in cbs:
            cb.set_model(self)
            cb.on_train_begin()
        self.stop_training = False
        history = {"loss": []}
        for epoch in range(epochs):
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            losses = []
            for step, batch in enumerate(train_data):
                x, y = batch if isinstance(batch, (list, tuple)) and \
                    len(batch) == 2 else (batch, None)
                for cb in cbs:
                    cb.on_train_batch_begin(step)
                loss = self.train_batch(x, y)
                losses.append(loss)
                for cb in cbs:
                    cb.on_train_batch_end(step, {"loss": loss})
            logs = {"loss": float(np.mean(losses))}
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                logs.update(self.evaluate(eval_data, batch_size=batch_size,
                                          verbose=0))
            history["loss"].append(logs["loss"])
            for cb in cbs:
                cb.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training:
                break
        for cb in cbs:
            cb.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 num_workers=0, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            eval_data = DataLoader(eval_data, batch_size=batch_size)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in eval_data:
            x, y = batch if isinstance(batch, (list, tuple)) and \
                len(batch) == 2 else (batch, None)
            loss, _ = self.eval_batch(x, y)
            losses.append(loss)
        out = {"eval_loss": float(np.mean(losses))}
        for m in self._metrics:
            out[f"eval_{m.name()}"] = m.accumulate()
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=0) -> List:
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            test_data = DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in test_data:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x))
        return outs

    # -- io ------------------------------------------------------------------
    def save(self, path: str, training: bool = True):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer=False):
        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fio.load(path + ".pdopt"))

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size, dtypes=dtype)
