"""Property/fuzz testing with hypothesis — parity with the reference's
auto_scan_test.py harness (SURVEY §4.3: random shapes/attrs generated per
op, result compared against the NumPy reference). Where the reference
fuzzes TRT converters/oneDNN fusion passes, the TPU-native property under
test is: for ANY generated shape/dtype/attr combination, the eager op, the
traced (jit) op, and the NumPy reference agree.
"""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

import paddle_tpu as paddle
from paddle_tpu import jit

settings.register_profile("ci", max_examples=25, deadline=None)
settings.load_profile("ci")


def shapes(max_rank=4, max_side=6):
    return st.lists(st.integers(1, max_side), min_size=1,
                    max_size=max_rank).map(tuple)


def _data(shape, seed):
    return (np.random.RandomState(seed).randn(*shape) * 1.5).astype(
        np.float32)


def _triangle(fn_paddle, fn_np, arrs, rtol=1e-4, atol=1e-5):
    """eager == numpy reference == traced (the §4.1 triangle, fuzzed)."""
    ts = [paddle.to_tensor(a) for a in arrs]
    eager = fn_paddle(*ts)
    ref = fn_np(*arrs)
    np.testing.assert_allclose(eager.numpy(), ref, rtol=rtol, atol=atol)
    traced = jit.to_static(fn_paddle)(*ts)
    np.testing.assert_allclose(traced.numpy(), eager.numpy(), rtol=1e-6,
                               atol=1e-6)


@given(shape=shapes(), seed=st.integers(0, 2**16))
def test_fuzz_elementwise_chain(shape, seed):
    _triangle(lambda x: paddle.tanh(paddle.exp(x * 0.3) + 1.0),
              lambda x: np.tanh(np.exp(x * 0.3) + 1.0),
              [_data(shape, seed)])


@given(shape=shapes(max_rank=3), seed=st.integers(0, 2**16),
       axis_frac=st.floats(0, 0.999))
def test_fuzz_reduction_any_axis(shape, seed, axis_frac):
    axis = int(axis_frac * len(shape))
    _triangle(lambda x: paddle.sum(x, axis=axis),
              lambda x: np.sum(x, axis=axis), [_data(shape, seed)])


@given(b=st.integers(1, 3), m=st.integers(1, 6), k=st.integers(1, 6),
       n=st.integers(1, 6), seed=st.integers(0, 2**16))
def test_fuzz_matmul_shapes(b, m, k, n, seed):
    x = _data((b, m, k), seed)
    y = _data((b, k, n), seed + 1)
    _triangle(paddle.matmul, np.matmul, [x, y], rtol=1e-3, atol=1e-4)


@given(shape=shapes(max_rank=3, max_side=5), seed=st.integers(0, 2**16))
def test_fuzz_broadcast_binary(shape, seed):
    x = _data(shape, seed)
    # broadcastable partner: collapse a random prefix to 1s
    y_shape = tuple(1 if i % 2 else s for i, s in enumerate(shape))
    y = _data(y_shape, seed + 1)
    _triangle(paddle.add, np.add, [x, y])
    _triangle(paddle.multiply, np.multiply, [x, y])


@given(shape=shapes(max_rank=2, max_side=6), seed=st.integers(0, 2**16))
def test_fuzz_softmax_lastaxis(shape, seed):
    x = _data(shape, seed)
    def ref(a):
        e = np.exp(a - a.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)
    _triangle(lambda t: paddle.nn.functional.softmax(t, axis=-1), ref, [x])


@given(shape=shapes(max_rank=3), seed=st.integers(0, 2**16),
       pad_lo=st.integers(0, 3), pad_hi=st.integers(0, 3))
def test_fuzz_pad_lastdim(shape, seed, pad_lo, pad_hi):
    x = _data(shape, seed)
    _triangle(
        lambda t: paddle.nn.functional.pad(t, [pad_lo, pad_hi], value=0.25,
                                           data_format="NCL"),
        lambda a: np.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad_lo, pad_hi)],
                         constant_values=0.25),
        [x])


@given(n=st.integers(1, 40), seed=st.integers(0, 2**16),
       descending=st.booleans())
def test_fuzz_sort_matches_numpy(n, seed, descending):
    x = _data((n,), seed)
    def pd(t):
        return paddle.sort(t, descending=descending)
    def ref(a):
        s = np.sort(a)
        return s[::-1].copy() if descending else s
    _triangle(pd, ref, [x])
