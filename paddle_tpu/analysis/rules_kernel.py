"""Pallas-kernel rules PK101-PK105 (docs/ANALYSIS.md, kernel-verification
section).

All checks run over the :mod:`kernelmodel` view of each ``pallas_call``
site and stay strictly syntactic: a site whose specs/grid/kernel cannot
be resolved (helper-built spec lists, ``*refs`` kernels) opts out of the
checks that need the missing piece rather than guessing.

- **PK101** (error): an index_map that reads a scalar-prefetch table
  without routing the read through a clamp, or returns a literal
  negative block index. Grid ids are bounded by the grid domain; table
  contents are not — the shipped page maps all wrap table reads in
  ``jnp.clip``/``minimum``/``maximum`` because dead slots hold sentinel
  entries, and an unclamped read DMAs from whatever address falls out.
- **PK102** (error; lane advisories as warning): block-shape rank vs
  index_map return arity, index_map parameter count vs grid +
  scalar-prefetch domain, kernel positional-ref count vs the operand
  list ``[prefetch, inputs, outputs, scratch]``, and literal lane dims
  that are neither 1 nor a multiple of 128.
- **PK103** (error): ``input_output_aliases`` hygiene — alias indices in
  range (flat *input* indices include the prefetch operands), the
  aliased output's ShapeDtypeStruct taking shape/dtype from the very
  array passed at the aliased input slot, structurally identical
  in/out BlockSpecs, and no unguarded read of the aliased input ref in
  a kernel whose block map can revisit a block (the seed-on-first-visit
  ``pl.when`` pattern).
- **PK104** (warning): sub-f32 VMEM scratch or ``preferred_element_type``
  in a kernel that does matmul/softmax work — the online-softmax
  discipline accumulates in f32 and casts on the way out.
- **PK105** (warning): a pallas kernel unit not reachable from any
  ``register_oracle(...)`` registration — the certification contract of
  ROADMAP item 5: every authored kernel names an XLA reference oracle
  and an interpret-parity test.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from typing import Dict, List, Optional, Set

from .callgraph import (FunctionInfo, ModuleInfo, PackageIndex, _last_name,
                        partial_inner, walk_shallow)
from .kernelmodel import (SUB_F32_DTYPES, BlockSpecModel, IndexMapModel,
                          KernelCallSite, collect_kernel_calls,
                          negative_components, scratch_dtype_name,
                          shape_dtype_struct, unclamped_prefetch_reads,
                          unparse)
from .model import Config, Finding, register_rule

register_rule("PK101", "index_map block index out of bounds: unclamped "
                       "scalar-prefetch table read or negative literal",
              severity="error", module=__name__)
register_rule("PK102", "BlockSpec/kernel mismatch: map arity, block rank "
                       "vs map result, ref count, lane alignment",
              severity="error", module=__name__)
register_rule("PK103", "input_output_aliases hazard: index/shape/dtype/"
                       "spec mismatch or unguarded aliased-input read",
              severity="error", module=__name__)
register_rule("PK104", "sub-f32 accumulator in a matmul/softmax kernel",
              severity="warning", module=__name__)
register_rule("PK105", "pallas kernel without a registered XLA reference "
                       "oracle (register_oracle certification contract)",
              severity="warning", module=__name__)

_MATMUL_SOFTMAX_FUNCS = {"dot", "dot_general", "matmul", "exp", "exp2",
                         "softmax", "logsumexp", "einsum"}


def _site_specs(site: KernelCallSite):
    """(kind, operand-index-base, spec) triples for every resolved spec."""
    out = []
    if site.in_specs is not None:
        for i, s in enumerate(site.in_specs):
            out.append(("in", site.n_prefetch + i, s))
    if site.out_specs is not None:
        for i, s in enumerate(site.out_specs):
            out.append(("out", i, s))
    return out


def _n_outputs(site: KernelCallSite) -> Optional[int]:
    if site.out_shapes is not None:
        return len(site.out_shapes)
    if site.out_specs is not None:
        return len(site.out_specs)
    return None


def _root_name(expr: ast.AST) -> Optional[str]:
    """Base variable of `x`, `x.attr`, `x[i]`, `x.astype(t)` chains."""
    while True:
        if isinstance(expr, ast.Call) and isinstance(expr.func,
                                                     ast.Attribute):
            expr = expr.func.value
        elif isinstance(expr, (ast.Attribute, ast.Subscript)):
            expr = expr.value
        else:
            break
    return expr.id if isinstance(expr, ast.Name) else None


def _map_reads_table(imap: IndexMapModel, n_grid: Optional[int]) -> bool:
    """True when the index_map indexes any scalar-prefetch operand at all
    (clamped or not): such a map can send two grid steps to the same
    block — the revisit precondition for the PK103 seed pattern."""
    if n_grid is None:
        return False
    prefetch = set(imap.params[n_grid:])
    if not prefetch:
        return False
    for stmt in imap.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Subscript):
                base = node.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in prefetch:
                    return True
    return False


# ---------------------------------------------------------------------------
# PK101
# ---------------------------------------------------------------------------

def _check_oob(site: KernelCallSite, findings: List[Finding]) -> None:
    for kind, opidx, spec in _site_specs(site):
        imap = spec.index_map
        if imap is None:
            continue
        for read in unclamped_prefetch_reads(imap, site.grid_len):
            findings.append(Finding(
                "PK101", "error", site.mi.rel, getattr(read, "lineno",
                                                       site.line),
                getattr(read, "col_offset", 0), site.qualname,
                f"index_map `{imap.text}` reads scalar-prefetch table "
                f"`{unparse(read)}` without a clamp — a sentinel/stale "
                f"entry becomes an out-of-bounds block index and the DMA "
                f"reads garbage silently",
                hint="wrap the table read in jnp.clip/minimum/maximum "
                     "against the operand's block count",
                detail=f"oob:{kind}{opidx}:{unparse(read, 40)}"))
        for comp in negative_components(imap):
            findings.append(Finding(
                "PK101", "error", site.mi.rel, getattr(comp, "lineno",
                                                       site.line),
                getattr(comp, "col_offset", 0), site.qualname,
                f"index_map `{imap.text}` returns literal negative block "
                f"index `{unparse(comp)}`",
                hint="block indices count blocks from 0; negative values "
                     "wrap outside the operand",
                detail=f"neg:{kind}{opidx}:{unparse(comp, 40)}"))


# ---------------------------------------------------------------------------
# PK102
# ---------------------------------------------------------------------------

def _check_blockspec(site: KernelCallSite, findings: List[Finding]) -> None:
    n_grid = site.grid_len
    for kind, opidx, spec in _site_specs(site):
        imap = spec.index_map
        rank = spec.rank
        if imap is not None and rank is not None:
            for comps in imap.returns:
                if len(comps) != rank:
                    findings.append(Finding(
                        "PK102", "error", site.mi.rel, site.line, 0,
                        site.qualname,
                        f"{kind}_spec[{opidx - (site.n_prefetch if kind == 'in' else 0)}]: "
                        f"index_map `{imap.text}` returns {len(comps)} "
                        f"component(s) for a rank-{rank} block "
                        f"{unparse(ast.Tuple(elts=spec.block_shape, ctx=ast.Load()), 40)}",
                        hint="one block index per block-shape dimension",
                        detail=f"rank:{kind}{opidx}:{len(comps)}!={rank}"))
                    break
        if imap is not None and n_grid is not None:
            want = n_grid + site.n_prefetch
            if len(imap.params) != want:
                findings.append(Finding(
                    "PK102", "error", site.mi.rel, site.line, 0,
                    site.qualname,
                    f"index_map `{imap.text}` takes {len(imap.params)} "
                    f"parameter(s) but the domain is {n_grid} grid id(s) "
                    f"+ {site.n_prefetch} scalar-prefetch ref(s)",
                    hint="index_map params are grid ids then prefetch "
                         "refs, in order",
                    detail=f"arity:{kind}{opidx}:{len(imap.params)}!={want}"))
        if spec.block_shape:
            lane = spec.block_shape[-1]
            if isinstance(lane, ast.Constant) and isinstance(lane.value, int) \
                    and lane.value != 1 and lane.value % 128 != 0:
                findings.append(Finding(
                    "PK102", "warning", site.mi.rel,
                    getattr(lane, "lineno", site.line),
                    getattr(lane, "col_offset", 0), site.qualname,
                    f"block lane dimension {lane.value} is neither 1 nor "
                    f"a multiple of 128 — Mosaic pads every tile",
                    hint="use a 128-multiple lane (last) dimension",
                    detail=f"lane:{kind}{opidx}:{lane.value}"))
    # kernel positional-ref count vs operand list
    params = site.kernel_positional_params()
    n_out = _n_outputs(site)
    if params is not None and site.in_specs is not None and n_out is not None:
        n_scratch = len(site.scratch) if site.scratch is not None else 0
        want = site.n_prefetch + len(site.in_specs) + n_out + n_scratch
        if len(params) != want:
            findings.append(Finding(
                "PK102", "error", site.mi.rel,
                site.kernel_fi.lineno if site.kernel_fi else site.line, 0,
                site.qualname,
                f"kernel `{site.kernel_fi.qualname}` takes {len(params)} "
                f"ref(s) but the call site passes {want} "
                f"({site.n_prefetch} prefetch + {len(site.in_specs)} in + "
                f"{n_out} out + {n_scratch} scratch)",
                hint="kernel refs are [prefetch, inputs, outputs, scratch] "
                     "in order",
                detail=f"refs:{len(params)}!={want}"))


# ---------------------------------------------------------------------------
# PK103
# ---------------------------------------------------------------------------

def _nested_fns(site: KernelCallSite) -> List[FunctionInfo]:
    k = site.kernel_fi
    if k is None:
        return []
    prefix = k.qualname + "."
    return [fi for qn, fi in site.mi.functions.items()
            if qn.startswith(prefix)]


def _has_when_decorator(fi: FunctionInfo) -> bool:
    for dec in getattr(fi.node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _last_name(target) == "when":
            return True
    return False


def _reads_of(fi_node: ast.AST, name: str) -> List[ast.AST]:
    out = []
    for node in walk_shallow(fi_node):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == name:
            out.append(node)
    return out


def _check_aliases(site: KernelCallSite, findings: List[Finding]) -> None:
    if site.aliases is None:
        if site.has_alias_kw:
            # non-literal alias dict: nothing checkable
            pass
        return
    n_in = (site.n_prefetch + len(site.in_specs)
            if site.in_specs is not None else None)
    n_out = _n_outputs(site)
    params = site.kernel_positional_params()
    for k, v in sorted(site.aliases.items()):
        where = f"{{{k}: {v}}}"
        if k < site.n_prefetch or (n_in is not None and k >= n_in) \
                or (n_out is not None and (v < 0 or v >= n_out)):
            findings.append(Finding(
                "PK103", "error", site.mi.rel, site.line, 0, site.qualname,
                f"input_output_aliases {where} out of range: inputs are "
                f"flat indices {site.n_prefetch}..{(n_in or 0) - 1} "
                f"(prefetch operands included), outputs 0..{(n_out or 0) - 1}",
                hint="recount the flat operand list — scalar-prefetch "
                     "args occupy the first input slots",
                detail=f"alias-range:{k}:{v}"))
            continue
        # shape/dtype of the aliased output must come from the aliased arg
        if site.out_shapes is not None and v < len(site.out_shapes) \
                and site.arg_exprs is not None and k < len(site.arg_exprs):
            sds = shape_dtype_struct(site.out_shapes[v])
            argroot = _root_name(site.arg_exprs[k])
            if sds is not None and argroot is not None:
                shape_e, dtype_e = sds
                for what, e in (("shape", shape_e), ("dtype", dtype_e)):
                    ok = (isinstance(e, ast.Attribute) and e.attr == what
                          and _root_name(e) == argroot)
                    if not ok:
                        findings.append(Finding(
                            "PK103", "error", site.mi.rel,
                            getattr(e, "lineno", site.line),
                            getattr(e, "col_offset", 0), site.qualname,
                            f"aliased output {v} declares {what} "
                            f"`{unparse(e)}` but aliases input "
                            f"`{argroot}` — an aliased pair shares one "
                            f"buffer, so shape and dtype must be taken "
                            f"from that same array",
                            hint=f"use `{argroot}.{what}` in the "
                                 f"ShapeDtypeStruct",
                            detail=f"alias-{what}:{k}:{v}:{unparse(e, 32)}"))
        # in/out BlockSpecs of an aliased pair must be identical
        if site.in_specs is not None and site.out_specs is not None \
                and v < len(site.out_specs):
            ispec = site.in_specs[k - site.n_prefetch]
            ospec = site.out_specs[v]
            if ispec.resolved and ospec.resolved \
                    and unparse(ispec.node, 200) != unparse(ospec.node, 200):
                findings.append(Finding(
                    "PK103", "error", site.mi.rel, site.line, 0,
                    site.qualname,
                    f"aliased pair {where} uses different BlockSpecs "
                    f"(`{unparse(ispec.node, 48)}` vs "
                    f"`{unparse(ospec.node, 48)}`) — the pair walks one "
                    f"buffer, so the block tiling must match",
                    hint="share one BlockSpec object between the aliased "
                         "input and output",
                    detail=f"alias-spec:{k}:{v}"))
        # unguarded aliased-input read when the block map can revisit
        if params is not None and site.in_specs is not None \
                and site.out_specs is not None and v < len(site.out_specs):
            ospec = site.out_specs[v]
            revisit = (ospec.index_map is not None
                       and _map_reads_table(ospec.index_map, site.grid_len))
            in_param = params[k] if k < len(params) else None
            if revisit and in_param is not None:
                offending = list(_reads_of(site.kernel_fi.node, in_param))
                for nf in _nested_fns(site):
                    if not _has_when_decorator(nf):
                        offending.extend(_reads_of(nf.node, in_param))
                for read in offending:
                    findings.append(Finding(
                        "PK103", "error", site.mi.rel,
                        getattr(read, "lineno", site.line),
                        getattr(read, "col_offset", 0),
                        site.kernel_fi.qualname,
                        f"aliased input ref `{in_param}` read outside a "
                        f"`pl.when` guard, but its block map revisits "
                        f"blocks — after the first visit the aliased "
                        f"buffer holds this kernel's own writes, not the "
                        f"original input",
                        hint="seed on first visit: read the input ref "
                             "only inside `@pl.when(first_visit)` and "
                             "write through the output ref after",
                        detail=f"alias-raw:{in_param}:{unparse(read, 32)}"))


# ---------------------------------------------------------------------------
# PK104
# ---------------------------------------------------------------------------

def _kernel_does_matmul_softmax(site: KernelCallSite) -> bool:
    if site.kernel_fi is None:
        return False
    nodes = [site.kernel_fi.node] + [nf.node for nf in _nested_fns(site)]
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) \
                    and _last_name(node.func) in _MATMUL_SOFTMAX_FUNCS:
                return True
    return False


def _check_accumulator(site: KernelCallSite,
                       findings: List[Finding]) -> None:
    if not _kernel_does_matmul_softmax(site):
        return
    for expr in site.scratch or []:
        dt = scratch_dtype_name(expr)
        if dt in SUB_F32_DTYPES:
            findings.append(Finding(
                "PK104", "warning", site.mi.rel,
                getattr(expr, "lineno", site.line),
                getattr(expr, "col_offset", 0), site.qualname,
                f"{dt} scratch accumulator `{unparse(expr)}` in a "
                f"matmul/softmax kernel — running sums in sub-f32 lose "
                f"the online-softmax renormalization guarantees",
                hint="accumulate in float32 scratch and cast once on the "
                     "final store",
                detail=f"acc:{unparse(expr, 40)}"))
    # sub-f32 preferred_element_type on dots inside the kernel body
    if site.kernel_fi is None:
        return
    roots = [site.kernel_fi.node] + [nf.node for nf in _nested_fns(site)]
    for root in roots:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            if _last_name(node.func) not in ("dot", "dot_general", "matmul"):
                continue
            for kw in node.keywords:
                if kw.arg == "preferred_element_type" \
                        and _last_name(kw.value) in SUB_F32_DTYPES:
                    findings.append(Finding(
                        "PK104", "warning", site.mi.rel, node.lineno,
                        node.col_offset, site.qualname,
                        f"`preferred_element_type={_last_name(kw.value)}` "
                        f"on a kernel matmul — the MXU accumulates in "
                        f"f32; asking for a narrower result dtype "
                        f"truncates partial sums",
                        hint="prefer float32 and cast the final result",
                        detail=f"pet:{unparse(node, 40)}"))


# ---------------------------------------------------------------------------
# PK105 — oracle certification
# ---------------------------------------------------------------------------

def _registered_kernel_keys(index: PackageIndex) -> Set[str]:
    keys: Set[str] = set()
    for mi in index.modules.values():
        for fi_or_none, call in index._all_calls(mi):
            if _last_name(call.func) != "register_oracle":
                continue
            kexpr = None
            if len(call.args) > 1:
                kexpr = call.args[1]
            for kw in call.keywords:
                if kw.arg == "kernel":
                    kexpr = kw.value
            if kexpr is None:
                continue
            keys |= index._direct_func_keys(mi, fi_or_none, kexpr)
            # cross-module registration: `from .x import k; register_oracle(.., k)`
            inner = partial_inner(kexpr)
            target = inner if inner is not None else kexpr
            if isinstance(target, ast.Name) \
                    and target.id in mi.import_names:
                src, orig = mi.import_names[target.id]
                if f"{src}:{orig}" in index.functions:
                    keys.add(f"{src}:{orig}")
    return keys


def _defvjp_edges(index: PackageIndex) -> Dict[str, Set[str]]:
    edges: Dict[str, Set[str]] = defaultdict(set)
    for mi in index.modules.values():
        for fi_or_none, call in index._all_calls(mi):
            if not (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "defvjp"):
                continue
            rkeys = index._funcs_from_arg(mi, fi_or_none, call.func.value)
            akeys: Set[str] = set()
            for a in call.args:
                akeys |= index._direct_func_keys(mi, fi_or_none, a)
            for rk in rkeys:
                edges[rk] |= akeys
    return edges


def _cert_closure(index: PackageIndex, roots: Set[str]) -> Set[str]:
    """Everything reachable from the registered kernels through call
    edges, factory returns, partial bindings and custom_vjp defvjp
    linkage — the set of functions 'covered' by some oracle."""
    edges = _defvjp_edges(index)
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        nxt: Set[str] = set(edges.get(key, ()))
        fi = index.functions.get(key)
        if fi is not None:
            for keys, _, _ in fi.calls:
                nxt |= keys
            nxt |= fi.returned_defs | fi.returned_calls
            for pkeys in fi.local_partial_vars.values():
                nxt |= pkeys
        for ck in nxt:
            if ck not in seen and ck in index.functions:
                seen.add(ck)
                frontier.append(ck)
    return seen


def _check_oracles(index: PackageIndex, sites: List[KernelCallSite],
                   findings: List[Finding]) -> None:
    covered = _cert_closure(index, _registered_kernel_keys(index))
    reported: Set[str] = set()
    for site in sites:
        if site.fi is None:
            continue
        parts = site.fi.qualname.split(".")
        chain = {f"{site.mi.modname}:{'.'.join(parts[:i])}"
                 for i in range(1, len(parts) + 1)}
        if chain & covered:
            continue
        unit = f"{site.mi.modname}:{site.top_qualname}"
        if unit in reported:
            continue
        reported.add(unit)
        top_fi = site.mi.functions.get(site.top_qualname)
        findings.append(Finding(
            "PK105", "warning", site.mi.rel,
            top_fi.lineno if top_fi else site.line, 0, site.top_qualname,
            f"pallas kernel unit `{site.top_qualname}` has no registered "
            f"XLA reference oracle — nothing certifies the kernel "
            f"against a known-good implementation",
            hint="register_oracle(name, kernel=<public entry>, reference="
                 "<XLA impl>, parity_test=<tests node id>) in this module",
            detail=f"oracle:{site.top_qualname}"))


# ---------------------------------------------------------------------------

def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    findings: List[Finding] = []
    sites = collect_kernel_calls(index)
    for site in sites:
        if cfg.wants("PK101"):
            _check_oob(site, findings)
        if cfg.wants("PK102"):
            _check_blockspec(site, findings)
        if cfg.wants("PK103"):
            _check_aliases(site, findings)
        if cfg.wants("PK104"):
            _check_accumulator(site, findings)
    if cfg.wants("PK105"):
        _check_oracles(index, sites, findings)
    return findings
