"""Statistics ops (ref surface: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile",
           "nanquantile", "numel", "histogram", "bincount"]

from .math import mean  # re-export the math reduction


def _axis(axis):
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None) -> Tensor:
    return apply("std",
                 lambda a: jnp.std(a, axis=_axis(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), [x])


def var(x, axis=None, unbiased=True, keepdim=False, name=None) -> Tensor:
    return apply("var",
                 lambda a: jnp.var(a, axis=_axis(axis),
                                   ddof=1 if unbiased else 0,
                                   keepdims=keepdim), [x])


def median(x, axis=None, keepdim=False, mode="avg", name=None) -> Tensor:
    def impl(a):
        if mode == "avg":
            return jnp.median(a, axis=axis, keepdims=keepdim)
        ax = axis if axis is not None else None
        if ax is None:
            flat = jnp.sort(a.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        s = jnp.sort(a, axis=ax)
        k = (a.shape[ax] - 1) // 2
        out = jnp.take(s, k, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out
    return apply("median", impl, [x])


def nanmedian(x, axis=None, keepdim=False, name=None) -> Tensor:
    return apply("nanmedian",
                 lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim),
                 [x])


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None) -> Tensor:
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    def impl(a):
        return jnp.quantile(a.astype(jnp.float32), qv, axis=_axis(axis),
                            keepdims=keepdim, method=interpolation)
    return apply("quantile", impl, [x])


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None) -> Tensor:
    qv = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    def impl(a):
        return jnp.nanquantile(a.astype(jnp.float32), qv, axis=_axis(axis),
                               keepdims=keepdim, method=interpolation)
    return apply("nanquantile", impl, [x])


def numel(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(x.size, jnp.int64))


def histogram(input, bins=100, min=0, max=0, name=None) -> Tensor:
    a = input._data
    if min == 0 and max == 0:
        lo, hi = jnp.min(a), jnp.max(a)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(a.reshape(-1), bins=bins, range=None if min == 0 and max == 0 else (min, max))
    return Tensor(hist)


def bincount(x, weights=None, minlength=0, name=None) -> Tensor:
    w = weights._data if isinstance(weights, Tensor) else weights
    import jax
    if isinstance(x._data, jax.core.Tracer):
        raise NotImplementedError("bincount is dynamic-shape under tracing; "
                                  "pass minlength and use one-hot sums instead")
    n = int(np.asarray(x._data).max()) + 1 if x.size else 0
    length = max(n, minlength)
    out = jnp.bincount(x._data.reshape(-1), weights=None if w is None else w.reshape(-1),
                       length=length)
    return Tensor(out)


