"""observability.tracing: percentile-from-cumulative-buckets math (exact
on synthetic distributions), span-event ordering/monotonicity under a
seeded join/leave serving trace, chrome-trace round-trip via
load_profiler_result with host-span correlation, terminal events for
refused/overloaded/timeout requests, the ring buffer + background
exporter, and trainer step-phase spans."""

import json
import threading

import numpy as np
import pytest

from paddle_tpu import serving as srv
from paddle_tpu.observability import Histogram, Registry
from paddle_tpu.observability import tracing as tr
from paddle_tpu.profiler import load_profiler_result


@pytest.fixture(autouse=True)
def _clean_recorder():
    tr.recorder().clear()
    yield
    tr.recorder().clear()
    tr.set_enabled(True)


# ---------------------------------------------------------------- percentiles

class TestPercentile:
    def test_exact_on_bucket_bounds(self):
        # 100 observations at 1.0 and 100 at 2.0 on bounds (1,2,4):
        # p50 interpolates to exactly 1.0, p100 to exactly 2.0
        h = Histogram(buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.0)
        for _ in range(100):
            h.observe(2.0)
        assert tr.percentile(h, 50) == pytest.approx(1.0)
        assert tr.percentile(h, 100) == pytest.approx(2.0)
        # p75: target=150 lands mid-bucket (1,2] -> 1 + (150-100)/100
        assert tr.percentile(h, 75) == pytest.approx(1.5)

    def test_uniform_interpolation(self):
        # uniform mass in one bucket: quantiles scale linearly
        h = Histogram(buckets=(0.0, 10.0))
        for _ in range(10):
            h.observe(5.0)
        assert tr.percentile(h, 50) == pytest.approx(5.0)
        assert tr.percentile(h, 90) == pytest.approx(9.0)
        assert tr.percentile(h, 10) == pytest.approx(1.0)

    def test_empty_is_none(self):
        h = Histogram(buckets=(1.0,))
        assert tr.percentile(h, 50) is None
        assert tr.percentiles(h) == {"p50": None, "p90": None, "p99": None}

    def test_inf_bucket_clamps_to_last_finite(self):
        h = Histogram(buckets=(1.0, 2.0))
        h.observe(100.0)   # lands in +Inf bucket
        assert tr.percentile(h, 99) == pytest.approx(2.0)

    def test_invalid_q_raises(self):
        h = Histogram(buckets=(1.0,))
        with pytest.raises(ValueError):
            tr.percentile(h, 101)

    def test_snapshot_series_form(self):
        # the snapshot dict shape ({counts, count}) + explicit buckets
        h = Histogram(buckets=(1.0, 2.0))
        for _ in range(4):
            h.observe(1.0)
        series = {"counts": list(h._counts), "count": h.count}
        assert tr.percentile(series, 100, buckets=h.buckets) == \
            pytest.approx(1.0)
        with pytest.raises(ValueError):
            tr.percentile(series, 50)   # buckets required

    def test_slo_summary_shape(self):
        reg = Registry()
        h = reg.histogram("serving.engine.ttft_seconds", buckets=(1.0, 2.0))
        h.observe(1.0)
        s = tr.slo_summary(["serving.engine.ttft_seconds"], reg=reg)
        row = s["serving.engine.ttft_seconds"]
        assert row["count"] == 1
        assert row["mean"] == pytest.approx(1.0)
        assert set(row) == {"count", "mean", "p50", "p90", "p99"}


# ------------------------------------------------------------------ recorder

class TestRecorder:
    def test_event_ordering_monotonic(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.begin("r1")
        for name in ("enqueue", "admit", "token", "token"):
            rec.stamp("r1", name)
        rec.finish("r1", "finish")
        t = rec.trace("r1")
        ts = [e.t_us for e in t.timeline()]
        assert ts == sorted(ts)
        assert [e.name for e in t.timeline()] == \
            ["enqueue", "admit", "token", "token", "finish"]
        assert t.outcome == "finish"

    def test_derived_latencies(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.begin("r")
        rec.stamp("r", "enqueue")
        rec.stamp("r", "admit")
        rec.stamp("r", "token")
        rec.stamp("r", "token")
        rec.stamp("r", "token")
        rec.finish("r", "finish")
        t = rec.trace("r")
        assert t.queue_wait_s() >= 0
        assert t.ttft_s() >= t.queue_wait_s()
        # 3 tokens -> tpot = (last-first)/2
        gap = (t.last("token").t_us - t.first("token").t_us) / 1e6
        assert t.tpot_s() == pytest.approx(gap / 2)
        assert t.e2e_s() >= t.ttft_s()

    def test_unknown_id_stamp_ignored(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.stamp("ghost", "token")
        rec.finish("ghost")
        assert rec.trace("ghost") is None

    def test_ring_eviction_oldest_first(self):
        rec = tr.TraceRecorder(capacity=3)
        for i in range(5):
            rec.begin(i)
            rec.stamp(i, "enqueue")
            rec.finish(i, "finish")
        done = rec.finished()
        assert [t.request_id for t in done] == [2, 3, 4]

    def test_disabled_records_nothing(self):
        rec = tr.TraceRecorder(capacity=4)
        tr.set_enabled(False)
        try:
            assert rec.begin("r") is None
            rec.stamp("r", "enqueue")
            rec.finish("r")
        finally:
            tr.set_enabled(True)
        assert not rec.live() and not rec.finished()

    def test_trace_prefers_live_then_latest_done(self):
        rec = tr.TraceRecorder(capacity=4)
        rec.begin("r")
        rec.stamp("r", "enqueue")
        rec.finish("r", "finish")
        rec.begin("r")           # same id re-submitted
        rec.stamp("r", "enqueue")
        assert rec.trace("r").outcome is None       # the live one
        rec.finish("r", "finish")
        assert rec.trace("r").outcome == "finish"

    def test_background_exporter_jsonl(self, tmp_path):
        rec = tr.TraceRecorder(capacity=16)
        path = str(tmp_path / "traces.jsonl")
        rec.start_exporter(path, interval_s=0.01)
        try:
            for i in range(4):
                rec.begin(i)
                rec.stamp(i, "enqueue")
                rec.stamp(i, "token")
                rec.finish(i, "finish")
        finally:
            rec.stop_exporter()
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        assert len(lines) == 4
        assert {r["request_id"] for r in lines} == {0, 1, 2, 3}
        assert all(r["outcome"] == "finish" for r in lines)
        assert all(e["t_us"] for r in lines for e in r["events"])

    def test_exporter_thread_shares_recorder_lock(self):
        # the flush thread must only touch state under the recorder lock
        # (the PT006 discipline): hammer finish() from the main thread
        # while the exporter drains, then verify nothing was lost
        rec = tr.TraceRecorder(capacity=512)
        stop = threading.Event()

        def producer():
            for i in range(200):
                rec.begin(("p", i))
                rec.stamp(("p", i), "enqueue")
                rec.finish(("p", i), "finish")
            stop.set()

        import tempfile
        with tempfile.TemporaryDirectory() as d:
            rec.start_exporter(d + "/t.jsonl", interval_s=0.001)
            th = threading.Thread(target=producer)
            th.start()
            th.join(timeout=10)
            rec.stop_exporter()
            assert stop.is_set()
            lines = [json.loads(ln) for ln in open(d + "/t.jsonl")
                     if ln.strip()]
        assert len(lines) == 200


# ------------------------------------------------- serving-engine integration

def _tiny_engine(**kw):
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
    cfg = llama_tiny_config(num_hidden_layers=1)
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefill_chunk", 4)
    return srv.ServingEngine(LlamaForCausalLM(cfg), **kw), cfg


@pytest.mark.slow
class TestEngineTracing:
    def test_seeded_join_leave_trace_timeline(self):
        eng, cfg = _tiny_engine()
        rng = np.random.RandomState(0)
        for i in range(3):
            eng.add_request(rng.randint(0, cfg.vocab_size, 5).astype(
                np.int32), max_new_tokens=3, request_id=i)
        eng.run_to_completion()
        done = {t.request_id: t for t in tr.recorder().finished("request")}
        assert set(done) == {0, 1, 2}
        for t in done.values():
            names = [e.name for e in t.timeline()]
            # monotonic timestamps, canonical order, terminal last
            ts = [e.t_us for e in t.timeline()]
            assert ts == sorted(ts)
            assert names[0] == "enqueue" and names[-1] == "finish"
            assert names.index("admit") < names.index("prefill_chunk") \
                < names.index("token")
            assert t.count("token") == 3
            assert t.outcome == "finish"
            # every request produced the full SLO set
            assert t.queue_wait_s() is not None
            assert t.ttft_s() is not None
            assert t.tpot_s() is not None
            assert t.e2e_s() is not None
        # SLO percentiles come out of serving.slo()
        s = srv.slo()
        assert s["serving.engine.ttft_seconds"]["count"] >= 3
        assert s["serving.engine.ttft_seconds"]["p99"] is not None

    def test_chrome_export_round_trip_and_host_correlation(self, tmp_path):
        eng, cfg = _tiny_engine()
        eng.add_request(np.arange(5, dtype=np.int32) % cfg.vocab_size,
                        max_new_tokens=2, request_id="rt")
        eng.run_to_completion()
        path = str(tmp_path / "trace.json")
        n = tr.recorder().export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n > 0
        req_trace = tr.recorder().trace("rt")
        # the request's lifetime span carries its span id in the
        # observability.span naming convention
        spans = [e for e in events
                 if e["name"].endswith(f"[span={req_trace.span_id}]")]
        assert len(spans) == 1 and spans[0]["ph"] == "X"
        assert spans[0]["args"]["outcome"] == "finish"
        # phase rows nest inside the lifetime span
        phases = {e["name"] for e in events if e.get("cat") == "phase"}
        assert {"queue", "prefill", "decode"} <= phases
        # token stamps carry the host-profiler span id of their engine
        # step -> joinable against the host chrome trace
        toks = [e for e in events if e["name"] == "token"]
        assert toks and all("host_span" in e["args"] for e in toks)

    def test_refused_request_appears_in_timeline(self):
        from paddle_tpu import resilience as res
        from paddle_tpu.inference import Config
        cfg = Config()
        cfg.set_admission(max_inflight=1, queue_timeout_s=0.0)
        eng, mcfg = _tiny_engine(config=cfg, max_slots=1)
        eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=2, request_id="a")
        with pytest.raises(res.Overloaded):
            eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                            max_new_tokens=2, request_id="b")
        t = tr.recorder().trace("b")
        assert t is not None and t.outcome == "refused"
        assert [e.name for e in t.timeline()] == ["enqueue", "refused"]
        eng.run_to_completion()
        assert tr.recorder().trace("a").outcome == "finish"

    def test_queue_timeout_stamps_overloaded(self):
        from paddle_tpu import resilience as res
        from paddle_tpu.inference import Config
        cfg = Config()
        cfg.set_admission(max_inflight=1, queue_timeout_s=1e-4)
        eng, mcfg = _tiny_engine(config=cfg, max_slots=1)
        eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=4, request_id="x")
        eng.add_request(np.arange(4, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=4, request_id="y")
        import time
        time.sleep(0.01)
        results = eng.run_to_completion()
        assert isinstance(results["y"], res.Overloaded)
        t = tr.recorder().trace("y")
        assert t.outcome == "overloaded"
        assert t.first("token") is None   # never decoded
        assert "waited_s" in t.last("overloaded").meta

    def test_deadline_timeout_stamps_terminal(self):
        from paddle_tpu import resilience as res
        eng, mcfg = _tiny_engine()
        eng.add_request(np.arange(6, dtype=np.int32) % mcfg.vocab_size,
                        max_new_tokens=8, deadline_s=1e-6,
                        request_id="d")
        results = eng.run_to_completion()
        assert isinstance(results["d"], res.TimeoutResult)
        t = tr.recorder().trace("d")
        assert t.outcome == "timeout"

    def test_tracing_off_engine_still_exact(self):
        tr.set_enabled(False)
        try:
            eng, mcfg = _tiny_engine()
            eng.add_request(np.arange(5, dtype=np.int32) % mcfg.vocab_size,
                            max_new_tokens=3, request_id=0)
            results = eng.run_to_completion()
            assert results[0].shape == (3,)
            assert tr.recorder().trace(0) is None
        finally:
            tr.set_enabled(True)


class TestServingStampRoundTrip:
    """PR-10 stamps (prefix_hit, preempted/resumed, draft/verify_accept)
    recorded on a RequestTrace survive the chrome-trace export."""

    def test_recorder_level_roundtrip(self, tmp_path):
        rec = tr.recorder()
        rec.begin("r", prompt_len=12, max_new_tokens=4, priority=2,
                  tenant="acme")
        rec.stamp("r", "enqueue")
        rec.stamp("r", "admit", slot=0)
        rec.stamp("r", "prefix_hit", tokens=8, pages=2)
        rec.stamp("r", "token")
        rec.stamp("r", "preempted", decoded=1)
        rec.stamp("r", "resumed", slot=1, decoded=1)
        rec.stamp("r", "draft", tokens=3)
        rec.stamp("r", "verify_accept", drafted=3, accepted=2)
        rec.stamp("r", "token")
        rec.finish("r", "finish")
        t = rec.trace("r")
        names = [e.name for e in t.timeline()]
        for name in ("prefix_hit", "preempted", "resumed", "draft",
                     "verify_accept"):
            assert name in names
        assert names.index("preempted") < names.index("resumed")
        assert t.first("prefix_hit").meta["tokens"] == 8
        assert t.first("verify_accept").meta == {"drafted": 3,
                                                 "accepted": 2}
        path = str(tmp_path / "trace.json")
        n = rec.export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n
        by_name = {e["name"]: e for e in events
                   if e["name"] in ("prefix_hit", "preempted", "resumed",
                                    "draft", "verify_accept")}
        assert set(by_name) == {"prefix_hit", "preempted", "resumed",
                                "draft", "verify_accept"}
        assert by_name["prefix_hit"]["args"]["tokens"] == 8
        assert by_name["verify_accept"]["args"]["accepted"] == 2


@pytest.mark.slow
class TestEngineServingStamps:
    def test_prefix_hit_and_spec_stamps(self, tmp_path):
        eng, cfg = _tiny_engine(spec_decode=3, prefix_sharing=False)
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, cfg.vocab_size, 12).astype(np.int32)
        eng.add_request(prompt, max_new_tokens=3, request_id="warm")
        eng.run_to_completion()
        eng.add_request(prompt.copy(), max_new_tokens=3, request_id="hit",
                        tenant="acme")
        eng.run_to_completion()
        t = tr.recorder().trace("hit")
        hit = t.first("prefix_hit")
        assert hit is not None and hit.meta["tokens"] >= 8
        assert t.meta.get("tenant") == "acme"
        # spec decode on a repetitive prompt stamps draft/verify_accept
        rep = np.asarray([5, 9, 5, 9, 5, 9, 5, 9], np.int32)
        eng.add_request(rep, max_new_tokens=6, request_id="spec")
        eng.run_to_completion()
        ts = tr.recorder().trace("spec")
        if ts.first("draft") is not None:       # model-dependent drafts
            assert ts.first("draft").meta["tokens"] >= 1
        # chrome export round-trips every stamped event
        path = str(tmp_path / "t.json")
        n = tr.recorder().export_chrome_trace(path)
        events = load_profiler_result(path)
        assert len(events) == n > 0
        assert any(e["name"] == "prefix_hit" for e in events)

    def test_preempt_resume_stamps(self):
        from paddle_tpu.serving.scheduler import DECODE
        eng, cfg = _tiny_engine(max_slots=1)
        rng = np.random.RandomState(9)
        p1 = rng.randint(0, cfg.vocab_size, 5).astype(np.int32)
        p2 = rng.randint(0, cfg.vocab_size, 5).astype(np.int32)
        r1 = eng.add_request(p1, max_new_tokens=8, request_id="low",
                             priority=0)
        while r1.state != DECODE or len(r1.tokens) < 1:
            eng.step()
        eng.add_request(p2, max_new_tokens=2, request_id="high",
                        priority=3)
        eng.run_to_completion()
        t = tr.recorder().trace("low")
        names = [e.name for e in t.timeline()]
        assert "preempted" in names and "resumed" in names
        assert names.index("preempted") < names.index("resumed")
        assert t.first("preempted").meta["decoded"] >= 1
        # no re-prefill on resume: every prefill_chunk stamp precedes
        # the preemption
        pre = names.index("preempted")
        assert all(i < pre for i, nm in enumerate(names)
                   if nm == "prefill_chunk")
        assert tr.recorder().trace("high").meta.get("priority") == 3


# ---------------------------------------------------------- trainer phases

@pytest.mark.slow
class TestTrainerTracing:
    def test_step_phase_spans(self):
        from paddle_tpu import nn
        from paddle_tpu.trainer.trainer import Trainer, TrainingArguments

        class DS:
            def __len__(self):
                return 4

            def __getitem__(self, i):
                x = np.random.RandomState(i).randn(4).astype("float32")
                return x, x.sum(keepdims=True).astype("float32")

        t = Trainer(model=nn.Linear(4, 1),
                    args=TrainingArguments(
                        max_steps=2, per_device_train_batch_size=2,
                        logging_steps=1),
                    train_dataset=DS(), criterion=nn.MSELoss())
        t.train()
        done = tr.recorder().finished("train")
        assert len(done) == 2
        for st in done:
            names = [e.name for e in st.timeline()]
            assert names == ["data", "fwd", "bwd", "opt", "finish"]
            assert all(e.meta and e.meta.get("dur_us", 0) >= 0
                       for e in st.timeline()[:-1])
            assert st.outcome == "finish"
        assert done[0].meta["step"] == 1
        # train-step traces must NOT pollute the serving SLO histograms
        # (kind guard): export still renders them as chrome rows
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            n = tr.recorder().export_chrome_trace(d + "/t.json")
            evs = load_profiler_result(d + "/t.json")
        assert any(e["name"].startswith("train:train-step-")
                   for e in evs)
        # phase events carry explicit durations -> exported as X spans
        assert any(e["ph"] == "X" and e["name"] == "fwd" for e in evs)
