"""Vision model zoo (ref: python/paddle/vision/models/ — resnet.py,
mobilenetv3.py, lenet.py). NCHW layouts as in the reference; on TPU, XLA
re-lays out convs for the MXU, so the user-facing format stays paddle-like.
"""

from __future__ import annotations

import math
from typing import List, Optional, Type

from .. import nn

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "MobileNetV3Small", "mobilenet_v3_small"]


class LeNet(nn.Layer):
    """ref: python/paddle/vision/models/lenet.py."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.reshape([x.shape[0], -1])
        return self.fc(x)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.conv3 = nn.Conv2D(planes, planes * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ref: python/paddle/vision/models/resnet.py."""

    def __init__(self, block, depth_cfg: List[int], num_classes: int = 1000,
                 with_pool: bool = True, in_channels: int = 3):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2D(in_channels, 64, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], 2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], 2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], 2)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.fc(x)
        return x


def resnet18(**kw) -> ResNet:
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(BottleneckBlock, [3, 4, 6, 3], **kw)


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcite(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        mid = _make_divisible(channels // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, mid, 1)
        self.fc2 = nn.Conv2D(mid, channels, 1)
        self.relu = nn.ReLU()
        self.hs = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class InvertedResidual(nn.Layer):
    def __init__(self, cin, cmid, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if cmid != cin:
            layers += [nn.Conv2D(cin, cmid, 1, bias_attr=False),
                       nn.BatchNorm2D(cmid), act()]
        layers += [nn.Conv2D(cmid, cmid, k, stride=stride, padding=k // 2,
                             groups=cmid, bias_attr=False),
                   nn.BatchNorm2D(cmid), act()]
        if use_se:
            layers.append(SqueezeExcite(cmid))
        layers += [nn.Conv2D(cmid, cout, 1, bias_attr=False),
                   nn.BatchNorm2D(cout)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3Small(nn.Layer):
    """ref: python/paddle/vision/models/mobilenetv3.py (small config) — also
    the PP-OCR backbone family (PaddleOCR ppocr/modeling/backbones)."""

    # k, exp, out, se, act, stride
    CFG = [
        (3, 16, 16, True, nn.ReLU, 2),
        (3, 72, 24, False, nn.ReLU, 2),
        (3, 88, 24, False, nn.ReLU, 1),
        (5, 96, 40, True, nn.Hardswish, 2),
        (5, 240, 40, True, nn.Hardswish, 1),
        (5, 240, 40, True, nn.Hardswish, 1),
        (5, 120, 48, True, nn.Hardswish, 1),
        (5, 144, 48, True, nn.Hardswish, 1),
        (5, 288, 96, True, nn.Hardswish, 2),
        (5, 576, 96, True, nn.Hardswish, 1),
        (5, 576, 96, True, nn.Hardswish, 1),
    ]

    def __init__(self, num_classes: int = 1000, scale: float = 1.0,
                 with_pool: bool = True, in_channels: int = 3,
                 feature_only: bool = False, out_indices=(0, 3, 8, 10),
                 rec_mode: bool = False):
        super().__init__()
        self.feature_only = feature_only
        self.out_indices = set(out_indices)
        cin = _make_divisible(16 * scale)
        self.stem = nn.Sequential(
            nn.Conv2D(in_channels, cin, 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(cin), nn.Hardswish())
        blocks = []
        self.feat_channels = []
        for (k, exp, cout, se, act, s) in self.CFG:
            cmid = _make_divisible(exp * scale)
            co = _make_divisible(cout * scale)
            # rec_mode: PaddleOCR's text-recognition variant
            # (ppocr/modeling/backbones/rec_mobilenet_v3.py) downsamples
            # HEIGHT only in the blocks — stride 2 -> (2, 1) — so the
            # CTC time axis keeps W/2 columns
            stride = (s, 1) if (rec_mode and s == 2) else s
            blocks.append(InvertedResidual(cin, cmid, co, k, stride, se,
                                           act))
            cin = co
        self.blocks = nn.LayerList(blocks)
        clast = _make_divisible(576 * scale)
        self.head_conv = nn.Sequential(
            nn.Conv2D(cin, clast, 1, bias_attr=False),
            nn.BatchNorm2D(clast), nn.Hardswish())
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(clast, 1024), nn.Hardswish(),
                nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for i, b in enumerate(self.blocks):
            x = b(x)
            if i in self.out_indices:
                feats.append(x)
        if self.feature_only:
            return feats
        x = self.head_conv(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.classifier(x)
        return x


def mobilenet_v3_small(**kw) -> MobileNetV3Small:
    return MobileNetV3Small(**kw)


def resnet101(**kw) -> ResNet:
    return ResNet(BottleneckBlock, [3, 4, 23, 3], **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(BottleneckBlock, [3, 8, 36, 3], **kw)


class VGG(nn.Layer):
    """ref: python/paddle/vision/models/vgg.py (features-classifier CNN)."""

    def __init__(self, cfg: List, num_classes: int = 1000,
                 batch_norm: bool = False, in_channels: int = 3):
        super().__init__()
        layers = []
        c_in = in_channels
        for v in cfg:
            if v == "M":
                layers.append(nn.MaxPool2D(2, stride=2))
            else:
                layers.append(nn.Conv2D(c_in, v, 3, padding=1))
                if batch_norm:
                    layers.append(nn.BatchNorm2D(v))
                layers.append(nn.ReLU())
                c_in = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.classifier(x)
        return x


_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
         "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def vgg11(batch_norm=False, **kw) -> VGG:
    return VGG(_VGG_CFGS[11], batch_norm=batch_norm, **kw)


def vgg13(batch_norm=False, **kw) -> VGG:
    return VGG(_VGG_CFGS[13], batch_norm=batch_norm, **kw)


def vgg16(batch_norm=False, **kw) -> VGG:
    return VGG(_VGG_CFGS[16], batch_norm=batch_norm, **kw)


def vgg19(batch_norm=False, **kw) -> VGG:
    return VGG(_VGG_CFGS[19], batch_norm=batch_norm, **kw)


__all__ += ["resnet101", "resnet152", "VGG", "vgg11", "vgg13", "vgg16",
            "vgg19"]
