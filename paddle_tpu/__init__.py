"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of the reference (maxin8899/Paddle ≈ PaddlePaddle).

Built on JAX/XLA/Pallas/PJRT: eager Tensor API with tape autograd, traced
compilation via jit, one device mesh for all parallelism (GSPMD), Pallas
fused kernels. See SURVEY.md for the blueprint and docs/ for design notes.
"""

from __future__ import annotations

__version__ = "0.1.0"

from . import flags as _flags_mod
from .flags import get_flags, set_flags

from .core.tensor import Tensor  # noqa: F401
from .core import dtypes as _dtypes
from .core.dtypes import (bfloat16, bool_, complex64, complex128, float16,  # noqa: F401
                          float32, float64, get_default_dtype, int8, int16,
                          int32, int64, set_default_dtype, uint8)
from .core.autograd import enable_grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401

# the tensor-function surface (also mounts Tensor methods)
from .tensor import *  # noqa: F401,F403
from . import tensor as tensor  # noqa: F401

from .framework import (Generator, get_rng_state, seed, set_rng_state)  # noqa: F401
from .framework.io import load, save  # noqa: F401

from . import device  # noqa: F401
from .device import get_device, set_device  # noqa: F401

from . import autograd  # noqa: F401
from . import linalg  # noqa: F401
from . import metric  # noqa: F401

# nn / optimizer / amp / io / jit land with their build milestones (SURVEY §7.1
# L2/L3); imported here once present so `import paddle_tpu` exposes them.
import importlib as _importlib

for _sub in ("nn", "optimizer", "amp", "io", "jit", "distribution",
             "sparse", "fft", "signal", "geometric", "audio",
             "quantization", "profiler", "vision", "hapi", "incubate",
             "native", "generation"):
    try:
        globals()[_sub] = _importlib.import_module(f".{_sub}", __name__)
    except ModuleNotFoundError:
        pass
del _importlib

# grad API at top level (paddle.grad)
from .core.autograd import grad  # noqa: F401


def disable_static():
    """Eager is the default and only authoring mode; kept for API parity."""
    return None


def enable_static():
    raise NotImplementedError(
        "the legacy static-graph authoring mode is replaced by tracing: "
        "use paddle_tpu.jit.to_static / paddle_tpu.jit.jit")


def in_dynamic_mode() -> bool:
    return True
