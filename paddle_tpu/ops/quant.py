"""Weight-only quantized linear (int8/int4) for serving.

Reference capability (SURVEY §2.1 fused kernels): WeightOnlyLinearKernel +
python/paddle/incubate/nn/functional weight_only_linear / weight_quantize.

TPU-native: per-output-channel symmetric int8 (or packed int4) weights
dequantized in-kernel; a Pallas kernel tiles the matmul onto the MXU with
dequant fused into the VMEM load (one HBM pass over the quantized weights —
the bandwidth win is the point of weight-only quant). Interpret mode keeps
it testable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear"]


def weight_quantize(w, algo: str = "weight_only_int8"):
    """w [K, N] -> (quantized weight, per-channel scale [N]).
    int8: symmetric absmax; int4: packed two nibbles per int8 byte."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=0)
    if algo == "weight_only_int8":
        scale = absmax / 127.0
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-8)), -127, 127)
        return q.astype(jnp.int8), scale
    if algo == "weight_only_int4":
        scale = absmax / 7.0
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-8)), -7, 7)
        qi = q.astype(jnp.int8)
        K = qi.shape[0]
        if K % 2:
            raise ValueError("int4 pack needs even K")
        lo = qi[0::2] & 0xF
        hi = (qi[1::2] & 0xF) << 4
        return (lo | hi).astype(jnp.int8), scale
    raise ValueError(f"unknown algo: {algo}")


def weight_dequantize(qw, scale, algo: str = "weight_only_int8"):
    if algo == "weight_only_int8":
        return qw.astype(jnp.float32) * scale[None, :]
    if algo == "weight_only_int4":
        lo = (qw << 4).astype(jnp.int8) >> 4          # sign-extend low nibble
        hi = qw.astype(jnp.int8) >> 4
        K2, N = qw.shape
        out = jnp.zeros((K2 * 2, N), jnp.int8)
        out = out.at[0::2].set(lo).at[1::2].set(hi)
        return out.astype(jnp.float32) * scale[None, :]
    raise ValueError(f"unknown algo: {algo}")


def _wol_kernel(x_ref, qw_ref, s_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    w = qw_ref[:].astype(jnp.float32) * s_ref[:].astype(jnp.float32)[None, :]
    o_ref[:] = jnp.dot(
        x, w, preferred_element_type=jnp.float32).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _wol_int8(x2, qw, scale):
    return _wol_int8_fwd_impl(x2, qw, scale)


def _wol_int8_fwd_impl(x2, qw, scale):
    M, K = x2.shape
    N = qw.shape[1]
    bm = 128 if M % 128 == 0 else (8 if M % 8 == 0 else 1)
    return pl.pallas_call(
        _wol_kernel,
        grid=(M // bm,),
        in_specs=[pl.BlockSpec((bm, K), lambda i: (i, 0)),
                  pl.BlockSpec((K, N), lambda i: (0, 0)),
                  pl.BlockSpec((N,), lambda i: (0,))],
        out_specs=pl.BlockSpec((bm, N), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, N), x2.dtype),
        interpret=jax.default_backend() != "tpu",
    )(x2, qw, scale)


def _wol_int8_fwd(x2, qw, scale):
    return _wol_int8_fwd_impl(x2, qw, scale), (qw, scale)


def _wol_int8_bwd(res, g):
    qw, scale = res
    w = qw.astype(jnp.float32) * scale[None, :]
    dx = (g.astype(jnp.float32) @ w.T).astype(g.dtype)
    return dx, None, None


_wol_int8.defvjp(_wol_int8_fwd, _wol_int8_bwd)


def weight_only_linear(x, qweight, scale, bias=None,
                       algo: str = "weight_only_int8"):
    """x [..., K] @ dequant(qweight [K, N]) + bias.

    int8 path runs the fused dequant+matmul Pallas kernel; int4 unpacks via
    XLA then reuses the same matmul (packing is a memory-format detail).
    """
    shape = x.shape
    K = shape[-1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    if algo == "weight_only_int4":
        w = weight_dequantize(qweight, scale, algo).astype(x.dtype)
        out = x2 @ w
    else:
        out = _wol_int8(x2, qweight, scale)
    if bias is not None:
        out = out + bias
    return out.reshape(*shape[:-1], out.shape[-1])
