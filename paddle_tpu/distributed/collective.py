"""Eager collective communication API (ref: python/paddle/distributed/
communication/ — all_reduce/all_gather/… over ProcessGroupNCCL; SURVEY §2.3
P13 and §5.8 altitude (1)).

TPU-native mechanism: each collective is a small jitted shard_map program
over the current mesh axis — the XLA collective (psum/all_gather/ppermute)
runs on ICI exactly where NCCL rings ran. On a 1-device (or axis-less) mesh
they degrade to identity, which is how the reference's tests run single-rank.

In-place semantics preserved: `all_reduce(t)` rewrites t's buffer.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._compat import shard_map

from .. import observability as _obs
from .. import resilience as _res
from ..core.tensor import Tensor
from . import watchdog as _wd
from .mesh import get_mesh

# per-collective visibility (ISSUE 1): calls, input-payload bytes, and
# host wall-time per call. Latency includes XLA dispatch only — PJRT runs
# collectives async, so device time shows up here only when the call
# itself materializes results (the eager in-place rewrite paths do).
_COLL_CALLS = _obs.registry().counter(
    "pt_collective_calls_total", "collective API calls",
    labels=("collective",))
_COLL_BYTES = _obs.registry().counter(
    "pt_collective_bytes_total", "input payload bytes per collective",
    labels=("collective",))
_COLL_LAT = _obs.registry().histogram(
    "pt_collective_seconds", "collective call wall time",
    labels=("collective",))


def _payload_bytes(args) -> int:
    n = 0
    for a in args:
        if isinstance(a, Tensor):
            n += int(a._data.size) * jnp.dtype(a._data.dtype).itemsize
        elif isinstance(a, (list, tuple)):
            n += _payload_bytes(a)
    return n


def _describe(args, shapes=None, dtypes=None):
    """Tensor shapes/dtypes of a call's inputs, for the flight record."""
    if shapes is None:
        shapes, dtypes = [], []
    for a in args:
        if isinstance(a, Tensor):
            shapes.append(list(a._data.shape))
            dtypes.append(str(a._data.dtype))
        elif isinstance(a, (list, tuple)):
            _describe(a, shapes, dtypes)
    return shapes, dtypes


def _maybe_fault(name: str) -> None:
    """Fault-injection hook shared by every collective entry point:
    collective_delay@collective=<name>[:ms=N] sleeps before dispatch,
    collective_hang@collective=<name>[:ms=N] simulates a dead-peer hang
    (bounded at ms, default 30 s; the watchdog is expected to cancel it
    first and raise CollectiveTimeout), collective_error@collective=<name>
    raises InjectedFault. `collective` may also be `all` to target every
    collective."""
    plan = _res.active_plan()
    if plan is None:
        return
    for site in (name, "all"):      # delays first: a delayed call can
        rule = _res.inject("collective_delay", collective=site)
        if rule is not None:        # ALSO error below, like real flakes
            time.sleep(float(rule.opts.get("ms", 50.0)) / 1e3)
    for site in (name, "all"):
        rule = _res.inject("collective_hang", collective=site)
        if rule is not None:
            _wd.simulate_hang(name, float(rule.opts.get("ms", 30000.0)) / 1e3)
    for site in (name, "all"):
        rule = _res.inject("collective_error", collective=site)
        if rule is not None:
            raise _res.InjectedFault(
                f"collective_error injected in {name}", rule)


def _instrumented(fn):
    """Wrap a collective: count calls/bytes, time the call, and log it to
    the watchdog flight recorder. Disabled metrics / disabled watchdog
    each cost one attribute check."""
    name = fn.__name__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        rec = None
        if _wd.enabled():
            shapes, dtypes = _describe(args)
            try:
                axis = _axis_of(kwargs.get("group"))
            except TypeError:
                axis = None
            rec = _wd.start_record(name, shapes, dtypes,
                                   _payload_bytes(args), axis)
        try:
            _maybe_fault(name)
            if not _obs.enabled():
                out = fn(*args, **kwargs)
            else:
                t0 = time.perf_counter()
                try:
                    out = fn(*args, **kwargs)
                finally:
                    _COLL_CALLS.labels(collective=name).inc()
                    _COLL_BYTES.labels(collective=name).inc(
                        _payload_bytes(args))
                    _COLL_LAT.labels(collective=name).observe(
                        time.perf_counter() - t0)
        except _wd.CollectiveTimeout:
            _wd.end_record(rec, "timeout")
            raise
        except BaseException:
            _wd.end_record(rec, "error")
            raise
        _wd.end_record(rec, "ok")
        return out
    return wrapper

__all__ = ["ReduceOp", "all_reduce", "all_gather", "reduce_scatter",
           "broadcast", "scatter", "reduce", "alltoall", "send", "recv",
           "barrier", "new_group", "get_group", "wait", "stream"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A mesh axis standing in for a comm group (ref: ProcessGroup gid)."""

    def __init__(self, axis: str, mesh: Optional[Mesh] = None):
        self.axis = axis
        self.mesh = mesh

    @property
    def nranks(self) -> int:
        m = self.mesh or get_mesh()
        return m.shape.get(self.axis, 1) if m is not None else 1

    def __repr__(self):
        return f"Group(axis={self.axis}, nranks={self.nranks})"


_groups = {}


def new_group(ranks=None, backend=None, axis: str = "dp") -> Group:
    g = Group(axis)
    _groups[axis] = g
    return g


def get_group(axis: str = "dp") -> Group:
    return _groups.get(axis) or new_group(axis=axis)


def _axis_of(group) -> str:
    if group is None:
        return "dp"
    if isinstance(group, Group):
        return group.axis
    if isinstance(group, str):
        return group
    raise TypeError(f"bad group: {group}")


def _active_mesh(axis: str) -> Optional[Mesh]:
    m = get_mesh()
    if m is None or axis not in m.axis_names or m.shape[axis] == 1:
        return None
    return m


def _collective(mesh: Mesh, axis: str, fn, x):
    """Run fn inside shard_map over `axis`, fully replicated on other axes."""
    spec = P(axis)
    # operate on a leading stacked axis: we gather per-device values by
    # treating the tensor as replicated except along the comm axis.
    out = shard_map(fn, mesh=mesh, in_specs=(P(*([None] * x.ndim)),),
                    out_specs=P(*([None] * x.ndim)), check_vma=False)(x)
    return out


@_instrumented
def all_reduce(tensor: Tensor, op: str = ReduceOp.SUM, group=None,
               sync_op: bool = True) -> Tensor:
    axis = _axis_of(group)
    mesh = _active_mesh(axis)
    if mesh is None:
        return tensor
    red = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
           "avg": lambda v, a: jax.lax.pmean(v, a)}[op if isinstance(op, str) else ReduceOp.SUM]

    def fn(x):
        return red(x, axis)

    nd = tensor.ndim
    out = shard_map(fn, mesh=mesh,
                    in_specs=(P(*([None] * nd)),),
                    out_specs=P(*([None] * nd)), check_vma=False)(tensor._data)
    tensor._data = out
    return tensor


@_instrumented
def all_gather(tensor_list: Optional[List], tensor: Tensor = None, group=None,
               sync_op: bool = True):
    """paddle signature: all_gather(out_list, in_tensor). With a 1-axis mesh
    this returns each rank's replica-view concatenated along dim 0."""
    if tensor is None:  # also allow functional style: all_gather(t)
        tensor, tensor_list = tensor_list, None
    axis = _axis_of(group)
    mesh = _active_mesh(axis)
    if mesh is None:
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return Tensor(tensor._data[None])
    n = mesh.shape[axis]

    def fn(x):
        return jax.lax.all_gather(x, axis)

    nd = tensor.ndim
    out = shard_map(fn, mesh=mesh, in_specs=(P(*([None] * nd)),),
                    out_specs=P(*([None] * (nd + 1))), check_vma=False)(
        tensor._data)
    if tensor_list is not None:
        for i in range(n):
            tensor_list.append(Tensor(out[i]))
        return tensor_list
    return Tensor(out)


@_instrumented
def reduce_scatter(tensor: Tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True) -> Tensor:
    axis = _axis_of(group)
    mesh = _active_mesh(axis)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        src = Tensor(jnp.concatenate([t._data for t in src], axis=0))
    if mesh is None:
        tensor._data = src._data
        return tensor
    n = mesh.shape[axis]

    def fn(x):
        return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)

    nd = src.ndim
    out = shard_map(fn, mesh=mesh, in_specs=(P(*([None] * nd)),),
                    out_specs=P(axis, *([None] * (nd - 1))),
                    check_vma=False)(src._data)
    # out is sharded along dim0; each rank's shard is this rank's result —
    # materialize the local view replicated for eager parity
    tensor._data = out
    return tensor


@_instrumented
def broadcast(tensor: Tensor, src: int = 0, group=None, sync_op=True) -> Tensor:
    """Within a mesh axis all replicas already hold identical values under
    SPMD; broadcast selects the src rank's value for all."""
    axis = _axis_of(group)
    mesh = _active_mesh(axis)
    if mesh is None:
        return tensor

    def fn(x):
        idx = jax.lax.axis_index(axis)
        val = jax.lax.all_gather(x, axis)[src]
        return val

    nd = tensor.ndim
    out = shard_map(fn, mesh=mesh, in_specs=(P(*([None] * nd)),),
                    out_specs=P(*([None] * nd)), check_vma=False)(tensor._data)
    tensor._data = out
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None,
           sync_op=True) -> Tensor:
    # SPMD: reduce == all_reduce with the result meaningful on dst
    return all_reduce(tensor, op if isinstance(op, str) else ReduceOp.SUM,
                      group, sync_op)


@_instrumented
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axis = _axis_of(group)
    mesh = _active_mesh(axis)
    if isinstance(in_tensor_list, Tensor):
        stacked = in_tensor_list._data
    else:
        stacked = jnp.stack([t._data for t in in_tensor_list], axis=0)
    if mesh is None:
        outs = [Tensor(s) for s in stacked]
        if out_tensor_list is not None:
            out_tensor_list.extend(outs)
            return out_tensor_list
        return Tensor(stacked)

    def fn(x):
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)

    nd = stacked.ndim
    out = shard_map(fn, mesh=mesh, in_specs=(P(*([None] * nd)),),
                    out_specs=P(*([None] * nd)), check_vma=False)(stacked)
    outs = [Tensor(o) for o in out]
    if out_tensor_list is not None:
        out_tensor_list.extend(outs)
        return out_tensor_list
    return Tensor(out)


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "eager point-to-point send/recv maps to compiled collective_permute "
        "on TPU — use distributed.pipeline (SURVEY §5.8: NCCL p2p has no "
        "eager ICI analog; pipeline schedules compile their permutes)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "eager point-to-point send/recv maps to compiled collective_permute "
        "on TPU — use distributed.pipeline")


@_instrumented
def barrier(group=None):
    """Fence all outstanding device work (SPMD: program order is the sync).

    With `FLAGS_collective_timeout` > 0 the fence runs in a helper thread
    and a dead peer raises a diagnostic `CollectiveTimeout` (flight dump +
    lagging rank) instead of hanging the pod forever on
    `block_until_ready`."""
    tmo = _wd.timeout_s()
    if tmo <= 0:
        for a in jax.live_arrays():
            a.block_until_ready()
        return
    err: List[BaseException] = []

    def _fence():
        try:
            for a in jax.live_arrays():
                a.block_until_ready()
        except BaseException as e:       # surfaced in the caller below
            err.append(e)

    t = threading.Thread(target=_fence, daemon=True, name="pt-barrier-fence")
    t0 = time.monotonic()
    t.start()
    while True:
        t.join(timeout=0.005)
        if not t.is_alive():
            break
        rec = _wd.current_record()
        if rec is not None and rec.cancelled:
            raise _wd.timeout_error(rec, "barrier", rec.elapsed_s)
        if time.monotonic() - t0 > tmo:
            elapsed = time.monotonic() - t0
            if rec is not None:
                _wd.handle_timeout(rec)
            raise _wd.timeout_error(rec, "barrier", elapsed)
    if err:
        raise err[0]


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and isinstance(tensor._data, jax.Array):
        tensor._data.block_until_ready()


class stream:
    """paddle.distributed.stream.* parity: explicit-stream variants are
    no-ops on TPU (PJRT owns ordering); same functions re-exported."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    alltoall = staticmethod(alltoall)
