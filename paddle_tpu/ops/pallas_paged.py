"""In-tree paged-attention decode kernel (authored, tunable).

Reference capability: BlockMultiheadAttention / masked_multihead_attention
decode kernels (paddle/phi/kernels/fusion/gpu/block_multi_head_attention*;
VERDICT r2 Missing #7 — own the serving decode kernel, not just wrap the
bundled one).

One decode step: q [B, H, D] (one query token per sequence) attends to a
PAGED KV cache [KV, total_pages, page_size, D] through a per-sequence
page table [B, pages_per_seq]. Same machinery family as
ops/pallas_flash.py, plus the paged-serving specifics:

  - the page table rides as SCALAR PREFETCH (pltpu.PrefetchScalarGridSpec):
    the k/v BlockSpec index_map reads page_indices[b, j] to fetch each
    sequence's j-th physical page — the gather never materializes;
  - grid (B, KV, pages_per_seq), innermost sequential over pages with
    online-softmax scratch accumulators (m/l/acc per [rep, D]);
  - pages fully past `lengths[b]` cost zero work (pl.when skip);
    the tail page applies an elementwise position mask;
  - GQA native: the q heads of one KV head ([rep, D]) process together,
    so the kernel never repeats K/V rep times (the XLA reference pays
    that jnp.repeat bandwidth);
  - decode-only (no backward — serving path), f32 accumulation,
    interpret mode off-TPU so the CPU suite covers the kernel logic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["paged_decode_attention", "paged_decode_attention_v2",
           "paged_kernel_eligible", "default_pages_per_group"]

_NEG = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _page_map(b, h, j, lens, tab, *, page_size, total_pages):
    jmax = jnp.maximum(lens[b] - 1, 0) // page_size
    # clamp the table value too: lengths[b]==0 rows and sentinel entries
    # (-1 for unallocated slots) must not emit an out-of-range physical
    # page for the prefetch DMA, even though compute is pl.when-skipped
    phys = jnp.clip(tab[b, jnp.minimum(j, jmax)], 0, total_pages - 1)
    return (h, phys, 0, 0)


def _kernel(lengths_ref, page_tab_ref,      # scalar prefetch
            q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref, *, page_size, scale):
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    seq_len = lengths_ref[b]

    @pl.when(j * page_size < seq_len)
    def _compute():
        q = q_ref[0, 0]                                   # [rep, D]
        k = k_ref[0, 0]                                   # [psz, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [rep, psz]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        masked = pos >= seq_len
        s = jnp.where(masked, _NEG, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(masked, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _emit():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def paged_kernel_eligible(H: int, KV: int, D: int, page_size: int) -> bool:
    """rep x D tiles want MXU-friendly D; any page_size >= 8 works (the
    tail mask handles partial pages)."""
    return (H % KV == 0 and (D % 128 == 0 or (D <= 128 and D % 64 == 0))
            and page_size >= 8)


def _v2_kernel(lens_ref, tab_ref, q_ref, k_hbm, v_hbm, o_ref,
               kbuf, vbuf, acc_ref, m_ref, l_ref, ksem, vsem,
               *, page_size, pages_per_group, n_groups_max, scale,
               total_pages):
    """Multi-page double-buffered decode kernel (one grid cell per
    (sequence, kv-head); G pages DMA'd per group, compute overlaps the
    next group's fetch). This is the DMA page-grouping the bundled kernel
    uses — the v1 BlockSpec kernel paid per-page grid steps whose 4KB
    copies left HBM idle (VERDICT r3 weak #1)."""
    b = pl.program_id(0)
    h = pl.program_id(1)
    G, psz = pages_per_group, page_size
    seq = lens_ref[b]
    # clamp to the padded table's group count: a length beyond the table's
    # nj*psz capacity must not walk off the page table (the positions past
    # it aren't maskable — pos < seq there)
    n_live = jnp.minimum((seq + psz * G - 1) // (psz * G), n_groups_max)

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, _NEG)
    l_ref[:] = jnp.zeros_like(l_ref)

    def page_dma(g, i, slot, tensor):
        hbm, buf, sem = ((k_hbm, kbuf, ksem) if tensor == 0
                         else (v_hbm, vbuf, vsem))
        page = tab_ref[b, g * G + i]
        page = jnp.clip(page, 0, total_pages - 1)   # sentinel slots
        return pltpu.make_async_copy(
            hbm.at[h, page], buf.at[slot, pl.ds(i * psz, psz)],
            sem.at[slot, i])

    def start_group(g, slot):
        for i in range(G):                            # static unroll
            page_dma(g, i, slot, 0).start()
            page_dma(g, i, slot, 1).start()

    def wait_group(g, slot):
        for i in range(G):
            page_dma(g, i, slot, 0).wait()
            page_dma(g, i, slot, 1).wait()

    @pl.when(n_live > 0)
    def _warmup():
        start_group(0, 0)

    def body(g, _):
        slot = jax.lax.rem(g, 2)

        @pl.when(g + 1 < n_live)
        def _prefetch():
            start_group(g + 1, jax.lax.rem(g + 1, 2))

        wait_group(g, slot)
        q = q_ref[0, 0]                               # [rep, D]
        k = kbuf[slot]                                # [G*psz, D]
        v = vbuf[slot]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [rep, G*psz]
        pos = g * (G * psz) + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        masked = pos >= seq
        s = jnp.where(masked, _NEG, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(masked, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        return _

    jax.lax.fori_loop(0, n_live, body, None)
    l = l_ref[:]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def default_pages_per_group(nj: int, page_size: int) -> int:
    """Measured heuristic (docs/SERVING_BENCH.json paged sweep): ~16 pages
    per group up to 8k-token contexts, 32 beyond — large enough DMA bursts
    to saturate HBM, small enough to keep the double buffer in VMEM."""
    ctx = nj * page_size
    return 16 if ctx <= 8192 else 32


def paged_decode_attention_v2(q, k_pages, v_pages, lengths, page_indices,
                              scale: Optional[float] = None,
                              pages_per_group: Optional[int] = None):
    """Grouped-DMA paged decode: grid (B, KV); inside each cell the page
    list is walked in groups of ``pages_per_group`` with double-buffered
    manual DMAs (HBM pages -> VMEM), so dead pages past lengths[b] are
    never fetched and live fetches are large enough to saturate HBM."""
    import functools as _ft
    B, H, D = q.shape
    KV, total, psz, _ = k_pages.shape
    rep = H // KV
    if scale is None:
        scale = D ** -0.5
    nj = page_indices.shape[1]
    if pages_per_group is None:
        pages_per_group = default_pages_per_group(nj, psz)
    G = max(1, min(pages_per_group, nj))
    # double buffer must fit VMEM: 2 slots x 2 tensors x G*psz*D
    esize = jnp.dtype(k_pages.dtype).itemsize
    while G > 1 and 4 * G * psz * D * esize > (32 << 20):
        G //= 2
    n_groups = -(-nj // G)
    pad = n_groups * G - nj
    tab = page_indices.astype(jnp.int32)
    if pad:
        tab = jnp.pad(tab, ((0, 0), (0, pad)))
    qg = q.reshape(B, KV, rep, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV),
        in_specs=[
            pl.BlockSpec((1, 1, rep, D),
                         lambda b, h, lens, tab: (b, h, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),    # k_pages stay in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, D),
                               lambda b, h, lens, tab: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, G * psz, D), k_pages.dtype),
            pltpu.VMEM((2, G * psz, D), v_pages.dtype),
            pltpu.VMEM((rep, D), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, G)),
            pltpu.SemaphoreType.DMA((2, G)),
        ],
    )
    out = pl.pallas_call(
        _ft.partial(_v2_kernel, page_size=psz, pages_per_group=G,
                    n_groups_max=n_groups, scale=float(scale),
                    total_pages=total),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, rep, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), tab, qg, k_pages, v_pages)
    return out.reshape(B, H, D)


def paged_decode_attention(q, k_pages, v_pages, lengths, page_indices,
                           scale: Optional[float] = None):
    """q [B, H, D]; k/v_pages [KV, total_pages, page_size, D];
    lengths [B] int32; page_indices [B, pages_per_seq] int32.
    Returns [B, H, D]."""
    B, H, D = q.shape
    KV, _total, page_size, _ = k_pages.shape
    rep = H // KV
    if scale is None:
        scale = D ** -0.5
    nj = page_indices.shape[1]
    # [B, H, D] -> [B, KV, rep, D]: one grid cell owns one KV head's
    # query group
    qg = q.reshape(B, KV, rep, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                      # lengths, page table
        grid=(B, KV, nj),
        in_specs=[
            pl.BlockSpec((1, 1, rep, D),
                         lambda b, h, j, lens, tab: (b, h, 0, 0)),
            # clamp to the last VALID page: steps past lengths[b] then
            # re-reference the previous block and Pallas elides the copy
            # (otherwise skipped pages still pay their HBM DMA)
            pl.BlockSpec((1, 1, page_size, D), functools.partial(
                _page_map, page_size=page_size, total_pages=_total)),
            pl.BlockSpec((1, 1, page_size, D), functools.partial(
                _page_map, page_size=page_size, total_pages=_total)),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, D),
                               lambda b, h, j, lens, tab: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((rep, D), jnp.float32),
                        pltpu.VMEM((rep, 1), jnp.float32),
                        pltpu.VMEM((rep, 1), jnp.float32)],
    )
    cparams = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=page_size,
                          scale=float(scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, rep, D), q.dtype),
        compiler_params=cparams,
        interpret=_interpret(),
    )(lengths.astype(jnp.int32), page_indices.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(B, H, D)


# certification (ROADMAP item 5 / paddlelint PK105); lazy strings —
# paged_attention imports us
from .oracles import register_oracle  # noqa: E402

register_oracle(
    "paged_decode_attention", kernel=paged_decode_attention,
    reference="paddle_tpu.ops.paged_attention:paged_attention_reference",
    parity_test="tests/test_paged_kernel.py::TestPagedKernelParity")
register_oracle(
    "paged_decode_attention_v2", kernel=paged_decode_attention_v2,
    reference="paddle_tpu.ops.paged_attention:paged_attention_reference",
    parity_test="tests/test_paged_kernel.py::TestPagedV2GroupedDMA")
