"""Device management (ref surface: python/paddle/device/).

On TPU, placement is owned by shardings/PJRT rather than per-tensor device
moves; set_device selects the default jax backend for eager ops.
"""

from __future__ import annotations

import jax

__all__ = ["set_device", "get_device", "device_count", "is_compiled_with_cuda",
           "is_compiled_with_xpu", "is_compiled_with_tpu", "get_all_devices",
           "synchronize", "memory_stats", "max_memory_allocated",
           "memory_allocated"]

_current = None


def _platform_of(spec: str) -> str:
    base = spec.split(":")[0]
    return {"gpu": "tpu", "cuda": "tpu", "tpu": "tpu", "cpu": "cpu",
            "axon": "axon"}.get(base, base)


def set_device(device: str):
    """'tpu', 'tpu:0', 'cpu' — 'gpu' aliases to the accelerator for
    code written against the reference API."""
    global _current
    plat = _platform_of(device)
    idx = int(device.split(":")[1]) if ":" in device else 0
    for d in jax.devices():
        if d.id == idx:
            _current = d
            break
    else:
        _current = jax.devices()[0]
    jax.config.update("jax_default_device", _current)
    return _current


def get_device() -> str:
    if _current is None:
        d = jax.devices()[0]
    else:
        d = _current
    return f"{d.platform}:{d.id}"


def device_count() -> int:
    return jax.device_count()


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform != "cpu" for d in jax.devices())


def synchronize(device=None) -> None:
    """Fence all async work (parity: paddle.device.synchronize)."""
    for d in jax.live_arrays():
        d.block_until_ready()


def memory_stats(device=None) -> dict:
    d = jax.devices()[0] if device is None else device
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))
