"""Grouped GEMM for MoE expert compute.

Reference capability: CUTLASS grouped-gemm fused MoE kernels
(paddle/phi/kernels/fusion/cutlass/ moe/weight-only gemm — SURVEY §2.3 P7).

TPU-native realization: `jax.lax.ragged_dot` — XLA's native ragged matmul
lowers onto the MXU with one kernel over all expert groups (the megablocks
"dropless" pattern). A pure-einsum fallback keeps the op correct on backends
or shapes where ragged_dot is unavailable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["grouped_gemm", "sort_by_group", "unsort_by_group"]


def grouped_gemm(lhs, rhs, group_sizes, *, prefer_ragged: bool = True):
    """lhs [M, K] rows grouped contiguously; rhs [G, K, N]; group_sizes [G]
    (sum == M). Returns [M, N] where row m is multiplied by its group's rhs.
    """
    G = rhs.shape[0]
    if prefer_ragged:
        if jax.default_backend() == "tpu":
            try:
                # megablox gmm: the Pallas TPU grouped-GEMM kernel
                from jax.experimental.pallas.ops.tpu.megablox import gmm
                return gmm(lhs, rhs, group_sizes.astype(jnp.int32))
            except Exception:  # pragma: no cover - kernel constraints
                pass
        try:
            return jax.lax.ragged_dot(lhs, rhs, group_sizes.astype(jnp.int32))
        except Exception:  # pragma: no cover - backend-specific gaps
            pass
    # fallback: one-hot group membership -> batched einsum (static shapes)
    M = lhs.shape[0]
    ends = jnp.cumsum(group_sizes)
    starts = ends - group_sizes
    rows = jnp.arange(M)
    member = (rows[None, :] >= starts[:, None]) & (rows[None, :] < ends[:, None])
    # [G, M] bool; project lhs per group, matmul, and sum (each row is in
    # exactly one group so the sum just selects)
    per_g = jnp.einsum("gm,mk->gmk", member.astype(lhs.dtype), lhs)
    out_g = jnp.einsum("gmk,gkn->gmn", per_g, rhs)
    return jnp.sum(out_g, axis=0)


def sort_by_group(x, group_ids, num_groups: int):
    """Stable-sort rows of x by group id. Returns (sorted_x, group_sizes,
    inverse permutation) — all static-shape, jit-safe."""
    order = jnp.argsort(group_ids, stable=True)
    inv = jnp.argsort(order, stable=True)
    sizes = jnp.bincount(group_ids, length=num_groups)
    return x[order], sizes.astype(jnp.int32), inv


def unsort_by_group(x_sorted, inverse_perm):
    return x_sorted[inverse_perm]
