"""Llama pretrain step — the flagship hybrid-parallel training program
(ref: PaddleNLP llm/run_pretrain.py over fleet 4D; SURVEY §3.5).

One jitted SPMD program composes every axis:
  pp  — compiled microbatch pipeline (distributed.pipeline)
  dp  — batch dim sharded (grad psum by GSPMD)
  sharding — ZeRO: params+opt-state dim-0 sharded
  sep — sequence dim sharded (context parallelism via GSPMD resharding
        around attention; ring-attention kernel lands at L6)
  mp  — Megatron TP (weight specs) + vocab-parallel CE
Optimizer is the framework's own AdamW (optimizer.functional.FunctionalAdamW
— the same adamw_kernel the eager optimizer.AdamW.step() runs) with
ClipGradByGlobalNorm semantics; bf16 compute params via amp.decorate_tree
(functional O2) over f32 master weights (multi_precision parity).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..amp import decorate_tree
from ..core.tensor import Tensor
from ..distributed.mesh import (build_hybrid_mesh, global_device_put,
                                mesh_context)
from ..distributed.pipeline import (PP_AXIS, spmd_pipeline,
                                    spmd_pipeline_interleaved,
                                    stack_layer_params,
                                    stack_layer_params_interleaved)
from ..models.llama import (LlamaConfig, LlamaDecoderLayer, LlamaForCausalLM,
                            precompute_rope)
from ..optimizer.functional import FunctionalAdamW
from ..jit import _StateSwap, bind_state, extract_state

__all__ = ["PretrainConfig", "build_llama_pretrain_step",
           "make_hybrid_mesh_for", "flops_per_token", "flops_per_token_hw"]


class PretrainConfig:
    def __init__(self, model: LlamaConfig, global_batch=8, seq_len=512,
                 n_microbatches=1, lr=3e-4, weight_decay=0.1,
                 param_dtype="bfloat16", grad_clip=1.0,
                 dp=1, mp=1, pp=1, sharding=1, sep=1, vpp=1,
                 scan_layers: bool = True, remat: str = "full",
                 ce_chunks: int = 4, pp_schedule: str = "compiled",
                 moment_dtype: str = "float32"):
        self.model = model
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.n_microbatches = n_microbatches
        self.lr = lr
        self.weight_decay = weight_decay
        self.param_dtype = param_dtype
        self.grad_clip = grad_clip
        self.dp, self.mp, self.pp = dp, mp, pp
        self.sharding, self.sep = sharding, sep
        # vpp > 1 = interleaved virtual-pipeline schedule (ref:
        # virtual_pp_degree / PipelineParallelWithInterleave)
        self.vpp = vpp
        # scan_layers=False unrolls the per-stage layer loop. On this
        # device generation each while-loop iteration costs ~2ms of host
        # round-trip, so unrolling 16 layers saves ~60ms/step fwd+bwd at
        # the price of longer compiles (ref parity: CINN-style tradeoff).
        self.scan_layers = scan_layers
        # remat: "full" checkpoints every layer (fleet recompute parity),
        # "dots" saves matmul outputs (recompute only elementwise),
        # "none" stores all residuals.
        if remat not in ("full", "dots", "none"):
            raise ValueError(f"remat must be full|dots|none, got {remat!r}")
        self.remat = remat
        # sequence chunks for the softmax-CE loss: bounds peak logits
        # memory at B*S/ce_chunks*vocab f32 (per-chunk remat)
        if ce_chunks < 1:
            raise ValueError(f"ce_chunks must be >= 1, got {ce_chunks}")
        self.ce_chunks = ce_chunks
        # pipeline execution strategy (ref: fleet pipeline_scheduler_pass):
        #   "compiled" — scan+ppermute program, autodiff'd (GPipe-class
        #                memory; + interleaved when vpp>1);
        #   "1F1B" / "ZBH1" / "FThenB" — the pp_schedule timetable run by
        #                the distributed.pp_exec executor (1F1B bounds
        #                live activations by stage depth, ZBH1 also fills
        #                bubbles with deferred weight-grads). Timetable
        #                modes imply stage-level remat and require vpp=1.
        if pp_schedule not in ("compiled", "1F1B", "ZBH1", "FThenB", "VPP"):
            raise ValueError(f"unknown pp_schedule {pp_schedule!r}")
        if pp_schedule == "VPP" and vpp <= 1:
            raise ValueError("pp_schedule='VPP' needs vpp>1 virtual "
                             "chunks per stage")
        if vpp > 1 and pp_schedule not in ("compiled", "VPP", "1F1B"):
            raise ValueError(f"pp_schedule={pp_schedule!r} does not "
                             f"support vpp>1 (use 'VPP' for the "
                             f"interleaved timetable executor)")
        if pp_schedule != "compiled" and pp <= 1:
            raise ValueError(f"pp_schedule={pp_schedule!r} requires "
                             f"pp>1 (got pp={pp}); a single stage has "
                             f"no pipeline to schedule")
        self.pp_schedule = pp_schedule
        # "bfloat16" halves Adam-state HBM (update math stays f32) —
        # the knob that admits a larger per-chip batch when optimizer
        # state crowds out activations
        if moment_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"moment_dtype must be float32|bfloat16, "
                             f"got {moment_dtype!r}")
        self.moment_dtype = moment_dtype


def make_hybrid_mesh_for(cfg: PretrainConfig, devices=None) -> Mesh:
    return build_hybrid_mesh(dp_degree=cfg.dp, mp_degree=cfg.mp,
                             pp_degree=cfg.pp, sharding_degree=cfg.sharding,
                             sep_degree=cfg.sep, devices=devices)


def _n_params(c: LlamaConfig) -> float:
    return (c.vocab_size * c.hidden_size * (1 if c.tie_word_embeddings else 2)
            + c.num_hidden_layers * (
                c.hidden_size * c.head_dim
                * (c.num_attention_heads + 2 * c.num_key_value_heads)
                + c.num_attention_heads * c.head_dim * c.hidden_size
                + 3 * c.hidden_size * c.intermediate_size
                + 2 * c.hidden_size)
            + c.hidden_size)


def flops_per_token(c: LlamaConfig) -> float:
    """6*N FLOPs/token — weight FLOPs only, NO attention term.

    This is the *model*-FLOPs MFU denominator (the conservative convention:
    attention score/value FLOPs the hardware actually performs are not
    credited, so MFU reported against this is a lower bound). For the
    hardware-FLOPs variant that adds the 12*L*h*s attention term, use
    `flops_per_token_hw`; both are reported in docs/FLAGSHIP.md.
    """
    return 6.0 * _n_params(c)


def flops_per_token_hw(c: LlamaConfig, seq_len: int) -> float:
    """6*N + attention FLOPs/token: the hardware-FLOPs MFU denominator.

    Attention adds 2 matmuls (QK^T and PV) per head per layer, each
    s*head_dim MACs = 2*s*head_dim FLOPs per token in the forward pass ->
    4*s*head_dim*n_heads*L forward FLOPs/token; the backward costs 2x the
    forward, so fwd+bwd = 3x -> 12 * L * n_heads * head_dim * seq_len per
    token (causal masking halves the realized work, but the dense
    convention is standard for MFU).
    """
    attn = 12.0 * c.num_hidden_layers * c.num_attention_heads * c.head_dim * seq_len
    return 6.0 * _n_params(c) + attn


def _param_spec_tree(state: Dict[str, jnp.ndarray], model) -> Dict[str, P]:
    """Collect each param's sharding spec (TP specs from the layers; the
    sharding (ZeRO) axis composes on dim 0 when divisible)."""
    sd = model.state_dict()
    specs = {}
    from ..distributed.mesh import get_mesh, sanitize_spec
    mesh = get_mesh()
    for k, v in state.items():
        spec = getattr(sd[k], "_sharding_spec", None)
        if mesh is not None:
            spec = sanitize_spec(mesh, spec)
        specs[k] = spec if spec is not None else P()
    return specs


def _compose_zero(spec: P, shape, axis: str, size: int) -> P:
    """Add ZeRO sharding on the first free dim divisible by the axis size."""
    if size <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for d, (e, s) in enumerate(zip(entries, shape)):
        used = () if e is None else (e if isinstance(e, tuple) else (e,))
        if axis in used:
            return P(*entries)
        if s % size == 0 and e is None:
            entries[d] = axis
            return P(*entries)
        if s % size == 0 and not isinstance(e, tuple):
            # compose with existing axis on same dim if still divisible
            continue
    return P(*entries)


class TrainState(NamedTuple):
    params: Any          # bf16 compute params
    master: Any          # f32 master weights
    opt_state: Any
    step: jnp.ndarray


def build_llama_pretrain_step(cfg: PretrainConfig, mesh: Mesh):
    """Returns (state, train_step, meta). train_step(state, batch_ids,
    labels) -> (state, metrics) — one fully-sharded jitted step."""
    mc = cfg.model
    with mesh_context(mesh):
        model = LlamaForCausalLM(mc)
    param_dtype = jnp.bfloat16 if cfg.param_dtype == "bfloat16" else jnp.float32

    full_state = extract_state(model)

    # split decoder-layer params (pipelined & stacked) from outer params
    layer_prefix = "llama.layers."
    per_layer: list = [dict() for _ in range(mc.num_hidden_layers)]
    outer: Dict[str, jnp.ndarray] = {}
    for k, v in full_state.items():
        if k.startswith(layer_prefix):
            rest = k[len(layer_prefix):]
            idx, sub = rest.split(".", 1)
            per_layer[int(idx)][sub] = v
        else:
            outer[k] = v

    n_stages = mesh.shape[PP_AXIS]
    if cfg.vpp > 1:
        stacked = stack_layer_params_interleaved(per_layer, n_stages, cfg.vpp)
    else:
        stacked = stack_layer_params(per_layer, n_stages)

    # sharding specs
    tmpl = LlamaDecoderLayer(mc)
    tmpl_sd = tmpl.state_dict()
    stacked_specs = {}
    n_lead = 3 if cfg.vpp > 1 else 2  # [S, (v,) L/stage, ...param dims]
    for k in stacked:
        base = getattr(tmpl_sd[k], "_sharding_spec", None) or P()
        entries = [PP_AXIS] + [None] * (n_lead - 1) + list(base) \
            + [None] * (stacked[k].ndim - n_lead - len(base))
        spec = P(*entries)
        stacked_specs[k] = spec
    model_sd = model.state_dict()
    outer_specs = {k: (getattr(model_sd[k], "_sharding_spec", None) or P())
                   for k in outer}

    # ZeRO composition on the sharding axis
    zdeg = mesh.shape.get("sharding", 1)
    stacked_specs = {k: _compose_zero(stacked_specs[k], stacked[k].shape,
                                      "sharding", zdeg)
                     for k in stacked}
    outer_specs = {k: _compose_zero(outer_specs[k], outer[k].shape,
                                    "sharding", zdeg) for k in outer}

    params = {"stacked": stacked, "outer": outer}
    specs = {"stacked": stacked_specs, "outer": outer_specs}

    def place(tree, specs_tree, dtype=None):
        out = {}
        for k, v in tree.items():
            arr = v.astype(dtype) if dtype is not None and \
                jnp.issubdtype(v.dtype, jnp.floating) else v
            out[k] = global_device_put(arr, NamedSharding(mesh, specs_tree[k]))
        return out

    master = {g: place(params[g], specs[g]) for g in params}
    compute = {g: place(params[g], specs[g], param_dtype) for g in params}

    tx = FunctionalAdamW(cfg.lr, beta1=0.9, beta2=0.95, epsilon=1e-8,
                         weight_decay=cfg.weight_decay,
                         clip_norm=cfg.grad_clip,
                         moment_dtype=cfg.moment_dtype)
    opt_state = tx.init(master)

    cos, sin = precompute_rope(mc.head_dim, cfg.seq_len, mc.rope_theta)

    # stage body: apply L/S decoder layers via scan over the local slice;
    # per-layer remat (ref: fleet recompute intervals) keeps scan residuals
    # at O(hidden) instead of O(attention-scores) per layer
    if cfg.remat == "dots":
        remat_wrap = functools.partial(
            jax.checkpoint, policy=jax.checkpoint_policies.dots_saveable)
    elif cfg.remat == "none":
        remat_wrap = lambda f: f
    else:
        remat_wrap = jax.checkpoint

    def stage_fn(params_slice, x, cos_, sin_):
        def body(h, layer_params):
            with _StateSwap([tmpl]):
                bind_state(tmpl, layer_params)
                from ..core import autograd as ag
                with ag.no_grad():
                    out = tmpl(Tensor(h), cos_, sin_)
            return out._data, None
        n_local = jax.tree.leaves(params_slice)[0].shape[0]
        h, _ = jax.lax.scan(remat_wrap(body), x, params_slice,
                            unroll=1 if cfg.scan_layers else n_local)
        return h

    embed_key = "llama.embed_tokens.weight"
    norm_key = "llama.norm.weight"
    head_key = "lm_head.weight"

    M = cfg.n_microbatches
    B, S = cfg.global_batch, cfg.seq_len
    assert B % M == 0

    use_timetable = cfg.pp_schedule != "compiled" and n_stages > 1
    if use_timetable:
        from ..distributed.pp_exec import scheduled_pipeline_loss
        from ..distributed.pp_schedule import generate_schedule
        # vpp>1 with a timetable mode runs the interleaved (VPP)
        # schedule through the chunked executor
        if cfg.vpp > 1:
            pp_timetable = generate_schedule("VPP", n_stages, M,
                                             n_chunks=cfg.vpp)
        else:
            pp_timetable = generate_schedule(cfg.pp_schedule, n_stages, M)
        pp_timetable.validate()

    def _rms_head_loss(norm_w, w_head, h, labels_h, constrain=False,
                       onehot_pick=False):
        """final RMSNorm + chunked-CE SUM over h [.., S, H]. constrain
        adds the logits sharding hint (outer-graph path only — inside the
        timetable executor's shard_map the pp axis is manual).
        onehot_pick replaces the label-pick gather with a one-hot
        contraction: under the executor's partial-manual sharding a
        take_along_axis on sep-sharded logits trips the SPMD
        partitioner's device-group factorization CHECK
        (spmd_partitioner_util.cc:495); the contraction partitions
        cleanly (and rides the MXU)."""
        h32 = h.astype(jnp.float32)
        hn = (h32 * jax.lax.rsqrt(
            jnp.mean(jnp.square(h32), -1, keepdims=True) + mc.rms_norm_eps)
        ).astype(h.dtype) * norm_w

        @jax.checkpoint
        def chunk_loss(h_c, labels_c):
            logits = h_c @ w_head
            if constrain:
                logits = jax.lax.with_sharding_constraint(
                    logits,
                    NamedSharding(mesh, P(("dp", "sharding"), None, "mp")))
            logits32 = logits.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits32, axis=-1)
            if onehot_pick:
                oh = jax.nn.one_hot(labels_c, logits32.shape[-1],
                                    dtype=logits32.dtype)
                picked = (logits32 * oh).sum(-1)
            else:
                picked = jnp.take_along_axis(
                    logits32, labels_c[..., None], axis=-1)[..., 0]
            return (lse - picked).sum()

        n_chunks = min(cfg.ce_chunks, S)
        bounds = [i * S // n_chunks for i in range(n_chunks)] + [S]
        total = jnp.zeros((), jnp.float32)
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            total = total + chunk_loss(hn[..., lo:hi, :],
                                       labels_h[..., lo:hi])
        return total

    def loss_fn(compute_params, ids, labels):
        emb = compute_params["outer"][embed_key]
        if mesh.shape.get("mp", 1) > 1:
            # vocab-parallel lookup as a one-hot CONTRACTION: a gather
            # over the vocab-sharded table forces GSPMD into involuntary
            # full rematerialization (replicate the table, then reshard —
            # the r2-flagged SPMD warnings); the contraction partitions
            # cleanly (batch-sharded one-hot x vocab-sharded table =
            # local matmul + psum over mp, the GSPMD analog of Megatron's
            # range-mask + allreduce) and rides the MXU
            oh = jax.nn.one_hot(ids, emb.shape[0], dtype=emb.dtype)
            x = oh @ emb                # [B,S,H]
        else:
            x = jnp.take(emb, ids, axis=0)  # [B,S,H]
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(("dp", "sharding"), "sep", None)))
        if use_timetable:
            # 1F1B/ZBH1/FThenB: the loss head runs ON the last stage
            # inside the executor (the cotangent seeds the interleaved
            # backward); embedding still differentiates through d_mbs.
            # The sep axis is GATHERED at this boundary: seq-sharded
            # operands inside the executor's switch branches deadlock
            # (see pp_exec composition-limit note); in-executor seq
            # parallelism rides mp (Megatron SP), ring context
            # parallelism composes with the compiled path instead.
            x_pp = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(("dp", "sharding"), None, None)))
            mbs = x_pp.reshape((M, B // M) + x_pp.shape[1:])
            if head_key in compute_params["outer"]:
                w_head = compute_params["outer"][head_key]
            else:
                w_head = emb.T
            hp = {"norm": compute_params["outer"][norm_key],
                  "head": w_head}
            labels_mb = labels.reshape((M, B // M, S))
            # one-hot label pick only where it's needed (sep axis in the
            # mesh): it dodges the partitioner CHECK on the gather but
            # costs an O(tokens x vocab) one-hot per CE chunk
            use_onehot = mesh.shape.get("sep", 1) > 1
            total = scheduled_pipeline_loss(
                pp_timetable, stage_fn,
                lambda hp_, y, lab: _rms_head_loss(hp_["norm"],
                                                   hp_["head"], y, lab,
                                                   onehot_pick=use_onehot),
                mesh, compute_params["stacked"], hp, mbs, labels_mb,
                extra_args=(cos.astype(x.dtype), sin.astype(x.dtype)),
                mb_auto_spec=P(("dp", "sharding"), None, None))
            return total / (B * S)
        mbs = x.reshape((M, B // M) + x.shape[1:])
        # remat="full" keeps the stage-level checkpoint (per-tick
        # residual = stage input only, GPipe footprint); for "dots"/"none"
        # the stage body owns the policy — an outer checkpoint would
        # discard what dots_saveable deliberately saved
        if cfg.vpp > 1:
            outs = spmd_pipeline_interleaved(
                stage_fn, compute_params["stacked"], mbs, mesh, M, cfg.vpp,
                extra_args=(cos.astype(x.dtype), sin.astype(x.dtype)),
                remat=(cfg.remat == "full"))
        else:
            outs = spmd_pipeline(stage_fn, compute_params["stacked"], mbs,
                                 mesh, M,
                                 extra_args=(cos.astype(x.dtype),
                                             sin.astype(x.dtype)),
                                 remat=(cfg.remat == "full"))
        h = outs.reshape((B, S, -1))
        if head_key in compute_params["outer"]:
            w_head = compute_params["outer"][head_key]
        else:
            w_head = emb.T
        # Chunked softmax cross-entropy (in _rms_head_loss): never
        # materializes the full [B, S, vocab] f32 logits (the reference's
        # c_softmax_with_cross_entropy solves the same memory blow-up for
        # TP; here the lever is chunking + per-chunk remat — bwd
        # recomputes each chunk's logits instead of keeping 4·B·S·V
        # bytes live). Uneven ceil-division chunk boundaries keep the
        # bound for every S with ≤2 compiled chunk variants.
        total = _rms_head_loss(compute_params["outer"][norm_key], w_head,
                               h, labels, constrain=True)
        loss = total / (B * S)
        return loss

    def train_step(state: TrainState, ids, labels):
        def cast_loss(master_params):
            return loss_fn(decorate_tree(master_params, param_dtype),
                           ids, labels)
        loss, grads = jax.value_and_grad(cast_loss)(state.master)
        new_master, new_opt, gnorm = tx.update(grads, state.opt_state,
                                               state.master)
        new_params = decorate_tree(new_master, param_dtype)
        return TrainState(new_params, new_master, new_opt,
                          state.step + 1), {"loss": loss,
                                            "grad_norm": gnorm}

    state = TrainState(compute, master, opt_state, jnp.zeros((), jnp.int32))

    data_spec = NamedSharding(mesh, P(("dp", "sharding"), None))
    jstep = jax.jit(train_step, donate_argnums=(0,))

    meta = {"model": model, "mesh": mesh, "data_sharding": data_spec,
            "flops_per_token": flops_per_token(mc)}
    return state, jstep, meta
