"""PF401-PF406: the kernel memory lane (docs/ANALYSIS.md).

Static verification of what interpret-mode runtime checks miss — VMEM
budgets, buffer donation, dtype chains — plus the fusion-opportunity
advisory that turns the decode-layer producer/consumer tilings into the
machine-checked worklist for ROADMAP item 1 (mega-kernel decode).  All
byte math comes from :mod:`vmemmodel` (the kernelmodel grid x BlockSpec
evaluator under the published canonical family shapes); this module only
turns it into findings.  Degrade to unknown, never guess: a shape that
does not evaluate is skipped, not reported.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from . import kernelmodel as km
from . import vmemmodel as vm
from .callgraph import PackageIndex
from .kernelmodel import SUB_F32_DTYPES, KernelCallSite
from .model import Config, Finding, register_rule

register_rule("PF401",
              "pallas_call VMEM footprint exceeds the per-core budget "
              "under its canonical decode shapes", "error",
              module=__name__)
register_rule("PF402",
              "donated input buffer (input_output_aliases) is read "
              "after the pallas_call launch", "error", module=__name__)
register_rule("PF403",
              "kernel dtype-chain break: f32 scratch accumulator stored "
              "at reduced precision, or packed-int4 lane not "
              "128-aligned", "error", module=__name__)
register_rule("PF404",
              "adjacent decode-chain kernels with compatible token "
              "tilings — an HBM round-trip a fused kernel would elide "
              "(ROADMAP item 1 worklist)", "info", module=__name__)
register_rule("PF405",
              "grid component does not divide evenly under the real "
              "family shapes (llama/gpt/moe/mla)", "error",
              module=__name__)
register_rule("PF406",
              "registered CostEstimate bytes drift from the "
              "BlockSpec-derived bytes beyond tolerance", "warning",
              module=__name__)

_MIB = 1024 * 1024


def _root_name(expr: ast.AST) -> Optional[str]:
    """Base variable of ``x`` / ``x.attr`` / ``x[i]`` / ``x.astype(...)``
    chains (the buffer a call argument ultimately names)."""
    while True:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, (ast.Attribute, ast.Subscript)):
            expr = expr.value
        elif isinstance(expr, ast.Call):
            expr = expr.func
        else:
            return None


# ---------------------------------------------------------------------------
# PF401 — VMEM budget
# ---------------------------------------------------------------------------

def _pf401(canon: Dict[str, KernelCallSite]) -> List[Finding]:
    out = []
    for qn, site in canon.items():
        entry = vm.CANONICAL[qn]
        fp = vm.site_footprint(site, entry)
        if fp["bytes"] <= vm.VMEM_BYTES_PER_CORE:
            continue
        lb = (" (lower bound: %d block(s) did not evaluate)"
              % fp["unresolved"] if fp["unresolved"] else "")
        out.append(Finding(
            "PF401", "error", site.mi.rel, site.line,
            site.call.col_offset, site.qualname,
            f"static VMEM footprint {fp['bytes'] / _MIB:.1f} MiB exceeds "
            f"the {vm.VMEM_BYTES_PER_CORE // _MIB} MiB per-core budget "
            f"under the canonical {entry['kernel']} shapes{lb}",
            hint="shrink the block/scratch shapes or retile: Mosaic "
                 "will refuse the allocation at compile time on real "
                 "hardware",
            detail=f"vmem:{qn}"))
    return out


# ---------------------------------------------------------------------------
# PF402 — read-after-donate
# ---------------------------------------------------------------------------

def _pf402(sites: List[KernelCallSite]) -> List[Finding]:
    out = []
    for site in sites:
        if not site.aliases or not site.arg_exprs or site.fi is None:
            continue
        boundary = site.call.end_lineno or site.call.lineno
        for a in site.arg_exprs:
            boundary = max(boundary, getattr(a, "end_lineno", 0) or 0)
        seen: Set[str] = set()
        for k in sorted(site.aliases):
            if k >= len(site.arg_exprs):
                continue
            root = _root_name(site.arg_exprs[k])
            if root is None or root in seen:
                continue
            seen.add(root)
            hit = next(
                (n for n in ast.walk(site.fi.node)
                 if isinstance(n, ast.Name) and n.id == root
                 and isinstance(n.ctx, ast.Load)
                 and n.lineno > boundary), None)
            if hit is None:
                continue
            out.append(Finding(
                "PF402", "error", site.mi.rel, hit.lineno,
                hit.col_offset, site.qualname,
                f"`{root}` is donated to output "
                f"{site.aliases[k]} via input_output_aliases but read "
                f"again after the launch — on TPU the buffer has been "
                f"overwritten in place",
                hint="capture the kernel's returned output instead of "
                     "re-reading the donated operand",
                detail=f"alias:{root}->out{site.aliases[k]}"))
    return out


# ---------------------------------------------------------------------------
# PF403 — dtype-chain breaks
# ---------------------------------------------------------------------------

def _scratch_param_names(site: KernelCallSite) -> List[Optional[str]]:
    """Kernel param name per scratch entry (positionally the LAST
    ``len(scratch)`` params), or Nones when unresolvable."""
    n = len(site.scratch or [])
    params = site.kernel_positional_params()
    if not n or params is None or len(params) < n:
        return [None] * n
    return list(params[-n:])


def _astype_sub_f32(value: ast.AST) -> bool:
    """Top-level ``<expr>.astype(<reduced dtype literal>)``."""
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "astype"
            and bool(value.args)
            and km._last_name(value.args[0]) in SUB_F32_DTYPES)


def _kernel_is_int4(site: KernelCallSite) -> bool:
    if site.kernel_fi is None:
        return False
    for n in ast.walk(site.kernel_fi.node):
        if isinstance(n, ast.BinOp):
            if isinstance(n.op, ast.BitAnd) and 0xF in (
                    km._int_const(n.left), km._int_const(n.right)):
                return True
            if isinstance(n.op, ast.RShift) \
                    and km._int_const(n.right) == 4:
                return True
    return False


def _pf403(sites: List[KernelCallSite]) -> List[Finding]:
    out = []
    for site in sites:
        # (a) f32 scratch accumulator stored at reduced precision
        if site.kernel_fi is not None and site.scratch:
            names = _scratch_param_names(site)
            f32_params = {
                nm for nm, expr in zip(names, site.scratch)
                if nm is not None
                and km.scratch_dtype_name(expr) == "float32"}
            if f32_params:
                for node in ast.walk(site.kernel_fi.node):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Subscript)):
                        continue
                    root = km._subscript_root(node.targets[0])
                    if root in f32_params and _astype_sub_f32(node.value):
                        dt = km._last_name(node.value.args[0])
                        out.append(Finding(
                            "PF403", "error", site.mi.rel, node.lineno,
                            node.col_offset, site.qualname,
                            f"f32 scratch accumulator `{root}` in "
                            f"`{site.kernel_fi.qualname}` is stored as "
                            f"{dt} — the online accumulation chain "
                            f"loses precision across grid steps",
                            hint="keep scratch accumulators f32; cast "
                                 "only the final output ref store",
                            detail=f"accum:{root}"))
        # (b) packed-int4 lane alignment
        if not _kernel_is_int4(site):
            continue
        entry = vm.CANONICAL.get(site.qualname)
        bindings = vm.site_bindings(entry) if entry else {}
        env = km.Env(site.mi, site.fi)
        reported: Set[str] = set()
        for specs in (site.in_specs, site.out_specs):
            for spec in specs or []:
                if not spec.block_shape or len(spec.block_shape) < 2:
                    continue
                lane = spec.block_shape[-1]
                v = vm.resolved_value(lane, env, bindings)
                if v is None or v == 1 or v % 128 == 0:
                    continue
                text = km.unparse(lane)
                if text in reported:
                    continue
                reported.add(text)
                out.append(Finding(
                    "PF403", "error", site.mi.rel, site.line,
                    site.call.col_offset, site.qualname,
                    f"packed-int4 kernel lane `{text}` = {v} is neither "
                    f"1 nor a multiple of 128 — nibble unpack breaks "
                    f"the (8, 128) tiling layout invariant",
                    hint="pick a lane block from the 128-multiple "
                         "ladder (the padded-N divisor chain)",
                    detail=f"int4lane:{text}"))
    return out


# ---------------------------------------------------------------------------
# PF404 — fusion opportunities (info; surfaces under --strict)
# ---------------------------------------------------------------------------

def _pf404(index: PackageIndex) -> List[Finding]:
    out = []
    for cand in vm.fusion_candidates(index):
        site = cand["site"]
        how = ("identical token tiling — fusable as-is"
               if cand["class"] == "aligned"
               else "both token-swept at different granularity (retile)")
        out.append(Finding(
            "PF404", "info", site.mi.rel, site.line,
            site.call.col_offset, site.qualname,
            f"decode chain {cand['producer']} -> {cand['consumer']}: "
            f"{how}; the intermediate HBM round-trip is a mega-kernel "
            f"fusion candidate (ROADMAP item 1)",
            hint="see docs/ANALYSIS.md 'PF404 as a fusion worklist'",
            detail=cand["detail"]))
    return out


# ---------------------------------------------------------------------------
# PF405 — grid divisibility under family shapes
# ---------------------------------------------------------------------------

def _pf405(canon: Dict[str, KernelCallSite]) -> List[Finding]:
    out = []
    for qn, site in canon.items():
        entry = vm.CANONICAL[qn]
        env = km.Env(site.mi, site.fi)
        fams: Dict[str, Dict[str, int]] = {"canonical": {}}
        fams.update(entry.get("families", {}))
        reported: Set[str] = set()
        for fam, over in fams.items():
            b = vm.site_bindings(entry)
            b.update(over)
            for e in site.grid_elts or []:
                if not (isinstance(e, ast.BinOp)
                        and isinstance(e.op, ast.FloorDiv)):
                    continue
                num = vm.resolved_value(e.left, env, b)
                den = vm.resolved_value(e.right, env, b)
                if num is None or not den:
                    continue
                if num % den == 0:
                    continue
                text = km.unparse(e)
                if text in reported:
                    continue
                reported.add(text)
                out.append(Finding(
                    "PF405", "error", site.mi.rel, site.line,
                    site.call.col_offset, site.qualname,
                    f"grid component `{text}` = {num} // {den} drops "
                    f"{num % den} row(s) under the {fam} shapes "
                    f"({entry['kernel']}) — the launch silently skips "
                    f"the ragged tail",
                    hint="pad to the block size or derive the block "
                         "from the runtime shape (`_row_block`-style "
                         "divisor ladder)",
                    detail=f"grid:{text}"))
    return out


# ---------------------------------------------------------------------------
# PF406 — cost-model drift
# ---------------------------------------------------------------------------

def _pf406(index: PackageIndex) -> List[Finding]:
    out = []
    for rec in vm.derive_cost_bytes(index):
        if rec["status"] != "drift":
            continue
        out.append(Finding(
            "PF406", "warning", rec["path"], rec["line"], 0,
            rec["qualname"],
            f"cost registry states {rec['expected']} HBM bytes for "
            f"{rec['kernel']} but the committed BlockSpecs transfer "
            f"{rec['derived']} (rel err {rec['rel_err']:.3f} > "
            f"{vm.COST_DRIFT_RTOL}) — the roofline observatory is "
            f"reporting a kernel that no longer exists",
            hint="update observability/costmodel.py (or the canonical "
                 "bindings in analysis/vmemmodel.py) to match the "
                 "edited kernel",
            detail=f"drift:{rec['kernel']}"))
    return out


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    findings: List[Finding] = []
    wanted = [r for r in ("PF401", "PF402", "PF403", "PF404", "PF405",
                          "PF406") if cfg.wants(r)]
    if not wanted:
        return findings
    sites = km.collect_kernel_calls(index)
    canon = {s.qualname: s for s in sites
             if s.qualname in vm.CANONICAL}
    if cfg.wants("PF401"):
        findings.extend(_pf401(canon))
    if cfg.wants("PF402"):
        findings.extend(_pf402(sites))
    if cfg.wants("PF403"):
        findings.extend(_pf403(sites))
    if cfg.wants("PF404"):
        findings.extend(_pf404(index))
    if cfg.wants("PF405"):
        findings.extend(_pf405(canon))
    if cfg.wants("PF406"):
        findings.extend(_pf406(index))
    return findings
