#!/usr/bin/env python
"""Machine-checked perf-regression gate over the committed bench
artifacts (ROADMAP item 5, the lane that makes kernel work safe to
iterate).

Two artifact families are gated, both higher-is-better throughputs:

  - **pretrain** — BENCH_r*.json (one flagship run per round, shape
    ``{"parsed": {"metric", "value"}}``). The acceptance band comes from
    the measured repeat spread: the union of every
    docs/BENCH_REPEATS_r*.json ``runs`` list and recorded ``*_band``
    ranges, widened by --margin (default 1%, on the order of the
    measured 1.03% spread). The LATEST round's value must not fall below
    the band floor.
  - **serving** — docs/SERVING_BENCH.json rows (decode*/prefill*/moe*/
    mla*/serving-engine throughput fields plus the prefix-cache and
    speculative-decode quality stats). No repeat artifacts exist per
    row, so each
    committed value is its own reference with a --noise band around it
    (default 15%, the upper edge of the file's own measurement-protocol
    "10-15% run-to-run variation" note).

Default mode self-checks the committed artifacts (they define the bands,
so they pass by construction unless an artifact is internally
inconsistent — e.g. a new BENCH round below the repeat band was
committed). `--check CANDIDATE.json` gates fresh measurements against
the committed baselines: CANDIDATE holds ``{metric_key: value}`` (keys
as printed in the report, e.g. ``serving.decode.decode_tokens_per_s_per_chip``
or ``pretrain.llama3_8b_shard_pretrain_tokens_per_sec_per_chip``).

Exit status: 0 = every gated row inside its band (or --check candidate
passes), 1 = regression beyond band, 0 with a notice when no artifacts
exist at all (CPU-only tier-1 checkouts stay green — the verify-skill
wiring relies on this).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import types
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One tolerance shared with paddlelint's PF406 cost-drift rule: imported
# from the analyzer (pure stdlib; the stub parent skips paddle_tpu's jax
# imports, same trick as tools/paddlelint.py) so the two gates cannot
# drift apart.
if "paddle_tpu" not in sys.modules:
    _stub = types.ModuleType("paddle_tpu")
    _stub.__path__ = [os.path.join(REPO, "paddle_tpu")]
    sys.modules["paddle_tpu"] = _stub
from paddle_tpu.analysis.vmemmodel import (  # noqa: E402
    COST_DRIFT_RTOL, load_costmodel)

# SERVING_BENCH fields gated per row (all higher-is-better: throughputs
# plus the prefix-cache hit-rate / TTFT-speedup and speculative-decode
# accepted-tokens-per-verify-step quality stats, which regress the same
# way a throughput does when the radix trie or the drafter breaks)
SERVING_FIELDS = ("decode_tokens_per_s_per_chip", "prefill_tokens_per_s",
                  "inflight_tokens_per_s", "ragged_tokens_per_s",
                  "cache_on_tokens_per_s", "prefix_hit_rate",
                  "spec_tokens_per_s", "accepted_tokens_per_verify_step",
                  "mega_tokens_per_s", "split_tokens_per_s",
                  "fused_tokens_per_s",
                  "disagg_tokens_per_s", "colocated_tokens_per_s",
                  "prefill_skip_rate", "fleet_tokens_per_s")

# ISSUE 14/20 launch-accounting pins on the megadecode and front_half
# A/B rows: exact and two-sided — more launches means the fusion
# regressed, fewer means the ledger itself broke. Each holds a
# {mode: count} dict in the artifact (front_half: 2 fused vs 5 split;
# layer body: 5 with both mega halves, 8 with either alone).
SERVING_LAUNCH_FIELDS = ("launches_per_layer", "back_half_launches",
                         "front_half_launches", "layer_body_launches")

# docs/FLEET_BENCH.json scenario rows (ISSUE 16 hostile-traffic
# harness). The scenarios replay bit-exactly from their seed, so the
# deterministic fields are pinned two-sided at exactly the committed
# value — any drift means the replay contract broke. Timing fields are
# machine-dependent: throughputs band like serving rows, latencies gate
# one-sided (slower than band top = regression; faster is a rerate).
FLEET_DETERMINISTIC_FIELDS = ("requests", "completed", "zero_loss",
                              "output_checksum", "handoffs", "shed",
                              "ttft_p90_steps", "e2e_p90_steps")
FLEET_HIGHER_FIELDS = ("fleet_tokens_per_s", "prefill_skip_rate")
FLEET_LOWER_FIELDS = ("ttft_p50_ms", "ttft_p90_ms", "ttft_p99_ms",
                      "e2e_p50_ms", "e2e_p90_ms", "e2e_p99_ms",
                      "handoff_latency_ms")

# OBSERVATORY.json per-kernel fields gated per row (ISSUE 11). These are
# two-sided: bytes or launches GROWING past the band means new HBM
# traffic / extra dispatches snuck into the decode step, while falling
# below it means the cost accounting itself broke — both are findings.
OBSERVATORY_KERNEL_FIELDS = ("bytes", "launches")
OBSERVATORY_SERVING_FIELDS = ("bytes_per_token_model",
                              "bytes_per_token_measured")
#: absolute acceptance band for measured/model bytes-per-token agreement
OBSERVATORY_RATIO_BAND = (0.75, 1.25)


def _load(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# band derivation
# ---------------------------------------------------------------------------

def pretrain_rows(repo: str = REPO, margin: float = 0.01
                  ) -> List[Dict[str, Any]]:
    """One gate row per pretrain metric: the latest BENCH_r*.json value
    vs the repeat-derived band. Band = [min, max] over every repeat run
    and every recorded band, widened by `margin` each side."""
    rounds: List[Tuple[int, str, float]] = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        d = _load(path)
        p = (d or {}).get("parsed") or {}
        if "metric" in p and isinstance(p.get("value"), (int, float)):
            m = re.search(r"BENCH_r(\d+)", path)
            rounds.append((int(m.group(1)) if m else 0,
                           p["metric"], float(p["value"])))
    if not rounds:
        return []
    metric = rounds[-1][1]
    lo, hi = [], []
    for path in sorted(glob.glob(os.path.join(repo, "docs",
                                              "BENCH_REPEATS_r*.json"))):
        d = _load(path) or {}
        if d.get("metric") not in (None, metric):
            continue
        runs = [float(v) for v in d.get("runs", [])
                if isinstance(v, (int, float))]
        if runs:
            lo.append(min(runs))
            hi.append(max(runs))
        for k, v in d.items():
            if k.endswith("_band") and isinstance(v, (list, tuple)) \
                    and len(v) == 2:
                lo.append(float(v[0]))
                hi.append(float(v[1]))
    if not lo:
        # no repeats recorded: band around the historical round values
        vals = [v for _, m, v in rounds if m == metric]
        lo, hi = [min(vals)], [max(vals)]
    band_lo = min(lo) * (1.0 - margin)
    band_hi = max(hi) * (1.0 + margin)
    latest_round, _, latest = max(rounds)
    return [{"key": f"pretrain.{metric}", "value": latest,
             "band": [band_lo, band_hi],
             "source": f"BENCH_r{latest_round:02d}.json",
             "ok": latest >= band_lo}]


def serving_rows(repo: str = REPO, noise: float = 0.15,
                 skips: Optional[List[Dict[str, str]]] = None
                 ) -> List[Dict[str, Any]]:
    """One gate row per (SERVING_BENCH row, throughput field): committed
    value ± noise. Self-check is trivially green; the bands exist for
    --check candidates. Rows excluded from gating are recorded on
    `skips` (when given) so the CLI can report them instead of
    dropping them silently."""
    path = os.path.join(repo, "docs", "SERVING_BENCH.json")
    bench = _load(path)
    if not bench:
        return []
    out = []
    for name, row in bench.items():
        if not isinstance(row, dict):
            continue
        if row.get("predates_megadecode"):
            # row measured before the PR-14 mega-kernel engine rebuild:
            # its throughputs describe a launch structure that no longer
            # exists, so banding fresh candidates against them would
            # misfire both ways — kept in the artifact for history, not
            # gated (remeasure on a chip to clear the flag)
            if skips is not None:
                skips.append({"source": "docs/SERVING_BENCH.json",
                              "key": f"serving.{name}",
                              "why": "predates_megadecode"})
            continue
        for field in SERVING_FIELDS:
            v = row.get(field)
            if not isinstance(v, (int, float)) or v <= 0:
                continue
            v = float(v)
            out.append({"key": f"serving.{name}.{field}", "value": v,
                        "band": [v * (1.0 - noise), v * (1.0 + noise)],
                        "source": "docs/SERVING_BENCH.json",
                        "ok": True})
        for field in SERVING_LAUNCH_FIELDS:
            d = row.get(field)
            if not isinstance(d, dict):
                continue
            for mode, v in sorted(d.items()):
                if not isinstance(v, (int, float)) or v <= 0:
                    continue
                v = float(v)
                out.append({"key": f"serving.{name}.{field}.{mode}",
                            "value": v, "direction": "both",
                            "band": [v, v],
                            "source": "docs/SERVING_BENCH.json",
                            "ok": True})
    return out


def fleet_rows(repo: str = REPO, noise: float = 0.15,
               skips: Optional[List[Dict[str, str]]] = None
               ) -> List[Dict[str, Any]]:
    """One gate row per (FLEET_BENCH scenario, field) — the ISSUE 16
    hostile-traffic harness artifact written by `tools/fleetboard.py
    --selftest`. Deterministic replay fields pin exactly; throughputs
    band ± noise; latency percentiles gate one-sided against the band
    top."""
    path = os.path.join(repo, "docs", "FLEET_BENCH.json")
    art = _load(path)
    if not art:
        return []
    src = "docs/FLEET_BENCH.json"
    out = []
    for name, row in sorted((art.get("scenarios") or {}).items()):
        if not isinstance(row, dict):
            continue
        if row.get("skip_gate"):
            if skips is not None:
                skips.append({"source": src, "key": f"fleet.{name}",
                              "why": str(row["skip_gate"])})
            continue
        for field in FLEET_DETERMINISTIC_FIELDS:
            v = row.get(field)
            if not isinstance(v, (int, float)):
                continue
            v = float(v)
            out.append({"key": f"fleet.{name}.{field}", "value": v,
                        "direction": "both", "band": [v, v],
                        "source": src, "ok": True})
        for field in FLEET_HIGHER_FIELDS:
            v = row.get(field)
            if not isinstance(v, (int, float)) or v <= 0:
                continue
            v = float(v)
            out.append({"key": f"fleet.{name}.{field}", "value": v,
                        "band": [v * (1.0 - noise), v * (1.0 + noise)],
                        "source": src, "ok": True})
        for field in FLEET_LOWER_FIELDS:
            v = row.get(field)
            if not isinstance(v, (int, float)) or v <= 0:
                continue
            v = float(v)
            out.append({"key": f"fleet.{name}.{field}", "value": v,
                        "direction": "lower",
                        "band": [v * (1.0 - noise), v * (1.0 + noise)],
                        "source": src, "ok": True})
    return out


def _judge(value: float, band: List[float], direction: str) -> bool:
    if direction == "both":
        return band[0] <= value <= band[1]
    if direction == "lower":
        return value <= band[1]
    return value >= band[0]


def observatory_rows(repo: str = REPO, noise: float = 0.15
                     ) -> List[Dict[str, Any]]:
    """Per-kernel bytes-and-launches bands from docs/OBSERVATORY.json
    (ISSUE 11) plus the bytes-per-token pair and the measured/model
    agreement ratio (absolute band — the committed artifact must itself
    satisfy the 25% acceptance gate, so self-check can fail here)."""
    art = _load(os.path.join(repo, "docs", "OBSERVATORY.json"))
    if not art:
        return []
    src = "docs/OBSERVATORY.json"
    out = []
    for k in art.get("kernels", []):
        if not isinstance(k, dict) or not k.get("kernel"):
            continue
        for field in OBSERVATORY_KERNEL_FIELDS:
            v = k.get(field)
            if not isinstance(v, (int, float)) or v <= 0:
                continue
            v = float(v)
            out.append({"key": f"observatory.kernel.{k['kernel']}.{field}",
                        "value": v, "direction": "both",
                        "band": [v * (1.0 - noise), v * (1.0 + noise)],
                        "source": src, "ok": True})
    srv = art.get("serving") or {}
    for field in OBSERVATORY_SERVING_FIELDS:
        v = srv.get(field)
        if isinstance(v, (int, float)) and v > 0:
            v = float(v)
            out.append({"key": f"observatory.serving.{field}", "value": v,
                        "direction": "both",
                        "band": [v * (1.0 - noise), v * (1.0 + noise)],
                        "source": src, "ok": True})
    ratio = srv.get("measured_over_model")
    if isinstance(ratio, (int, float)):
        band = list(OBSERVATORY_RATIO_BAND)
        out.append({"key": "observatory.serving.measured_over_model",
                    "value": float(ratio), "direction": "both",
                    "band": band, "source": src,
                    "ok": _judge(float(ratio), band, "both")})
    return out


def gate_rows(repo: str = REPO, margin: float = 0.01,
              noise: float = 0.15,
              skips: Optional[List[Dict[str, str]]] = None
              ) -> List[Dict[str, Any]]:
    return (pretrain_rows(repo, margin)
            + serving_rows(repo, noise, skips=skips)
            + fleet_rows(repo, noise, skips=skips)
            + observatory_rows(repo, noise))


def check_candidate(candidate: Dict[str, float],
                    rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Re-judge `rows` against fresh measurements: for every key present
    in `candidate`, the candidate value replaces the committed one and
    must sit at or above the band floor (higher-is-better: exceeding the
    band top is a rerate, not a failure). Keys the candidate omits are
    left out of the verdict; unknown candidate keys become failing rows
    so typos can't silently pass."""
    by_key = {r["key"]: r for r in rows}
    out = []
    for key, val in candidate.items():
        base = by_key.get(key)
        if base is None:
            out.append({"key": key, "value": val, "band": None,
                        "source": "candidate", "ok": False,
                        "why": "unknown metric key"})
            continue
        direction = base.get("direction", "higher")
        r = dict(base, value=float(val))
        r["ok"] = _judge(float(val), r["band"], direction)
        if not r["ok"]:
            word = ("outside band" if direction == "both"
                    else "regressed below band floor")
            r["why"] = (f"{word} [{r['band'][0]:.3g}, "
                        f"{r['band'][1]:.3g}] (committed "
                        f"{base['value']:.3g})")
        out.append(r)
    return out


def flatten_observatory(art: Dict[str, Any]
                        ) -> Tuple[Dict[str, float],
                                   List[Dict[str, Any]]]:
    """Turn an OBSERVATORY.json-shaped candidate into {metric_key:
    value} plus pre-failed rows for every kernel entry missing a gated
    field (a candidate that stops reporting bytes must not pass by
    omission)."""
    flat: Dict[str, float] = {}
    bad: List[Dict[str, Any]] = []
    for k in art.get("kernels", []):
        name = (k.get("kernel") if isinstance(k, dict) else None) \
            or "<unnamed>"
        for field in OBSERVATORY_KERNEL_FIELDS:
            v = k.get(field) if isinstance(k, dict) else None
            if isinstance(v, (int, float)):
                flat[f"observatory.kernel.{name}.{field}"] = float(v)
            else:
                bad.append({"key": f"observatory.kernel.{name}.{field}",
                            "value": None, "band": None,
                            "source": "candidate", "ok": False,
                            "why": f"candidate kernel row missing "
                                   f"'{field}'"})
    srv = art.get("serving") or {}
    for field in OBSERVATORY_SERVING_FIELDS + ("measured_over_model",):
        v = srv.get(field)
        if isinstance(v, (int, float)):
            flat[f"observatory.serving.{field}"] = float(v)
    return flat, bad


#: scenario fields an observatory candidate must record for the static
#: cross-check to recompute its per-kernel bytes
_SCENARIO_KEYS = ("max_slots", "context", "hidden", "heads", "kv_heads",
                  "head_dim", "intermediate", "page_size", "layers",
                  "device_steps", "weight_bytes_per_layer")


def vmem_drift_rows(candidate: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-check an observatory candidate's per-kernel bytes against a
    fresh costmodel recompute at the candidate's own recorded scenario
    shapes — the same registry paddlelint's PF406 holds byte-consistent
    with the committed BlockSpecs, judged at the same COST_DRIFT_RTOL.
    A candidate whose bytes disagree was produced by a stale or edited
    cost table and must not rerate the bands. Candidates predating the
    scenario extension (missing recompute fields) are skipped, not
    failed, so old artifacts stay green."""
    sc = candidate.get("scenario") or {}
    if any(not isinstance(sc.get(k), (int, float))
           for k in _SCENARIO_KEYS):
        return []
    cm = load_costmodel()
    if cm is None:
        return []
    try:
        layer = cm.decode_layer_kernels(
            "llama", batch=int(sc["max_slots"]),
            context=int(sc["context"]), hidden=int(sc["hidden"]),
            heads=int(sc["heads"]), kv_heads=int(sc["kv_heads"]),
            head_dim=int(sc["head_dim"]),
            intermediate=int(sc["intermediate"]),
            page_size=int(sc["page_size"]),
            weight_bytes_per_layer=int(sc["weight_bytes_per_layer"]))
    except Exception:
        return []
    mult = int(sc["layers"]) * int(sc["device_steps"])
    out = []
    for k in candidate.get("kernels", []):
        if not isinstance(k, dict):
            continue
        name, v = k.get("kernel"), k.get("bytes")
        ref = layer["kernels"].get(name)
        if ref is None or not isinstance(v, (int, float)) or v <= 0:
            continue
        n, est = ref
        expected = float(est.hbm_bytes * n * mult)
        if expected <= 0:
            continue
        rel = abs(float(v) - expected) / expected
        row = {"key": f"observatory.vmem.{name}.bytes",
               "value": float(v), "band": [expected, expected],
               "source": "costmodel@scenario",
               "ok": rel <= COST_DRIFT_RTOL}
        if not row["ok"]:
            row["why"] = (f"disagrees with the static memory model by "
                          f"{rel:.1%} (tolerance {COST_DRIFT_RTOL:.0%}:"
                          f" model says {expected:.0f} bytes)")
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--check", metavar="CANDIDATE.json",
                    help="gate fresh {metric_key: value} measurements "
                         "against the committed bands")
    ap.add_argument("--margin", type=float, default=0.01,
                    help="extra fractional width on the pretrain repeat "
                         "band (default 0.01)")
    ap.add_argument("--noise", type=float, default=0.15,
                    help="fractional band around committed serving rows "
                         "(default 0.15 per the measurement protocol)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    skips: List[Dict[str, str]] = []
    rows = gate_rows(args.repo, args.margin, args.noise, skips=skips)
    if not rows:
        print("perf_gate: no bench artifacts found — nothing to gate "
              "(ok)")
        return 0
    if args.check:
        cand = _load(args.check)
        if cand is None:
            print(f"perf_gate: cannot read candidate {args.check}",
                  file=sys.stderr)
            return 2
        if isinstance(cand.get("kernels"), list):
            # an OBSERVATORY.json-shaped candidate: flatten to metric
            # keys; missing gated fields become pre-failed rows
            flat, bad = flatten_observatory(cand)
            rows = (check_candidate(flat, rows) + bad
                    + vmem_drift_rows(cand))
        else:
            rows = check_candidate(
                {k: v for k, v in cand.items()
                 if isinstance(v, (int, float))}, rows)
        if not rows:
            print("perf_gate: candidate contains no gated metrics (ok)")
            return 0
    failed = [r for r in rows if not r["ok"]]
    if args.json:
        print(json.dumps({"rows": rows, "failed": len(failed),
                          "skipped": skips}, indent=1))
    else:
        for r in rows:
            band = (f"[{r['band'][0]:.1f}, {r['band'][1]:.1f}]"
                    if r.get("band") else "-")
            mark = "ok  " if r["ok"] else "FAIL"
            val = (f"{r['value']:>12.1f}" if r["value"] is not None
                   else f"{'-':>12}")
            line = f"{mark} {r['key']:<58} {val}  band {band}"
            if r.get("why"):
                line += f"  ({r['why']})"
            print(line)
        # per-artifact accounting, skips included: a stale-band row
        # dropped from gating must be VISIBLE, not silently green
        for source in sorted({r["source"] for r in rows}
                             | {s["source"] for s in skips}):
            checked = sum(r["source"] == source for r in rows)
            sk = [s for s in skips if s["source"] == source]
            line = f"perf_gate: {source}: {checked} rows checked"
            if sk:
                reasons = sorted({s["why"] for s in sk})
                counts = ", ".join(
                    f"{sum(s['why'] == w for s in sk)} {w}"
                    for w in reasons)
                line += f", {len(sk)} skipped ({counts})"
            print(line)
        print(f"perf_gate: {len(rows) - len(failed)}/{len(rows)} rows "
              f"inside band")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
