"""Recurrent layers — paddle.nn.{SimpleRNN,LSTM,GRU} + cells (ref:
python/paddle/nn/layer/rnn.py over the cuDNN RNN kernels,
paddle/phi/kernels/gpu/rnn_kernel.cu).

TPU-native mechanism: the time loop is a `lax.scan` over the sequence —
XLA compiles it into an on-device loop (no cuDNN descriptor machinery).
Gate equations follow the cuDNN formulation (identical in paddle and
torch), so weights transplant 1:1. Layout: batch-first [B, T, C] by
default (`time_major=False`), multi-layer, optional bidirection.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .layers import Layer
from .. import initializer as I

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "SimpleRNN", "LSTM",
           "GRU", "RNN"]


def _uniform_init(fan, shape):
    k = 1.0 / math.sqrt(fan)
    return I.Uniform(-k, k)(list(shape), "float32")


class _CellBase(Layer):
    def __init__(self, input_size: int, hidden_size: int, gates: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        g = gates * hidden_size
        self.weight_ih = self.create_parameter([g, input_size])
        self.weight_hh = self.create_parameter([g, hidden_size])
        self.bias_ih = self.create_parameter([g], is_bias=True)
        self.bias_hh = self.create_parameter([g], is_bias=True)
        for p, fan in ((self.weight_ih, hidden_size),
                       (self.weight_hh, hidden_size),
                       (self.bias_ih, hidden_size),
                       (self.bias_hh, hidden_size)):
            p._data = _uniform_init(fan, p.shape)

    def _gates(self, x, h):
        return (x @ self.weight_ih._data.T + self.bias_ih._data
                + h @ self.weight_hh._data.T + self.bias_hh._data)

    def _params(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)



def _norm_state(states, n):
    """Accept Tensor, tuple of Tensors, or None; return tuple of Tensors."""
    if states is None:
        return None
    if isinstance(states, Tensor):
        st = (states,)
    else:
        st = tuple(states)
    if len(st) != n:
        raise ValueError(f"expected {n} state tensor(s), got {len(st)}")
    return tuple(x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
                 for x in st)


def _cell_forward(cell, op_name, inputs, states, n_states):
    B = inputs.shape[0]
    H = cell.hidden_size
    init = _norm_state(states, n_states)
    if init is None:
        init = tuple(Tensor(jnp.zeros((B, H))) for _ in range(n_states))
    n_p = 4

    def impl(x, *rest):
        params, st = rest[:n_p], rest[n_p:]
        out, ncarry = cell._pure_step(params, x, tuple(st))
        return (out,) + tuple(ncarry)
    # states go through dispatch too: BPTT through chained cells and
    # grads into user-provided initial states both need the link
    res = apply(op_name, impl, [inputs, *cell._params(), *init])
    carry = tuple(res[1:])
    # paddle convention: 1-state cells return the bare tensor
    return res[0], (carry if n_states > 1 else carry[0])


class SimpleRNNCell(_CellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 name=None):
        super().__init__(input_size, hidden_size, 1)
        if activation not in ("tanh", "relu"):
            raise ValueError(f"activation must be tanh|relu, got "
                             f"{activation!r}")
        self.activation = activation

    def _step(self, x, state):
        return self._pure_step(
            tuple(p._data for p in self._params()), x, state)

    def _pure_step(self, params, x, state):
        w_ih, w_hh, b_ih, b_hh = params
        h = state[0] if isinstance(state, tuple) else state
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        nh = act(x @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
        return nh, (nh,)

    def forward(self, inputs, states=None):
        return _cell_forward(self, "simple_rnn_cell", inputs, states, 1)


class LSTMCell(_CellBase):
    """cuDNN gate order [i, f, g, o]."""

    def __init__(self, input_size, hidden_size, name=None):
        super().__init__(input_size, hidden_size, 4)

    def _step(self, x, state):
        return self._pure_step(
            tuple(p._data for p in self._params()), x, state)

    def _pure_step(self, params, x, state):
        w_ih, w_hh, b_ih, b_hh = params
        h, c = state
        z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        H = self.hidden_size
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H:2 * H])
        g = jnp.tanh(z[..., 2 * H:3 * H])
        o = jax.nn.sigmoid(z[..., 3 * H:])
        nc = f * c + i * g
        nh = o * jnp.tanh(nc)
        return nh, (nh, nc)

    def forward(self, inputs, states=None):
        return _cell_forward(self, "lstm_cell", inputs, states, 2)


class GRUCell(_CellBase):
    """cuDNN gate order [r, z, n]; h' = (1-z)*n + z*h."""

    def __init__(self, input_size, hidden_size, name=None):
        super().__init__(input_size, hidden_size, 3)

    def _step(self, x, state):
        return self._pure_step(
            tuple(p._data for p in self._params()), x, state)

    def _pure_step(self, params, x, state):
        w_ih, w_hh, b_ih, b_hh = params
        h = state[0] if isinstance(state, tuple) else state
        H = self.hidden_size
        gi = x @ w_ih.T + b_ih
        gh = h @ w_hh.T + b_hh
        r = jax.nn.sigmoid(gi[..., :H] + gh[..., :H])
        z = jax.nn.sigmoid(gi[..., H:2 * H] + gh[..., H:2 * H])
        n = jnp.tanh(gi[..., 2 * H:] + r * gh[..., 2 * H:])
        nh = (1.0 - z) * n + z * h
        return nh, (nh,)

    def forward(self, inputs, states=None):
        return _cell_forward(self, "gru_cell", inputs, states, 1)


class RNN(Layer):
    """Run a cell over time (ref: paddle.nn.RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False, name=None):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None):
        if not isinstance(inputs, Tensor):
            inputs = Tensor(jnp.asarray(inputs))
        B = inputs.shape[0] if not self.time_major else inputs.shape[1]
        H = self.cell.hidden_size
        n_states = 2 if isinstance(self.cell, LSTMCell) else 1
        init = _norm_state(initial_states, n_states)
        if init is None:
            init = tuple(Tensor(jnp.zeros((B, H))) for _ in range(n_states))

        cell = self.cell
        time_major, is_reverse = self.time_major, self.is_reverse
        n_p = 4

        def impl(xx, *rest):
            # params AND initial states enter through dispatch so autograd
            # reaches the weights and any state provider (e.g. an encoder)
            params, st = rest[:n_p], tuple(rest[n_p:])
            if not time_major:
                xx = jnp.swapaxes(xx, 0, 1)  # [T, B, C]
            if is_reverse:
                xx = jnp.flip(xx, 0)

            def step(carry, xt):
                out, ncarry = cell._pure_step(params, xt, carry)
                return ncarry, out
            carry, ys = jax.lax.scan(step, st, xx)
            if is_reverse:
                ys = jnp.flip(ys, 0)
            if not time_major:
                ys = jnp.swapaxes(ys, 0, 1)
            return (ys,) + tuple(carry)
        res = apply("rnn_scan", impl, [inputs, *cell._params(), *init])
        y, carry = res[0], tuple(res[1:])
        return y, (carry if len(carry) > 1 else carry[0])


class _MultiLayerRNN(Layer):
    CELL = None
    N_STATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"direction must be forward|bidirect, got "
                             f"{direction!r}")
        self.bidirect = direction != "forward"
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.time_major = time_major
        self.dropout = dropout
        ndir = 2 if self.bidirect else 1
        from .layers import LayerList
        cells_fw, cells_bw, rnns_fw, rnns_bw = [], [], [], []
        for l in range(num_layers):
            in_sz = input_size if l == 0 else hidden_size * ndir
            cfw = self._make_cell(in_sz, hidden_size, activation)
            cells_fw.append(cfw)
            rnns_fw.append(RNN(cfw, time_major=time_major))
            if self.bidirect:
                cbw = self._make_cell(in_sz, hidden_size, activation)
                cells_bw.append(cbw)
                rnns_bw.append(RNN(cbw, is_reverse=True,
                                   time_major=time_major))
        self.cells_fw = LayerList(cells_fw)
        self.cells_bw = LayerList(cells_bw) if self.bidirect else None
        # wrappers share the cells' parameters; built once, reused per call
        self._rnns_fw = rnns_fw
        self._rnns_bw = rnns_bw

    def _make_cell(self, in_sz, hidden, activation):
        if self.CELL is SimpleRNNCell:
            return SimpleRNNCell(in_sz, hidden, activation)
        return self.CELL(in_sz, hidden)

    def _layer_states(self, initial_states, l, d, ndir):
        """Slice [num_layers*ndir, B, H] stacked states for (layer, dir)."""
        if initial_states is None:
            return None
        st = initial_states if isinstance(initial_states, (tuple, list)) \
            else (initial_states,)
        idx = l * ndir + d
        return tuple(x[idx] for x in st)

    def forward(self, inputs, initial_states=None):
        from ..functional import dropout as F_dropout
        from ...tensor.manipulation import concat, stack
        x = inputs
        ndir = 2 if self.bidirect else 1
        finals = []
        for l in range(self.num_layers):
            y_fw, st_fw = self._rnns_fw[l](
                x, self._layer_states(initial_states, l, 0, ndir))
            if self.bidirect:
                y_bw, st_bw = self._rnns_bw[l](
                    x, self._layer_states(initial_states, l, 1, ndir))
                y = concat([y_fw, y_bw], axis=-1)
                finals.append((st_fw, st_bw))
            else:
                y = y_fw
                finals.append((st_fw,))
            if self.dropout and l < self.num_layers - 1 and self.training:
                y = F_dropout(y, p=self.dropout, training=True)
            x = y

        # stack final states to [num_layers*ndir, B, H] (paddle layout)
        def stk(idx):
            parts = []
            for per_layer in finals:
                for st in per_layer:
                    v = st if not isinstance(st, tuple) else st[idx]
                    parts.append(Tensor(v) if not isinstance(v, Tensor)
                                 else v)
            return stack(parts, axis=0)
        if self.N_STATES == 2:
            out_states = (stk(0), stk(1))
        else:
            out_states = stk(0)
        return x, out_states


class SimpleRNN(_MultiLayerRNN):
    """paddle positional order: (input_size, hidden_size, num_layers,
    activation, direction, ...)."""
    CELL = SimpleRNNCell
    N_STATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 activation="tanh", direction="forward", time_major=False,
                 dropout=0.0, name=None):
        super().__init__(input_size, hidden_size, num_layers=num_layers,
                         direction=direction, time_major=time_major,
                         dropout=dropout, activation=activation)


class LSTM(_MultiLayerRNN):
    CELL = LSTMCell
    N_STATES = 2


class GRU(_MultiLayerRNN):
    CELL = GRUCell
    N_STATES = 1
