"""Kernel certification registry (paddle_tpu.ops.oracles).

Importing the ops modules populates the registry as a side effect; this
file checks the certification contract end to end: every authored kernel
is registered, every reference resolves to a callable, every named
parity-test node exists in the tree, and the entries whose parity_test
points HERE are re-run against their XLA reference (interpret mode on
CPU). paddlelint rule PK105 enforces the same contract statically.
"""

import os
import re

import jax.numpy as jnp
import numpy as np

# registration side effects                                  # noqa: F401
from paddle_tpu.ops import (fused, pallas_flash, pallas_flashmask,
                            pallas_gmm, pallas_megadecode,
                            pallas_megafront, pallas_mla,
                            pallas_paged, pallas_ragged, quant)
from paddle_tpu.ops.oracles import oracles, resolve_reference

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXPECTED = {
    "fused_rms_norm", "fused_layer_norm",
    "fused_bias_residual_layer_norm", "fused_moe_dispatch_combine",
    "fused_rope", "fused_rope_append", "fused_append_rows", "swiglu",
    "mla_decode_attention", "gmm", "int4_dequantize",
    "weight_only_linear", "flash_sdpa", "flashmask_sdpa",
    "paged_decode_attention", "paged_decode_attention_v2",
    "ragged_paged_attention", "fused_oproj_norm", "fused_ffn",
    "fused_qkv_rope_append",
}


class TestRegistry:
    def test_every_authored_kernel_registered(self):
        assert EXPECTED <= set(oracles())

    def test_references_resolve_to_callables(self):
        for name, entry in sorted(oracles().items()):
            assert callable(resolve_reference(entry)), name

    def test_parity_test_nodes_exist(self):
        for name, entry in sorted(oracles().items()):
            path, sep, node = entry.parity_test.partition("::")
            assert sep, (name, entry.parity_test)
            full = os.path.join(REPO, path)
            assert os.path.isfile(full), (name, path)
            first = node.split("::")[0]
            with open(full) as f:
                text = f.read()
            assert re.search(rf"(class|def)\s+{re.escape(first)}\b",
                             text), (name, entry.parity_test)


class TestOracleParity:
    """Runtime side of the entries registered with
    parity_test=tests/test_oracles.py::TestOracleParity (the kernels
    whose pre-existing suites pin behavior but not a named oracle)."""

    def _check(self, name, *args, atol=2e-5):
        entry = oracles()[name]
        want = resolve_reference(entry)(*args)   # pure: runs first
        got = entry.kernel(*args)
        if not isinstance(got, tuple):
            got, want = (got,), (want,)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=atol, rtol=atol)

    def test_bias_residual_layer_norm(self):
        rng = np.random.default_rng(0)
        T, H = 8, 256
        x, r = (jnp.asarray(rng.standard_normal((T, H)), jnp.float32)
                for _ in range(2))
        b, w, lb = (jnp.asarray(rng.standard_normal(H), jnp.float32)
                    for _ in range(3))
        self._check("fused_bias_residual_layer_norm", x, r, b, w, lb)

    def test_moe_dispatch_combine(self):
        rng = np.random.default_rng(1)
        T, K, E, C = 8, 2, 8, 128
        keep = jnp.asarray(rng.integers(0, 2, (T, K, E)), jnp.float32)
        oh = jnp.asarray(rng.integers(0, 2, (T, K, C)), jnp.float32)
        gv = jnp.asarray(rng.random((T, K)), jnp.float32)
        self._check("fused_moe_dispatch_combine", keep, oh, gv)

    def test_append_rows(self):
        rng = np.random.default_rng(2)
        KV, total, psz, D, T = 2, 4, 4, 128, 4
        pages = jnp.asarray(rng.standard_normal((KV, total, psz, D)),
                            jnp.float32)
        rows = jnp.asarray(rng.standard_normal((T, KV, D)), jnp.float32)
        # engine contract: tokens sharing a page are adjacent in t
        page_idx = jnp.asarray([1, 1, 2, 2], jnp.int32)
        page_off = jnp.asarray([0, 1, 0, 1], jnp.int32)
        self._check("fused_append_rows", pages, rows, page_idx, page_off)
