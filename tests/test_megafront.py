"""Mega-kernel decode front half (ops/pallas_megafront.py, ISSUE 20).

Interpret-mode parity of fused_qkv_rope_append against its XLA oracle
(ops/references.py qkv_rope_append_reference) across fp / int8 /
packed-int4 and the MLA layout — including non-128 dims and
trash-page sentinel table rows — plus the paged-append seeding
contract (partial-page walk across launches), the eligibility gate's
TPU tiling rules, and the engine wiring: megafront vs split-front
greedy exactness for all four families (fused-on/off and vs solo
generate_cached, including an all-features trace with prefix cache +
spec decode + preemption) and the 2-vs-5 front-half launch
accounting."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.generation import generate_cached
from paddle_tpu.ops.pallas_megafront import (fused_qkv_rope_append,
                                             megafront_eligible)
from paddle_tpu.ops.quant import weight_quantize
from paddle_tpu.ops.references import qkv_rope_append_reference
from paddle_tpu.serving import ServingEngine


def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def _q(rng, K, N, algo):
    w = _rand(rng, K, N)
    qw, s = weight_quantize(w, algo=algo)
    return qw, s.astype(jnp.float32)


def _setup(rng, T, H, heads, kv_heads, D, total=5, psz=4):
    """Standard-layout operands with an adjacency-contract page walk
    (tokens sharing a page adjacent in t, pages 1.. so the engine's
    trash page 0 stays free for the sentinel tests)."""
    h = _rand(rng, T, H)
    w = _rand(rng, H, (heads + 2 * kv_heads) * D)
    cos, sin = _rand(rng, T, D // 2), _rand(rng, T, D // 2)
    kp = _rand(rng, kv_heads, total, psz, D)
    vp = _rand(rng, kv_heads, total, psz, D)
    page_idx = jnp.asarray([1 + t // psz for t in range(T)], jnp.int32)
    page_off = jnp.asarray([t % psz for t in range(T)], jnp.int32)
    return h, w, cos, sin, kp, vp, page_idx, page_off


class TestQkvRopeAppendParity:
    """fused_qkv_rope_append vs qkv_rope_append_reference (the
    registered oracle): fused projection + rope + paged K/V scatter,
    all three outputs."""

    def _check(self, got, want, atol=2e-6):
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=atol, rtol=atol)

    # family geometries incl. non-128 lane widths (interpret mode
    # carries no lane constraint; TPU gates via megafront_eligible)
    @pytest.mark.parametrize("T,H,heads,kv,D", [(8, 64, 4, 2, 16),
                                                (8, 40, 3, 1, 12),
                                                (4, 24, 2, 2, 8)])
    def test_fp_exact(self, T, H, heads, kv, D):
        rng = np.random.default_rng(0)
        h, w, cos, sin, kp, vp, pg, off = _setup(rng, T, H, heads, kv, D)
        kw = dict(heads=heads, kv_heads=kv, head_dim=D)
        got = fused_qkv_rope_append(h, w, None, None, cos, sin, kp, vp,
                                    pg, off, **kw)
        want = qkv_rope_append_reference(h, w, None, None, cos, sin,
                                         kp, vp, pg, off, **kw)
        self._check(got, want)

    def test_fp_gpt_bias_identity_trig(self):
        # gpt geometry: heads == kv_heads, qkv bias, identity trig
        rng = np.random.default_rng(1)
        T, H, nh, D = 8, 32, 2, 16
        h, w, _, _, kp, vp, pg, off = _setup(rng, T, H, nh, nh, D)
        b = _rand(rng, 3 * nh * D)
        cos = jnp.ones((T, D // 2), jnp.float32)
        sin = jnp.zeros((T, D // 2), jnp.float32)
        kw = dict(heads=nh, kv_heads=nh, head_dim=D)
        got = fused_qkv_rope_append(h, w, None, b, cos, sin, kp, vp,
                                    pg, off, **kw)
        want = qkv_rope_append_reference(h, w, None, b, cos, sin,
                                         kp, vp, pg, off, **kw)
        self._check(got, want)

    def test_int8_exact(self):
        rng = np.random.default_rng(2)
        T, H, heads, kv, D = 8, 64, 4, 2, 16
        h, _, cos, sin, kp, vp, pg, off = _setup(rng, T, H, heads, kv, D)
        qw, s = _q(rng, H, (heads + 2 * kv) * D, "weight_only_int8")
        kw = dict(heads=heads, kv_heads=kv, head_dim=D,
                  algo="weight_only_int8")
        got = fused_qkv_rope_append(h, qw, s, None, cos, sin, kp, vp,
                                    pg, off, **kw)
        want = qkv_rope_append_reference(h, qw, s, None, cos, sin,
                                         kp, vp, pg, off, **kw)
        self._check(got, want)

    @pytest.mark.parametrize("H", [64, 40])     # incl. non-128 dims
    def test_int4_tracks_oracle(self, H):
        rng = np.random.default_rng(3)
        T, heads, kv, D = 8, 4, 2, 16
        h, _, cos, sin, kp, vp, pg, off = _setup(rng, T, H, heads, kv, D)
        qw, s = _q(rng, H, (heads + 2 * kv) * D, "weight_only_int4")
        kw = dict(heads=heads, kv_heads=kv, head_dim=D,
                  algo="weight_only_int4")
        got = fused_qkv_rope_append(h, qw, s, None, cos, sin, kp, vp,
                                    pg, off, **kw)
        want = qkv_rope_append_reference(h, qw, s, None, cos, sin,
                                         kp, vp, pg, off, **kw)
        # int4 contracts even/odd planes separately — summation-order
        # noise only vs the whole-dequant oracle
        self._check(got, want, atol=1e-5)

    def test_sentinel_trash_page_rows(self):
        # inactive ragged slots interleave trash-page-0 visits between
        # real pages (the engine's sentinel table rows). The trash page
        # re-seeds on every revisit — its content is garbage by
        # contract — but the REAL pages and every q row must still
        # match the oracle at 2e-6.
        rng = np.random.default_rng(4)
        T, H, heads, kv, D = 6, 32, 2, 1, 16
        h, w, cos, sin, kp, vp, _, _ = _setup(rng, T, H, heads, kv, D)
        pg = jnp.asarray([0, 2, 2, 0, 3, 0], jnp.int32)
        off = jnp.asarray([0, 0, 1, 1, 0, 2], jnp.int32)
        kw = dict(heads=heads, kv_heads=kv, head_dim=D)
        q, kp2, vp2 = fused_qkv_rope_append(h, w, None, None, cos, sin,
                                            kp, vp, pg, off, **kw)
        qr, kpr, vpr = qkv_rope_append_reference(h, w, None, None, cos,
                                                 sin, kp, vp, pg, off,
                                                 **kw)
        self._check([q], [qr])
        real = np.asarray([2, 3])
        self._check([np.asarray(kp2)[:, real], np.asarray(vp2)[:, real]],
                    [np.asarray(kpr)[:, real], np.asarray(vpr)[:, real]])

    def test_partial_page_seeding_walk(self):
        # decode fills a page one token per step across SEPARATE
        # launches: each launch must seed the resident block from the
        # aliased input pool so earlier rows survive. Walk offsets
        # 0..3 of one page in four chained calls and compare the final
        # pool against the sequentially-applied oracle.
        rng = np.random.default_rng(5)
        T, H, heads, kv, D = 1, 32, 2, 1, 16
        h4 = _rand(rng, 4, H)
        w = _rand(rng, H, (heads + 2 * kv) * D)
        cos, sin = _rand(rng, 4, D // 2), _rand(rng, 4, D // 2)
        kp = _rand(rng, kv, 3, 4, D)
        vp = _rand(rng, kv, 3, 4, D)
        kpr, vpr = kp, vp
        kw = dict(heads=heads, kv_heads=kv, head_dim=D)
        pg = jnp.asarray([1], jnp.int32)
        for step in range(4):
            off = jnp.asarray([step], jnp.int32)
            h = h4[step:step + 1]
            c, s = cos[step:step + 1], sin[step:step + 1]
            _, kp, vp = fused_qkv_rope_append(h, w, None, None, c, s,
                                              kp, vp, pg, off, **kw)
            _, kpr, vpr = qkv_rope_append_reference(h, w, None, None,
                                                    c, s, kpr, vpr,
                                                    pg, off, **kw)
        self._check([kp, vp], [kpr, vpr])


class TestMlaLayout:
    """The MLA front: q (+rope tail) + kv_a projection + in-launch
    latent rms norm + [latent | rope-key] row append, one pool."""

    def _setup(self, rng, T=8, H=40, heads=2, dn=16, dr=8, r=12,
               total=4, psz=4):
        h = _rand(rng, T, H)
        w = _rand(rng, H, heads * (dn + dr) + r + dr)
        g = _rand(rng, r)
        cos, sin = _rand(rng, T, dr // 2), _rand(rng, T, dr // 2)
        pool = _rand(rng, 1, total, psz, r + dr)
        pg = jnp.asarray([1 + t // psz for t in range(T)], jnp.int32)
        off = jnp.asarray([t % psz for t in range(T)], jnp.int32)
        kw = dict(heads=heads, norm_weight=g, eps=1e-6, nope_dim=dn,
                  rope_dim=dr, lora_rank=r)
        return h, w, cos, sin, pool, pg, off, kw

    def _check(self, got, want, atol=2e-6):
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=atol, rtol=atol)

    def test_fp_exact(self):
        rng = np.random.default_rng(6)
        h, w, cos, sin, pool, pg, off, kw = self._setup(rng)
        got = fused_qkv_rope_append(h, w, None, None, cos, sin, pool,
                                    None, pg, off, **kw)
        want = qkv_rope_append_reference(h, w, None, None, cos, sin,
                                         pool, None, pg, off, **kw)
        self._check(got, want)

    def test_int8_exact(self):
        rng = np.random.default_rng(7)
        h, w, cos, sin, pool, pg, off, kw = self._setup(rng)
        qw, s = weight_quantize(w, algo="weight_only_int8")
        kw["algo"] = "weight_only_int8"
        got = fused_qkv_rope_append(h, qw, s.astype(jnp.float32), None,
                                    cos, sin, pool, None, pg, off, **kw)
        want = qkv_rope_append_reference(h, qw, s.astype(jnp.float32),
                                         None, cos, sin, pool, None,
                                         pg, off, **kw)
        self._check(got, want)

    def test_v_pages_rejected(self):
        rng = np.random.default_rng(8)
        h, w, cos, sin, pool, pg, off, kw = self._setup(rng)
        with pytest.raises(ValueError):
            fused_qkv_rope_append(h, w, None, None, cos, sin, pool,
                                  pool, pg, off, **kw)


class TestEligibility:
    """megafront_eligible: always True in interpret mode; on TPU the
    128-lane / even-contraction / VMEM-budget rules gate the default
    and the engine falls back to the split front."""

    def test_interpret_mode_always_eligible(self):
        assert megafront_eligible(40, 152, 12)

    def test_tpu_rules(self, monkeypatch):
        import paddle_tpu.ops.pallas_megafront as mf
        monkeypatch.setattr(mf, "_interpret", lambda: False)
        # the llama3_8b 8-way shard geometry (SERVING_BENCH) tiles
        assert mf.megafront_eligible(512, 768, 128)
        assert mf.megafront_eligible(512, 768, 128, int4=True)
        # non-128 lane dims fall back (the mla deploy N=3648 case)
        assert not mf.megafront_eligible(520, 768, 128)
        assert not mf.megafront_eligible(512, 760, 128)
        assert not mf.megafront_eligible(640, 3648, 192)
        # unsharded llama3-8B qkv slab blows the VMEM weight budget
        assert not mf.megafront_eligible(4096, 6144, 128)


def _solo(model, prompt, max_new, **kw):
    out, _ = generate_cached(model, paddle.to_tensor(prompt[None]),
                             max_new_tokens=max_new,
                             decode_strategy="greedy_search", **kw)
    return out.numpy()[0]


class TestEngineMegafront:
    """Engine wiring: default-on fused front half, split-front
    fallback parity, per-family and quantized exactness vs solo
    generate_cached, MLA fallbacks, launch accounting."""

    @pytest.fixture(scope="class")
    def model(self):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=2))
        m.eval()
        return m

    def _run(self, model, prompts, max_new=4, **kw):
        eng = ServingEngine(model, max_slots=2, page_size=4,
                            prefill_chunk=4, **kw)
        for i, p in enumerate(prompts):
            eng.add_request(p, max_new_tokens=max_new, request_id=i)
        return eng.run_to_completion(), eng

    def test_default_on_and_front_half_launches(self, model):
        eng = ServingEngine(model, max_slots=2, page_size=4)
        assert eng.megafront
        assert eng.front_half_launches == 2
        # ISSUE 20 acceptance: the whole decode layer body is <=5
        assert eng.hbm_accounting()["layer_body_launches"] <= 5
        off = ServingEngine(model, max_slots=2, page_size=4,
                            megafront=False)
        assert not off.megafront
        assert off.front_half_launches == 5
        assert off.hbm_accounting()["layer_body_launches"] == 8

    def test_megafront_matches_split_front_and_solo(self, model):
        V = model.config.vocab_size
        rng = np.random.RandomState(31)
        prompts = [rng.randint(0, V, rng.randint(3, 9)).astype(np.int32)
                   for _ in range(3)]
        on, e1 = self._run(model, prompts)
        off, e2 = self._run(model, prompts, megafront=False)
        assert e1.megafront and not e2.megafront
        assert set(on) == set(off)
        for i in on:
            np.testing.assert_array_equal(on[i], off[i])
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(on[i], _solo(model, p, 4))
        assert all(v == 1 for v in e1.program_cache_sizes().values())
        assert all(v == 1 for v in e2.program_cache_sizes().values())

    def test_gpt_megafront_matches_split_front(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
        paddle.seed(0)
        c = gpt_tiny_config(max_position_embeddings=64)
        m = GPTForCausalLM(c)
        m.eval()
        rng = np.random.RandomState(32)
        prompts = [rng.randint(0, c.vocab_size, rng.randint(3, 7))
                   .astype(np.int32) for _ in range(2)]
        on, e1 = self._run(m, prompts)
        off, e2 = self._run(m, prompts, megafront=False)
        assert e1.megafront and not e2.megafront
        # gpt's native fused-qkv weight needs no deploy concat: the
        # split front is only 3 launches (norm + qkv dot + rope-append)
        assert e1.front_half_launches == 2
        assert e2.front_half_launches == 3
        for i in on:
            np.testing.assert_array_equal(on[i], off[i])
            np.testing.assert_array_equal(on[i], _solo(m, prompts[i], 4))

    def test_moe_megafront_matches_solo(self):
        from paddle_tpu.models.moe_llm import (MoEForCausalLM,
                                               qwen2_moe_tiny_config)
        paddle.seed(0)
        c = qwen2_moe_tiny_config(moe_dropless=True,
                                  first_k_dense_replace=1,
                                  max_position_embeddings=64)
        m = MoEForCausalLM(c)
        m.eval()
        rng = np.random.RandomState(33)
        prompts = [rng.randint(0, c.vocab_size, rng.randint(3, 9))
                   .astype(np.int32) for _ in range(2)]
        out, eng = self._run(m, prompts)
        assert eng.megafront and eng.front_half_launches == 2
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(out[i], _solo(m, p, 4))

    def test_mla_fused_when_no_q_lora(self):
        from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                                deepseek_v2_tiny_config)
        paddle.seed(0)
        c = deepseek_v2_tiny_config(moe_dropless=True,
                                    num_hidden_layers=2,
                                    q_lora_rank=None)
        m = DeepSeekV2ForCausalLM(c)
        m.eval()
        rng = np.random.RandomState(34)
        prompts = [rng.randint(0, c.vocab_size, rng.randint(3, 9))
                   .astype(np.int32) for _ in range(2)]
        on, e1 = self._run(m, prompts)
        off, e2 = self._run(m, prompts, megafront=False)
        assert e1.megafront and e1.front_half_launches == 2
        assert not e2.megafront
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(on[i], off[i])
            np.testing.assert_array_equal(on[i], _solo(m, p, 4))

    def test_mla_q_lora_falls_back(self):
        # the two-stage q compression contracts against an
        # intermediate normed activation — not the hidden stream — so
        # the fused front can't absorb it; the gate must fall back
        from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                                deepseek_v2_tiny_config)
        paddle.seed(0)
        c = deepseek_v2_tiny_config(moe_dropless=True,
                                    num_hidden_layers=2)
        m = DeepSeekV2ForCausalLM(c)
        m.eval()
        eng = ServingEngine(m, max_slots=2, page_size=4)
        assert not eng.megafront
        assert eng.front_half_launches == 7
        i4 = ServingEngine(m, max_slots=2, page_size=4,
                           weight_only_quant="int4")
        assert not i4.megafront      # packed-int4 MLA also splits

    @pytest.mark.parametrize("quant", ["int8", "int4"])
    def test_quantized_fused_front_exact(self, model, quant):
        # in-kernel dequant paths: greedy tokens equal the solo
        # quantized run exactly, fused front on
        V = model.config.vocab_size
        rng = np.random.RandomState(35)
        prompts = [rng.randint(0, V, rng.randint(3, 9)).astype(np.int32)
                   for _ in range(2)]
        out, eng = self._run(model, prompts, weight_only_quant=quant)
        assert eng.megafront and eng.front_half_launches == 2
        for i, p in enumerate(prompts):
            np.testing.assert_array_equal(
                out[i], _solo(model, p, 4, weight_only_quant=quant))

    def test_all_features_trace_exact(self, model):
        # prefix cache + speculative decoding + oversubscription
        # (queueing/preemption path): fused-on and fused-off runs both
        # reproduce the solo greedy stream for every request
        V = model.config.vocab_size
        rng = np.random.RandomState(36)
        base = rng.randint(0, V, 6).astype(np.int32)
        prompts = [base,                                  # shared
                   np.concatenate([base, base[:3]]),      # prefix
                   np.concatenate([base[:4], base[:4]]),  # repetitive
                   rng.randint(0, V, 5).astype(np.int32),
                   rng.randint(0, V, 7).astype(np.int32)]
        kw = dict(max_new=6, spec_decode=3)
        on, e1 = self._run(model, prompts, **kw)
        off, e2 = self._run(model, prompts, megafront=False, **kw)
        assert e1.megafront and not e2.megafront
        assert e1.prefix_cache is not None and e1.spec_k == 3
        for i, p in enumerate(prompts):
            want = _solo(model, p, 6)
            np.testing.assert_array_equal(on[i], want)
            np.testing.assert_array_equal(off[i], want)
        assert all(v == 1 for v in e1.program_cache_sizes().values())

    def test_launch_metric_path_label(self, model):
        from paddle_tpu import serving as srv
        V = model.config.vocab_size
        rng = np.random.RandomState(37)
        prompts = [rng.randint(0, V, 5).astype(np.int32)]
        self._run(model, prompts)
        m = srv.metrics()
        paths = {s["labels"]["path"]: s["value"]
                 for s in m["serving.engine.launches"]["series"]}
        assert paths.get("unified_megafront", 0) >= 1

    def test_accounting_and_scrape_fields(self, model):
        eng = ServingEngine(model, max_slots=2, page_size=4)
        acc = eng.hbm_accounting()
        assert acc["front_half_launches"] == 2
        assert acc["back_half_launches"] == 2
        assert acc["layer_body_launches"] == 5
        snap = eng.scrape()
        assert "serving.replica.front_half_launches" in snap
        assert "serving.replica.back_half_launches" in snap
