"""Flash prefill in the cached serving paths (r5): a multi-token
prefill-from-zero must route through the O(S) sdpa flash path instead of
materializing [*, S, max_len] f32 scores against the whole cache — the
dense path OOMs long-context prefill (measured: S0=12288 B=8 on a 16 GB
chip) and wastes the (max_len - S) masked columns. Covers the llama/GQA,
GPT and MLA cached bodies plus the padded-head SDPA that unlocks flash
for DeepSeek's dv != dn+dr geometry (ref capability: PaddleNLP use_cache
generation + FlashAttnKernel routing, SURVEY §2.1/§2.2)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.generation import generate, generate_cached
from paddle_tpu.ops import flash_attention as fa


def _ids(B, S, vocab, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randint(1, vocab, (B, S)).astype("int32"))


class TestSdpaPaddedHeads:
    def test_matches_reference_unpadded_math(self):
        # dqk=24, dv=16 (tiny MLA geometry): padding must be exactly
        # score- and output-preserving vs the unpadded composite
        rng = np.random.RandomState(0)
        B, S, H, dqk, dv = 2, 16, 3, 24, 16
        q = jnp.asarray(rng.randn(B, S, H, dqk), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, dqk), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, dv), jnp.float32)
        scale = dqk ** -0.5
        got = fa.sdpa_padded_heads(q, k, v, causal=True, scale=scale)
        exp = fa.sdpa_reference(q, k, v, causal=True, scale=scale)
        assert got.shape == (B, S, H, dv)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_default_scale_uses_unpadded_dim(self):
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 8, 2, 24), jnp.float32)
        k = jnp.asarray(rng.randn(1, 8, 2, 24), jnp.float32)
        v = jnp.asarray(rng.randn(1, 8, 2, 16), jnp.float32)
        got = fa.sdpa_padded_heads(q, k, v, causal=True)
        exp = fa.sdpa_reference(q, k, v, causal=True, scale=24 ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)


class TestCachedPrefillRoute:
    """The cached bodies must CALL the sdpa route at prefill (token
    parity alone can't distinguish it from the dense path)."""

    def _count_sdpa_calls(self, monkeypatch):
        calls = []
        orig = fa.sdpa
        monkeypatch.setattr(
            fa, "sdpa", lambda *a, **k: (calls.append(1), orig(*a, **k))[1])
        return calls

    def test_llama_prefill_routes_sdpa(self, monkeypatch):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
        paddle.seed(5)
        m = LlamaForCausalLM(llama_tiny_config(max_position_embeddings=32))
        m.eval()
        calls = self._count_sdpa_calls(monkeypatch)
        ids = _ids(2, 8, m.config.vocab_size)
        ref, _ = generate(m, ids, max_new_tokens=4,
                          decode_strategy="greedy_search")
        n_buffer = len(calls)
        calls.clear()
        got, _ = generate_cached(m, ids, max_new_tokens=4,
                                 decode_strategy="greedy_search")
        # prefill hits sdpa once per layer; decode steps never do
        assert len(calls) == m.config.num_hidden_layers
        np.testing.assert_array_equal(got.numpy(), ref.numpy())
        assert n_buffer > 0  # the buffer forward also routes sdpa

    def test_gpt_prefill_routes_sdpa(self, monkeypatch):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
        paddle.seed(6)
        m = GPTForCausalLM(gpt_tiny_config(max_position_embeddings=32))
        m.eval()
        calls = self._count_sdpa_calls(monkeypatch)
        ids = _ids(1, 6, m.config.vocab_size, seed=2)
        ref, _ = generate(m, ids, max_new_tokens=4,
                          decode_strategy="greedy_search")
        calls.clear()
        got, _ = generate_cached(m, ids, max_new_tokens=4,
                                 decode_strategy="greedy_search")
        assert len(calls) == m.config.num_hidden_layers
        np.testing.assert_array_equal(got.numpy(), ref.numpy())

    def test_mla_prefill_routes_padded_heads(self, monkeypatch):
        from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM,
                                                deepseek_v2_tiny_config)
        paddle.seed(7)
        cfg = deepseek_v2_tiny_config(moe_dropless=True,
                                      max_position_embeddings=32)
        m = DeepSeekV2ForCausalLM(cfg)
        m.eval()
        calls = []
        orig = fa.sdpa_padded_heads
        monkeypatch.setattr(
            fa, "sdpa_padded_heads",
            lambda *a, **k: (calls.append(1), orig(*a, **k))[1])
        ids = _ids(2, 6, cfg.vocab_size, seed=3)
        ref, _ = generate(m, ids, max_new_tokens=4,
                          decode_strategy="greedy_search")
        # the buffer forward itself routes padded heads (dv != dn+dr)
        assert len(calls) > 0
        calls.clear()
        got, _ = generate_cached(m, ids, max_new_tokens=4,
                                 decode_strategy="greedy_search")
        assert len(calls) == cfg.num_hidden_layers
        np.testing.assert_array_equal(got.numpy(), ref.numpy())


class TestSdpaPrefillPadding:
    """Non-128-multiple prompts must NOT silently take the O(S^2) f32
    composite: sdpa_prefill zero-pads the window to the next 128-multiple
    and routes the segment-id flash path (real tokens segment 1, padding
    segment 0) — exactly causal-preserving because no real query row can
    attend a padded key."""

    def test_short_or_divisible_falls_through_to_sdpa(self, monkeypatch):
        calls = []
        orig = fa.sdpa
        monkeypatch.setattr(
            fa, "sdpa", lambda *a, **k: (calls.append(1), orig(*a, **k))[1])
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 12, 2, 8), jnp.float32)
        out = fa.sdpa_prefill(q, q, q, causal=True)
        assert len(calls) == 1
        assert out.shape == q.shape

    def test_padded_segment_path_matches_reference(self, monkeypatch):
        # force the padded route but keep the masked composite underneath
        # (kernel eligibility off): validates the pad + segment-id math
        # itself is exactly equivalent to unpadded causal attention
        monkeypatch.setattr(fa, "_tpu_flash_available", lambda: True)
        monkeypatch.setattr(fa, "_flash_eligible", lambda *a, **k: False)
        rng = np.random.RandomState(3)
        B, S, H, D = 2, 131, 2, 64
        q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
        got = fa.sdpa_prefill(q, k, v, causal=True, pad_to_flash_min=0)
        exp = fa.sdpa_reference(q, k, v, causal=True)
        assert got.shape == (B, S, H, D)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_s12289_routes_padded_flash(self, monkeypatch):
        # the ADVICE.md shape: a 12289-token prompt misses every flash
        # block divisor by one token. Assert the route, padded geometry
        # and segment ids without paying for the attention compute.
        monkeypatch.setattr(fa, "_tpu_flash_available", lambda: True)
        seen = {}

        def fake_segmented(q, k, v, segment_ids, **kw):
            seen["Sp"] = q.shape[1]
            seen["seg"] = np.asarray(segment_ids)
            seen["causal"] = kw.get("causal")
            return jnp.zeros(q.shape[:3] + (v.shape[-1],), q.dtype)

        monkeypatch.setattr(fa, "sdpa_segmented", fake_segmented)
        B, S, H, D = 1, 12289, 1, 64
        q = jnp.zeros((B, S, H, D), jnp.float32)
        out = fa.sdpa_prefill(q, q, q, causal=True)
        assert seen["Sp"] == 12416  # next 128-multiple
        assert seen["Sp"] % 128 == 0
        assert fa._largest_dividing_block(seen["Sp"]) > 0
        assert seen["causal"] is True
        assert seen["seg"].shape == (B, 12416)
        assert (seen["seg"][0, :S] == 1).all()
        assert (seen["seg"][0, S:] == 0).all()
        assert out.shape == (B, S, H, D)  # padding sliced off

    def test_s12289_composite_fallback_off_tpu(self, monkeypatch):
        # off-TPU there is no flash kernel to rescue: the plain sdpa
        # route must be taken (no padding, no segment detour)
        calls = []
        monkeypatch.setattr(
            fa, "sdpa",
            lambda *a, **k: (calls.append(a[0].shape), jnp.zeros_like(a[2]))[1])
        q = jnp.zeros((1, 12289, 1, 64), jnp.float32)
        out = fa.sdpa_prefill(q, q, q, causal=True)
        assert calls == [(1, 12289, 1, 64)]
        assert out.shape == (1, 12289, 1, 64)


class TestDenseFallbackParity:
    """S>1 with a TRACED start keeps the dense [S, max_len] path (the
    flash branch requires the statically-pinned start=0 program). The
    two programs must agree — the fallback is what chunked or
    library-internal callers hit."""

    def test_traced_start_matches_static_prefill(self):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        from paddle_tpu.generation import (_llama_decode_params,
                                           _cached_step_body,
                                           _llama_weights, _init_caches)
        import jax
        paddle.seed(47)
        m = LlamaForCausalLM(llama_tiny_config(max_position_embeddings=16))
        m.eval()
        p = _llama_decode_params(m)
        body = _cached_step_body(p, 12)
        w = _llama_weights(p)
        rng = np.random.RandomState(8)
        ids = jnp.asarray(rng.randint(1, m.config.vocab_size, (2, 8)),
                          jnp.int32)
        # static start=0 -> flash branch
        flash_logits, flash_caches = jax.jit(
            lambda w, ids, c: body(w, ids, c, 0))(
                w, ids, _init_caches(p, 2, 12))
        # traced start -> dense branch (start abstracted by jit)
        dense_logits, dense_caches = jax.jit(body)(
            w, ids, _init_caches(p, 2, 12), 0)
        np.testing.assert_allclose(np.asarray(flash_logits, np.float32),
                                   np.asarray(dense_logits, np.float32),
                                   rtol=2e-5, atol=2e-5)
        for (fk, fv), (dk, dv) in zip(flash_caches, dense_caches):
            np.testing.assert_allclose(np.asarray(fk), np.asarray(dk),
                                       rtol=2e-5, atol=2e-5)
            np.testing.assert_allclose(np.asarray(fv), np.asarray(dv),
                                       rtol=2e-5, atol=2e-5)
