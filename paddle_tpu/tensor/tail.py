"""Tensor-API long tail, batch 2 (ref surface: python/paddle/tensor/ —
math.py / manipulation.py / creation.py stragglers plus the in-place
`*_` family from the generated inplace API; VERDICT r1 item 8).

Same contract as the rest of the surface: differentiable ops dispatch
through core.dispatch.apply; in-place ops rebind the Tensor's buffer
(value semantics underneath — the XLA-native reading of the reference's
inplace kernels) and, like the reference, are meant for no-grad/leaf
use: they do not record a tape entry.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from . import manipulation as _manip
from . import math as _math

__all__ = [
    # math stragglers
    "copysign", "gammaln", "gammainc", "gammaincc", "multigammaln",
    "i0e", "i1e", "frexp", "isin", "isneginf", "isposinf", "isreal",
    "sigmoid", "baddbmm", "block_diag", "combinations",
    "cumulative_trapezoid", "histogram_bin_edges", "histogramdd",
    "bitwise_left_shift", "bitwise_right_shift", "bitwise_invert",
    "nanargmax", "nanargmin", "positive", "take_along_dim",
    # stacking / layout
    "column_stack", "row_stack", "dstack", "hstack", "vstack",
    "diagonal_scatter", "view_as", "reverse",
    # random
    "standard_gamma", "cauchy_", "geometric_",
    # in-place family
    "ceil_", "exp_", "fill_", "floor_", "reciprocal_", "round_",
    "rsqrt_", "sqrt_", "tanh_", "zero_", "erfinv_", "lerp_",
    "remainder_", "scatter_", "tril_", "triu_", "flatten_", "sigmoid_",
    "index_fill_", "masked_fill_", "index_put_", "fill_diagonal_",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _unary(name, jfn):
    def op(x, name_=None):
        return apply(name, jfn, [x])
    op.__name__ = name
    return op


# ---------------------------------------------------------------------------
# math stragglers
# ---------------------------------------------------------------------------
def copysign(x, y, name=None):
    yv = _arr(y)
    return apply("copysign", lambda a: jnp.copysign(a, yv), [x])


gammaln = _unary("gammaln", jax.scipy.special.gammaln)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1e = _unary("i1e", jax.scipy.special.i1e)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
positive = _unary("positive", lambda a: a)


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (ref: paddle.gammainc)."""
    return apply("gammainc", jax.scipy.special.gammainc, [x, y])


def gammaincc(x, y, name=None):
    return apply("gammaincc", jax.scipy.special.gammaincc, [x, y])


def multigammaln(x, p, name=None):
    p = int(p)

    def impl(a):
        a = a[..., None]
        j = jnp.arange(1, p + 1, dtype=a.dtype)
        terms = jax.scipy.special.gammaln(a + (1.0 - j) / 2.0)
        const = p * (p - 1) / 4.0 * np.log(np.pi)
        return terms.sum(-1) + const
    return apply("multigammaln", impl, [x])


def frexp(x, name=None):
    m, e = jnp.frexp(_arr(x))
    return Tensor(m), Tensor(e.astype(jnp.int32))


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    t = _arr(test_x)
    out = jnp.isin(_arr(x), t, invert=invert)
    return Tensor(out)


def isneginf(x, name=None):
    return Tensor(jnp.isneginf(_arr(x)))


def isposinf(x, name=None):
    return Tensor(jnp.isposinf(_arr(x)))


def isreal(x, name=None):
    return Tensor(jnp.isreal(_arr(x)))


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * bmm(x, y) (ref: paddle.baddbmm)."""
    def impl(inp, a, b):
        return beta * inp + alpha * jnp.matmul(a, b)
    return apply("baddbmm", impl, [input, x, y])


def block_diag(inputs, name=None):
    import jax.scipy.linalg as jsl
    return apply("block_diag", lambda *xs: jsl.block_diag(*xs),
                 list(inputs))


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor (ref: paddle.combinations).
    Index set is static (depends on len(x) only)."""
    n = int(_arr(x).shape[0])
    import itertools as it
    gen = it.combinations_with_replacement(range(n), r) \
        if with_replacement else it.combinations(range(n), r)
    idx = np.asarray(list(gen), np.int32).reshape(-1, r)

    def impl(a):
        return a[jnp.asarray(idx)]
    return apply("combinations", impl, [x])


def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1, name=None):
    def impl(yv):
        y1 = jnp.moveaxis(yv, axis, -1)
        if x is not None:
            xs = jnp.moveaxis(_arr(x), axis, -1) \
                if _arr(x).ndim == yv.ndim else _arr(x)
            d = jnp.diff(xs, axis=-1)
        else:
            d = dx
        avg = (y1[..., 1:] + y1[..., :-1]) / 2.0
        return jnp.moveaxis(jnp.cumsum(avg * d, -1), -1, axis)
    return apply("cumulative_trapezoid", impl, [y])


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    a = np.asarray(_arr(input))
    rng = None if (min == 0 and max == 0) else (min, max)
    return Tensor(np.histogram_bin_edges(a, bins=bins, range=rng)
                  .astype(np.float32))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """Eager (data-dependent bin counts; ref: paddle.histogramdd)."""
    a = np.asarray(_arr(x))
    w = None if weights is None else np.asarray(_arr(weights))
    hist, edges = np.histogramdd(a, bins=bins, range=ranges,
                                 density=density, weights=w)
    return Tensor(hist.astype(np.float32)), [Tensor(e.astype(np.float32))
                                             for e in edges]


def bitwise_left_shift(x, y, is_arithmetic=True, name=None):
    yv = _arr(y)
    return apply("bitwise_left_shift",
                 lambda a: jnp.left_shift(a, yv), [x])


def bitwise_right_shift(x, y, is_arithmetic=True, name=None):
    yv = _arr(y)
    fn = jnp.right_shift if is_arithmetic else \
        lambda a, b: jax.lax.shift_right_logical(a, b.astype(a.dtype))
    return apply("bitwise_right_shift", lambda a: fn(a, yv), [x])


def bitwise_invert(x, out=None, name=None):
    return apply("bitwise_invert", jnp.invert, [x])


def nanargmax(x, axis=None, keepdim=False, name=None):
    out = jnp.nanargmax(_arr(x), axis=axis, keepdims=keepdim)
    return Tensor(out.astype(jnp.int64))


def nanargmin(x, axis=None, keepdim=False, name=None):
    out = jnp.nanargmin(_arr(x), axis=axis, keepdims=keepdim)
    return Tensor(out.astype(jnp.int64))


def take_along_dim(x, indices, dim=0, name=None):
    return _manip.take_along_axis(x, indices, dim)


# ---------------------------------------------------------------------------
# stacking / layout
# ---------------------------------------------------------------------------
def _stackop(name, jfn):
    def op(x, name_=None):
        return apply(name, lambda *xs: jfn(xs), list(x))
    op.__name__ = name
    return op


column_stack = _stackop("column_stack", jnp.column_stack)
dstack = _stackop("dstack", jnp.dstack)
hstack = _stackop("hstack", jnp.hstack)
vstack = _stackop("vstack", jnp.vstack)
row_stack = vstack


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def impl(a, b):
        n = min(a.shape[axis1], a.shape[axis2]) - abs(offset)
        if b.shape[-1] != n:
            raise ValueError(
                f"diagonal_scatter: y length {b.shape[-1]} != diagonal "
                f"length {n} for offset {offset}")
        i = jnp.arange(b.shape[-1])
        rows = i - (offset if offset < 0 else 0)
        cols = i + (offset if offset > 0 else 0)
        # move the diag axes to front for a vectorized scatter
        a2 = jnp.moveaxis(a, (axis1, axis2), (0, 1))
        b2 = jnp.moveaxis(b, -1, 0)
        a2 = a2.at[rows, cols].set(b2)
        return jnp.moveaxis(a2, (0, 1), (axis1, axis2))
    return apply("diagonal_scatter", impl, [x, y])


def view_as(x, other, name=None):
    return _manip.view(x, list(_arr(other).shape))


def reverse(x, axis, name=None):
    """Legacy alias of flip (ref: paddle.reverse -> paddle.flip)."""
    return _manip.flip(x, axis)


# ---------------------------------------------------------------------------
# random
# ---------------------------------------------------------------------------
def standard_gamma(x, name=None):
    from ..framework.random import next_key
    shape_alpha = _arr(x)
    return Tensor(jax.random.gamma(next_key(), shape_alpha))


def cauchy_(x, loc=0, scale=1, name=None):
    _guard_inplace(x, "cauchy_")
    from ..framework.random import next_key
    u = jax.random.uniform(next_key(), _arr(x).shape,
                           minval=1e-7, maxval=1.0 - 1e-7)
    x._data = (loc + scale * jnp.tan(jnp.pi * (u - 0.5))).astype(x.dtype)
    return x


def geometric_(x, probs, name=None):
    _guard_inplace(x, "geometric_")
    from ..framework.random import next_key
    u = jax.random.uniform(next_key(), _arr(x).shape,
                           minval=1e-7, maxval=1.0 - 1e-7)
    p = _arr(probs) if isinstance(probs, Tensor) else probs
    x._data = (jnp.floor(jnp.log1p(-u) / jnp.log1p(-p)) + 1).astype(
        x.dtype)
    return x


# ---------------------------------------------------------------------------
# in-place family: value-semantics rebind (no tape entry, like the
# reference's inplace ops outside autograd)
# ---------------------------------------------------------------------------
def _guard_inplace(x, name):
    """In-place on a grad-requiring tensor would orphan the tape entry
    and silently corrupt gradients — refuse, like the reference refuses
    in-place on leaves that require grad."""
    from ..core import autograd as _ag
    if _ag.is_grad_enabled() and isinstance(x, Tensor) \
            and not x.stop_gradient:
        raise RuntimeError(
            f"{name} in-place on a tensor that requires grad is not "
            f"supported; wrap in no_grad() or use the out-of-place op")


def _inplace_of(fn):
    def op(x, *args, **kwargs):
        from ..core import autograd as _ag
        _guard_inplace(x, getattr(fn, "__name__", "op") + "_")
        with _ag.no_grad():
            out = fn(x, *args, **kwargs)
        x._data = out._data if isinstance(out, Tensor) else out
        return x
    return op


ceil_ = _inplace_of(_math.ceil)
exp_ = _inplace_of(_math.exp)
floor_ = _inplace_of(_math.floor)
reciprocal_ = _inplace_of(_math.reciprocal)
round_ = _inplace_of(_math.round)
rsqrt_ = _inplace_of(_math.rsqrt)
sqrt_ = _inplace_of(_math.sqrt)
tanh_ = _inplace_of(_math.tanh)
erfinv_ = _inplace_of(_math.erfinv)
lerp_ = _inplace_of(_math.lerp)
remainder_ = _inplace_of(_math.remainder)
sigmoid_ = _inplace_of(sigmoid)
flatten_ = _inplace_of(_manip.flatten)
scatter_ = _inplace_of(_manip.scatter)
masked_fill_ = _inplace_of(_manip.masked_fill)
index_fill_ = _inplace_of(_manip.index_fill)


def fill_(x, value, name=None):
    _guard_inplace(x, "fill_")
    x._data = jnp.full_like(x._data, value)
    return x


def zero_(x, name=None):
    return fill_(x, 0)


def tril_(x, diagonal=0, name=None):
    _guard_inplace(x, "tril_")
    x._data = jnp.tril(x._data, k=diagonal)
    return x


def triu_(x, diagonal=0, name=None):
    _guard_inplace(x, "triu_")
    x._data = jnp.triu(x._data, k=diagonal)
    return x


def index_put_(x, indices, value, accumulate=False, name=None):
    _guard_inplace(x, "index_put_")
    idx = tuple(_arr(i) for i in indices)
    v = _arr(value)
    x._data = x._data.at[idx].add(v) if accumulate \
        else x._data.at[idx].set(v)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    _guard_inplace(x, "fill_diagonal_")
    a = x._data
    m, n = a.shape[-2], a.shape[-1]
    if wrap and a.ndim == 2 and m > n:
        # numpy fill_diagonal(wrap=True) semantics: the diagonal
        # continues in bands every n+1 flat positions
        start = offset if offset >= 0 else -offset * n
        flat = np.arange(start, m * n, n + 1)
        rows, cols = np.divmod(flat, n)
    else:
        i = np.arange(min(m, n))  # static indices — jit-safe
        rows = i - (offset if offset < 0 else 0)
        cols = i + (offset if offset > 0 else 0)
        keep = (rows < m) & (cols < n)
        rows, cols = rows[keep], cols[keep]
    x._data = a.at[..., rows, cols].set(value)
    return x


# ---------------------------------------------------------------------------
# in-place family, batch 2 (ref: the generated inplace API surface,
# python/paddle/tensor/inplace_apis in paddle 2.6)
# ---------------------------------------------------------------------------
abs_ = _inplace_of(_math.abs)
acos_ = _inplace_of(_math.acos)
asin_ = _inplace_of(_math.asin)
atan_ = _inplace_of(_math.atan)
atanh_ = _inplace_of(_math.atanh)
acosh_ = _inplace_of(_math.acosh)
asinh_ = _inplace_of(_math.asinh)
cos_ = _inplace_of(_math.cos)
cosh_ = _inplace_of(_math.cosh)
sin_ = _inplace_of(_math.sin)
sinh_ = _inplace_of(_math.sinh)
tan_ = _inplace_of(_math.tan)
expm1_ = _inplace_of(_math.expm1)
log_ = _inplace_of(_math.log)
log2_ = _inplace_of(_math.log2)
log10_ = _inplace_of(_math.log10)
log1p_ = _inplace_of(_math.log1p)
digamma_ = _inplace_of(_math.digamma)
lgamma_ = _inplace_of(_math.lgamma)
neg_ = _inplace_of(_math.neg)
frac_ = _inplace_of(_math.frac)
trunc_ = _inplace_of(_math.trunc)
divide_ = _inplace_of(_math.divide)
floor_divide_ = _inplace_of(_math.floor_divide)
pow_ = _inplace_of(_math.pow)
nan_to_num_ = _inplace_of(_math.nan_to_num)
logit_ = _inplace_of(_math.logit)
hypot_ = _inplace_of(_math.hypot)
ldexp_ = _inplace_of(_math.ldexp)
gcd_ = _inplace_of(_math.gcd)
lcm_ = _inplace_of(_math.lcm)
cumsum_ = _inplace_of(_math.cumsum)
cumprod_ = _inplace_of(_math.cumprod)
renorm_ = _inplace_of(_math.renorm)
index_add_ = _inplace_of(_manip.index_add)
put_along_axis_ = _inplace_of(_manip.put_along_axis)
masked_scatter_ = _inplace_of(_manip.masked_scatter)
copysign_ = _inplace_of(copysign)
gammaln_ = _inplace_of(gammaln)
gammainc_ = _inplace_of(gammainc)
gammaincc_ = _inplace_of(gammaincc)
multigammaln_ = _inplace_of(multigammaln)
atan2_ = _inplace_of(_math.atan2)
deg2rad_ = _inplace_of(_math.deg2rad)
rad2deg_ = _inplace_of(_math.rad2deg)
nextafter_ = _inplace_of(_math.nextafter)
sign_ = _inplace_of(_math.sign)
stanh_ = _inplace_of(_math.stanh)
bitwise_left_shift_ = _inplace_of(bitwise_left_shift)
bitwise_right_shift_ = _inplace_of(bitwise_right_shift)


def index_copy(x, index, axis, value, name=None):
    """ref: paddle.index_copy — rows of ``value`` written into ``x`` at
    ``index`` along ``axis`` (the scatter twin of index_select)."""
    idx = _arr(index).astype(jnp.int32)
    ax = int(axis)

    def impl(a, v):
        mov = jnp.moveaxis(a, ax, 0)
        vv = jnp.moveaxis(v, ax, 0)
        out = mov.at[idx].set(vv)
        return jnp.moveaxis(out, 0, ax)
    return apply("index_copy", impl, [x, value])


def index_copy_(x, index, axis, value, name=None):
    _guard_inplace(x, "index_copy_")
    x._data = index_copy(x, index, axis, value)._data
    return x


__all__ += [
    "abs_", "acos_", "asin_", "atan_", "atanh_", "acosh_", "asinh_",
    "cos_", "cosh_", "sin_", "sinh_", "tan_", "expm1_", "log_", "log2_",
    "log10_", "log1p_", "digamma_", "lgamma_", "neg_", "frac_", "trunc_",
    "divide_", "floor_divide_", "pow_", "nan_to_num_", "logit_",
    "hypot_", "ldexp_", "gcd_", "lcm_", "cumsum_", "cumprod_", "renorm_",
    "index_add_", "put_along_axis_", "masked_scatter_", "copysign_",
    "gammaln_", "gammainc_", "gammaincc_", "multigammaln_",
    "atan2_", "deg2rad_", "rad2deg_", "nextafter_", "sign_", "stanh_",
    "bitwise_left_shift_", "bitwise_right_shift_",
    "index_copy", "index_copy_",
]
