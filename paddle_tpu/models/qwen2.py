"""Qwen2 dense decoder family (ref capability: PaddleNLP
paddlenlp/transformers/qwen2/modeling.py — the dense sibling of the
Qwen2-MoE baseline row, SURVEY §2.4).

Architecture = Llama GQA backbone with two Qwen2 signatures: attention
q/k/v projections carry BIASES (o_proj does not), and small configs tie the
LM head to the token embedding. Reuses the Llama rope/SDPA path; weights
carry the same Megatron TP specs.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.parallel_layers import MP_AXIS, ParallelCrossEntropy
from .llama import (LlamaConfig, LlamaMLP, apply_rope, precompute_rope)

__all__ = ["Qwen2Config", "Qwen2Model", "Qwen2ForCausalLM",
           "qwen2_tiny_config"]


class Qwen2Config(LlamaConfig):
    def __init__(self, qkv_bias=True, **kw):
        kw.setdefault("rope_theta", 1000000.0)
        super().__init__(**kw)
        self.qkv_bias = qkv_bias


def qwen2_tiny_config(**kw) -> Qwen2Config:
    base = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256,
                tie_word_embeddings=True)
    base.update(kw)
    return Qwen2Config(**base)


class Qwen2Attention(nn.Layer):
    """GQA with biased q/k/v projections (the Qwen2 signature)."""

    def __init__(self, c: Qwen2Config):
        super().__init__()
        self.c = c
        H, D, KV = c.num_attention_heads, c.head_dim, c.num_key_value_heads
        bias = c.qkv_bias

        def lin(out_f, col):
            l = nn.Linear(c.hidden_size, out_f,
                          bias_attr=None if (bias and col) else False)
            l.weight._sharding_spec = P(None, MP_AXIS) if col \
                else P(MP_AXIS, None)
            if l.bias is not None:
                l.bias._sharding_spec = P(MP_AXIS)
            return l

        self.q_proj = lin(H * D, True)
        self.k_proj = lin(KV * D, True)
        self.v_proj = lin(KV * D, True)
        self.o_proj = lin(c.hidden_size, False)

    def forward(self, x, cos, sin, attn_mask=None):
        c = self.c
        B, S, _ = x.shape
        H, KV, D = c.num_attention_heads, c.num_key_value_heads, c.head_dim
        mask = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
        from ..core.dispatch import apply as _apply

        def impl(h, wq, bq, wk, bk, wv, bv, wo):
            q = (h @ wq + bq).reshape(B, S, H, D)
            k = (h @ wk + bk).reshape(B, S, KV, D)
            v = (h @ wv + bv).reshape(B, S, KV, D)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            rep = H // KV
            if rep > 1:
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            from ..ops.flash_attention import sdpa, sdpa_reference
            if c.use_flash_attention and mask is None:
                o = sdpa(q, k, v, causal=True)
            else:
                o = sdpa_reference(q, k, v, mask=mask, causal=True)
            return o.reshape(B, S, -1) @ wo

        if c.qkv_bias:
            inputs = [x, self.q_proj.weight, self.q_proj.bias,
                      self.k_proj.weight, self.k_proj.bias,
                      self.v_proj.weight, self.v_proj.bias,
                      self.o_proj.weight]
            return _apply("qwen2_attention", impl, inputs)

        def impl_nobias(h, wq, wk, wv, wo):
            z = jnp.zeros((1,), h.dtype)
            return impl(h, wq, z, wk, z, wv, z, wo)
        return _apply("qwen2_attention", impl_nobias,
                      [x, self.q_proj.weight, self.k_proj.weight,
                       self.v_proj.weight, self.o_proj.weight])


class Qwen2DecoderLayer(nn.Layer):
    def __init__(self, c: Qwen2Config):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(c.hidden_size, c.rms_norm_eps)
        self.self_attn = Qwen2Attention(c)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   c.rms_norm_eps)
        self.mlp = LlamaMLP(c)

    def forward(self, x, cos, sin, attn_mask=None):
        h = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        return h + self.mlp(self.post_attention_layernorm(h))


class Qwen2Model(nn.Layer):
    def __init__(self, config: Qwen2Config):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size)
        self.embed_tokens.weight._data = init(
            [config.vocab_size, config.hidden_size], "float32")
        self.embed_tokens.weight._sharding_spec = P(MP_AXIS, None)
        self.layers = nn.LayerList(
            [Qwen2DecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        cos, sin = precompute_rope(config.head_dim,
                                   config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        cos, sin = self.rope_cos._data, self.rope_sin._data
        for layer in self.layers:
            if self.config.recompute and self.training:
                from ..distributed.recompute import recompute
                x = recompute(layer, x, cos, sin, attn_mask)
            else:
                x = layer(x, cos, sin, attn_mask)
        return self.norm(x)


class Qwen2ForCausalLM(nn.Layer):
    def __init__(self, config: Qwen2Config):
        super().__init__()
        self.config = config
        self.qwen2 = Qwen2Model(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
            self.lm_head.weight._sharding_spec = P(None, MP_AXIS)

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.qwen2(input_ids, attn_mask)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = F.linear(h, self.qwen2.embed_tokens.weight.T)
        if labels is not None:
            tok_loss = ParallelCrossEntropy()(logits, labels)
            return tok_loss.mean(), logits
        return logits
