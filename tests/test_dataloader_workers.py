"""Multiprocess DataLoader workers (ref: python/paddle/io/dataloader/
worker.py — VERDICT r1 item 9): order/content parity with the serial
path, per-worker seeding + worker_init_fn, error propagation, and
genuine cross-process concurrency (interval overlap, not wall-clock).

Everything the loader ships to a worker lives at module level: with a
jax-initialized parent the DataLoader resolves mp_context=None to
"spawn" (fork-after-init is the flake this guards against), and spawn
pickles the dataset, collate_fn and worker_init_fn by qualname.
"""

import os
import pathlib
import time

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset, IterableDataset, \
    get_worker_info


class SquareDataset(Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i, i * i], np.int64)


class OverlapDataset(Dataset):
    """Each item sleeps, then reports (pid, start_ns, end_ns) from the
    system-wide monotonic clock — overlapping intervals from distinct
    pids prove the workers really ran concurrently."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        t0 = time.monotonic_ns()
        time.sleep(0.25)
        return np.asarray([os.getpid(), t0, time.monotonic_ns()], np.int64)


class FailingDataset(SquareDataset):
    def __getitem__(self, i):
        if i == 7:
            raise RuntimeError("boom at 7")
        return super().__getitem__(i)


class WorkerInfoDataset(SquareDataset):
    def __getitem__(self, i):
        info = get_worker_info()
        assert info is not None and info.num_workers == 2
        return np.asarray([i, info.id], np.int64)


class DictDS(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return {"x": np.full((3,), i, np.float32), "tag": str(i)}


class ObjDS(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return {"x": np.full((2,), i, np.float32),
                "meta": np.array([{"id": i}], object)}


class InitMarker:
    """Picklable worker_init_fn carrying its marker directory."""

    def __init__(self, directory):
        self.directory = str(directory)

    def __call__(self, worker_id):
        (pathlib.Path(self.directory) / f"init{worker_id}").write_text(
            str(worker_id))


def sum_collate(batch):
    return np.stack(batch).sum(0)


def obj_collate(batch):
    return {"x": np.stack([b["x"] for b in batch]),
            "meta": np.concatenate([b["meta"] for b in batch])}


def _collect(loader):
    return [np.asarray(b._data) if hasattr(b, "_data") else np.asarray(b)
            for b in loader]


class TestProcessWorkers:
    def test_matches_serial_order_and_content(self):
        ds = SquareDataset(33)
        serial = _collect(DataLoader(ds, batch_size=4, num_workers=0))
        proc = _collect(DataLoader(ds, batch_size=4, num_workers=3,
                                   worker_mode="process"))
        assert len(serial) == len(proc)
        for a, b in zip(serial, proc):
            np.testing.assert_array_equal(a, b)

    def test_worker_info_and_init_fn(self, tmp_path):
        out = _collect(DataLoader(WorkerInfoDataset(8), batch_size=2,
                                  num_workers=2, worker_mode="process",
                                  worker_init_fn=InitMarker(tmp_path)))
        ids = np.concatenate([o[:, 1] for o in out])
        assert set(ids.tolist()) == {0, 1}
        assert (tmp_path / "init0").exists()
        assert (tmp_path / "init1").exists()

    def test_error_propagates(self):
        dl = DataLoader(FailingDataset(16), batch_size=4, num_workers=2,
                        worker_mode="process")
        with pytest.raises(RuntimeError, match="boom at 7"):
            _collect(dl)

    def test_workers_run_concurrently(self):
        # interval-overlap, not wall-clock: worker startup under spawn is
        # load-sensitive (seconds on a busy 1-core CI host) and is not
        # the mechanism under test. Two workers round-robin the batches;
        # sleeping items from DIFFERENT pids must overlap in time.
        rows = np.concatenate(_collect(DataLoader(
            OverlapDataset(), batch_size=1, num_workers=2,
            worker_mode="process")))
        by_pid = {}
        for pid, t0, t1 in rows.tolist():
            by_pid.setdefault(pid, []).append((t0, t1))
        assert len(by_pid) == 2, by_pid.keys()
        (a_iv, b_iv) = by_pid.values()
        overlap = any(a0 < b1 and b0 < a1
                      for a0, a1 in a_iv for b0, b1 in b_iv)
        assert overlap, (a_iv, b_iv)

    def test_auto_spawn_when_jax_initialized(self):
        # importing paddle_tpu initializes the cpu backend in this
        # process, so the default (mp_context=None) must resolve to
        # spawn; an explicit context always wins
        assert DataLoader(SquareDataset(4))._resolve_mp_context() \
            == "spawn"
        assert DataLoader(SquareDataset(4),
                          mp_context="fork")._resolve_mp_context() \
            == "fork"

    def test_iterable_rejected(self):
        class It(IterableDataset):
            def __iter__(self):
                yield from range(4)
        with pytest.raises(NotImplementedError):
            DataLoader(It(), num_workers=2, worker_mode="process")

    def test_custom_collate_runs_in_worker(self):
        out = list(DataLoader(SquareDataset(8), batch_size=4,
                              num_workers=2, worker_mode="process",
                              collate_fn=sum_collate))
        ref = list(DataLoader(SquareDataset(8), batch_size=4,
                              num_workers=0, collate_fn=sum_collate))
        for a, b in zip(out, ref):
            np.testing.assert_array_equal(a, b)


class TestSharedMemoryTransport:
    def test_shm_matches_pickle(self):
        ds = SquareDataset(24)
        shm = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                  worker_mode="process",
                                  use_shared_memory=True))
        pkl = _collect(DataLoader(ds, batch_size=4, num_workers=2,
                                  worker_mode="process",
                                  use_shared_memory=False))
        assert len(shm) == len(pkl) == 6
        for a, b in zip(shm, pkl):
            np.testing.assert_array_equal(a, b)

    def test_shm_dict_batches(self):
        out = list(DataLoader(DictDS(), batch_size=4, num_workers=2,
                              worker_mode="process",
                              use_shared_memory=True))
        assert len(out) == 2
        np.testing.assert_allclose(np.asarray(out[0]["x"]._data)[:, 0],
                                   [0, 1, 2, 3])
        assert out[0]["tag"] == ["0", "1", "2", "3"]

    def test_no_leaked_segments(self):
        # scope to this loader's attributable names: global /dev/shm
        # diffs flake against unrelated concurrent processes
        import glob
        _collect(DataLoader(SquareDataset(16), batch_size=4,
                            num_workers=2, worker_mode="process",
                            use_shared_memory=True))
        assert glob.glob("/dev/shm/ppio*") == []

    def test_early_break_cleans_up(self):
        import glob
        dl = DataLoader(SquareDataset(32), batch_size=4, num_workers=2,
                        worker_mode="process", use_shared_memory=True)
        it = iter(dl)
        next(it)
        it.close()  # early break — pending batches must be unlinked
        time.sleep(0.3)
        leaked = glob.glob("/dev/shm/ppio*")
        assert leaked == [], leaked

    def test_object_dtype_stays_on_pickle_path(self):
        out = list(DataLoader(ObjDS(), batch_size=4, num_workers=2,
                              worker_mode="process",
                              use_shared_memory=True,
                              collate_fn=obj_collate))
        assert out[0]["meta"][0]["id"] == 0
        np.testing.assert_allclose(out[1]["x"][:, 0], [4, 5, 6, 7])

    def test_early_break_pickle_mode_does_not_hang(self):
        ds = SquareDataset(32)
        dl = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_mode="process", use_shared_memory=False)
        it = iter(dl)
        next(it)
        t0 = time.perf_counter()
        it.close()
        assert time.perf_counter() - t0 < 10
