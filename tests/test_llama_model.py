"""Llama model family knobs (fuse_attention_qkv / fuse_attention_ffn —
PaddleNLP parity; column layout is framework-native, see models/llama.py)."""


def test_llama_fused_qkv_ffn_trains():
    """fuse_attention_qkv/fuse_attention_ffn (PaddleNLP parity knobs)
    produce a trainable model with the same output shapes."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    c = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=32,
                    sequence_parallel=False, fuse_attention_qkv=True,
                    fuse_attention_ffn=True)
    m = LlamaForCausalLM(c)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 64, (2, 16)).astype(np.int32))
    loss, logits = m(ids, labels=ids)
    assert logits.shape == [2, 16, 64]
    loss.backward()
    g = m.llama.layers[0].self_attn.qkv_proj.weight.grad
    assert g is not None and float(paddle.abs(g).sum()) > 0
    g2 = m.llama.layers[0].mlp.gate_up_proj.weight.grad
    assert g2 is not None and float(paddle.abs(g2).sum()) > 0
