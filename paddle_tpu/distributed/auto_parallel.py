"""Semi-auto parallel API (ref: paddle.distributed.shard_tensor /
Shard/Replicate/Partial placements / reshard — SURVEY §2.3 P11).

This is the layer that maps 1:1 onto GSPMD: placements become
PartitionSpecs, the Completer/Resharder become XLA sharding propagation, and
`reshard` is a device_put to a new NamedSharding. The op-by-op dist branch of
the reference's generated API (dist_api_gen.py) is unnecessary: once inputs
carry shardings, every traced op propagates them.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .mesh import ProcessMesh, get_mesh, sanitize_spec

__all__ = ["Shard", "Replicate", "Partial", "shard_tensor", "reshard",
           "dtensor_from_fn", "placements_to_spec", "shard_layer",
           "mark_sharding", "get_placements"]


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    """Pending-reduction placement. GSPMD materializes partials internally;
    an explicit Partial placement on user tensors reduces on creation."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


def _mesh_of(mesh) -> Mesh:
    if mesh is None:
        m = get_mesh()
        if m is None:
            raise ValueError("no mesh: pass one or enter a ProcessMesh/"
                             "mesh_context")
        return m
    return mesh.jax_mesh if isinstance(mesh, ProcessMesh) else mesh


def placements_to_spec(mesh: Mesh, placements: Sequence[Placement],
                       ndim: int) -> PartitionSpec:
    """[per-mesh-axis placements] → PartitionSpec over tensor dims."""
    axes = list(mesh.axis_names)
    dims: List = [None] * ndim
    for axis_name, pl in zip(axes, placements):
        if isinstance(pl, Shard):
            if dims[pl.dim] is None:
                dims[pl.dim] = axis_name
            elif isinstance(dims[pl.dim], tuple):
                dims[pl.dim] = dims[pl.dim] + (axis_name,)
            else:
                dims[pl.dim] = (dims[pl.dim], axis_name)
    while dims and dims[-1] is None:  # canonical form: no trailing Nones
        dims.pop()
    return PartitionSpec(*dims)


def get_placements(t: Tensor):
    """Best-effort inverse: tensor's sharding → placement list (parity with
    DistTensor.placements)."""
    arr = t._data
    if not isinstance(arr, jax.Array) or arr.sharding is None:
        return None
    sh = arr.sharding
    if not isinstance(sh, NamedSharding):
        return None
    mesh = sh.mesh
    out: List[Placement] = [Replicate() for _ in mesh.axis_names]
    spec = sh.spec
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for n in names:
            out[list(mesh.axis_names).index(n)] = Shard(dim)
    return out


def shard_tensor(t, mesh=None, placements: Optional[Sequence[Placement]] = None,
                 spec: Optional[PartitionSpec] = None) -> Tensor:
    """ref: paddle.distributed.shard_tensor(t, mesh, [Shard(0), Replicate()]).

    Places the tensor's buffer onto the mesh with the requested sharding;
    under tracing, applies a sharding constraint instead.
    """
    m = _mesh_of(mesh)
    x = t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
    if spec is None:
        placements = list(placements or [])
        # Partial on creation: divide then psum? Eager Partial is rare; treat
        # as replicate-after-reduce is not expressible here — reject clearly.
        if any(isinstance(p, Partial) for p in placements):
            raise NotImplementedError(
                "Partial placement on shard_tensor inputs is produced by ops, "
                "not by placement requests (GSPMD handles partials internally)")
        spec = placements_to_spec(m, placements, x.ndim)
    sharding = NamedSharding(m, spec)
    if isinstance(x._data, jax.core.Tracer):
        out = jax.lax.with_sharding_constraint(x._data, sharding)
        r = Tensor(out, stop_gradient=x.stop_gradient)
        return r
    new = Tensor(jax.device_put(x._data, sharding),
                 stop_gradient=x.stop_gradient)
    new.name = x.name
    return new


def reshard(t: Tensor, mesh=None, placements=None, spec=None) -> Tensor:
    """ref: paddle.distributed.reshard — same mechanism as shard_tensor (XLA
    computes the minimal collective to move between shardings)."""
    return shard_tensor(t, mesh, placements, spec)


def mark_sharding(x: Tensor, *spec_dims, mesh=None) -> Tensor:
    """Sharding constraint annotation inside traced code (the Megatron-SP /
    activation-sharding lever — ref: sequence_parallel_utils' explicit
    scatter/gather becomes this single annotation under GSPMD)."""
    m = _mesh_of(mesh)
    sharding = NamedSharding(m, PartitionSpec(*spec_dims))
    from ..core.dispatch import apply
    return apply("sharding_constraint",
                 lambda a: jax.lax.with_sharding_constraint(a, sharding), [x])


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs) -> Tensor:
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def shard_layer(layer, mesh=None, shard_fn=None, input_fn=None,
                output_fn=None):
    """ref: paddle.distributed.shard_layer — apply a per-sublayer shard_fn
    (defaults to replicating every parameter onto the mesh)."""
    m = _mesh_of(mesh)

    def default_shard(name, sublayer):
        for pname, p in sublayer.__dict__["_parameters"].items():
            if p is None:
                continue
            # layer-declared specs (TP layers pin e.g. "mp") must be
            # sanitized: the caller's mesh is configurable and may lack
            # the axis the layer assumed (PS306)
            spec = sanitize_spec(m, getattr(p, "_sharding_spec", None))
            p._data = jax.device_put(p._data, NamedSharding(m, spec))

    fn = shard_fn or default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub)
    return layer
