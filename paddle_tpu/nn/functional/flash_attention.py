"""paddle.nn.functional.flash_attention namespace parity
(ref: python/paddle/nn/functional/flash_attention.py).

All entry points route to paddle_tpu.ops.flash_attention: the Pallas TPU
flash kernel (with segment-ID varlen) where eligible, the f32-softmax XLA
composite otherwise.
"""

from __future__ import annotations

from ...ops.flash_attention import (flash_attention, flash_attn_unpadded,
                                    flashmask_attention, sdpa,
                                    sdpa_segmented)
from . import scaled_dot_product_attention

def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, name=None):
    """[B, S, 3, H, D] packed qkv → flash_attention on the unpacked views."""
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax)


__all__ = ["flash_attention", "flash_attn_unpadded", "flash_attn_qkvpacked",
           "flashmask_attention", "scaled_dot_product_attention", "sdpa",
           "sdpa_segmented"]
