"""Quantization depth (VERDICT r3 item 7; ref: python/paddle/quantization/
observers + quanters, python/paddle/nn/quant): per-channel weight quant,
histogram/percentile + KL calibration, a PTQ-int8 accuracy gate on the
BERT classification model, and the weight-only-int8 decode path."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.quantization import (AbsmaxObserver, PerChannelAbsmaxObserver,
                                     HistObserver, KLObserver,
                                     FakeQuanterWithAbsMax,
                                     FakeQuanterChannelWiseAbsMax,
                                     QuantConfig, QAT, PTQ)


class TestObservers:
    def test_per_channel_absmax(self):
        obs = PerChannelAbsmaxObserver(axis=-1)
        x = paddle.to_tensor(np.array([[1.0, -8.0], [2.0, 4.0]], np.float32))
        obs.observe(x)
        s = np.asarray(obs.scale())
        np.testing.assert_allclose(s, [2.0 / 127, 8.0 / 127], rtol=1e-6)
        # running max across batches
        obs.observe(paddle.to_tensor(np.array([[5.0, 1.0]], np.float32)))
        np.testing.assert_allclose(np.asarray(obs.scale()),
                                   [5.0 / 127, 8.0 / 127], rtol=1e-6)

    def test_hist_observer_percentile_robust_to_outliers(self):
        rng = np.random.RandomState(0)
        bulk = rng.uniform(-1, 1, 100000).astype(np.float32)
        with_outlier = np.concatenate([bulk, [1000.0]]).astype(np.float32)
        plain = AbsmaxObserver()
        hist = HistObserver(percent=0.999)
        plain.observe(paddle.to_tensor(with_outlier))
        hist.observe(paddle.to_tensor(with_outlier))
        # absmax wastes the int8 range on the outlier; the histogram
        # percentile keeps the scale near the bulk's range
        assert plain.scale() > 5.0
        assert hist.scale() < 0.05, hist.scale()

    def test_hist_observer_range_growth_rebins(self):
        obs = HistObserver(bins=64)
        obs.observe(paddle.to_tensor(np.linspace(0, 1, 1000,
                                                 dtype=np.float32)))
        total1 = obs.hist.sum()
        obs.observe(paddle.to_tensor(np.linspace(0, 10, 1000,
                                                 dtype=np.float32)))
        assert obs.hist_max >= 10.0
        assert obs.hist.sum() == total1 + 1000   # mass preserved

    def test_kl_observer_prefers_clip_below_outlier(self):
        rng = np.random.RandomState(1)
        data = np.concatenate([rng.normal(0, 1, 50000),
                               [500.0]]).astype(np.float32)
        kl = KLObserver(bins=512)
        kl.observe(paddle.to_tensor(data))
        # KL calibration clips far below the outlier
        assert kl._threshold() < 250.0
        assert kl.scale() < 2.0


class TestQATPerChannel:
    def test_channelwise_fake_quant_ste(self):
        q = FakeQuanterChannelWiseAbsMax(axis=-1)
        x = paddle.to_tensor(np.array([[0.5, 50.0], [-1.0, -100.0]],
                                      np.float32))
        x.stop_gradient = False
        y = q(x)
        # column 0 quantized with scale 1/127, column 1 with 100/127
        err = np.abs(y.numpy() - x.numpy())
        assert err[:, 0].max() < 1.0 / 127
        assert err[:, 1].max() < 100.0 / 127
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((2, 2)),
                                   rtol=1e-6)   # straight-through

    def test_qat_flow_with_channelwise_weights(self):
        lin_model = paddle.nn.Sequential(paddle.nn.Linear(8, 8),
                                         paddle.nn.ReLU(),
                                         paddle.nn.Linear(8, 2))
        cfg = QuantConfig(activation=FakeQuanterWithAbsMax,
                          weight=FakeQuanterChannelWiseAbsMax)
        qat = QAT(cfg)
        qm = qat.quantize(lin_model)
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 8).astype(np.float32))
        out = qm(x)
        assert list(out.shape) == [4, 2]


class TestPTQAccuracyGate:
    def test_bert_gate_survives_ptq_int8(self):
        """PTQ weight-only-int8 must not break the classification gate:
        quantized accuracy within 2 points of the fp32 model's."""
        from paddle_tpu.models.bert import (BertForSequenceClassification,
                                            bert_tiny_config)
        from tests.test_quality_gates import _sentiment_corpus
        paddle.seed(0)
        cfg = bert_tiny_config(vocab_size=64, hidden_size=64,
                               num_hidden_layers=2, num_attention_heads=4,
                               intermediate_size=128,
                               max_position_embeddings=32, num_labels=2)
        model = BertForSequenceClassification(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=list(model.parameters()))
        Xtr, ytr = _sentiment_corpus(512, 0)
        Xdev, ydev = _sentiment_corpus(128, 1)
        B = 32
        for epoch in range(10):
            perm = np.random.RandomState(epoch).permutation(len(Xtr))
            for i in range(0, len(Xtr), B):
                idx = perm[i:i + B]
                loss, _ = model(paddle.to_tensor(Xtr[idx]),
                                labels=paddle.to_tensor(ytr[idx]))
                loss.backward()
                opt.step()
                opt.clear_grad()
        model.eval()
        fp_acc = (np.asarray(model(paddle.to_tensor(Xdev)).numpy())
                  .argmax(-1) == ydev).mean()

        ptq = PTQ(QuantConfig(activation=HistObserver))
        ptq.quantize(model)
        model(paddle.to_tensor(Xdev[:64]))       # calibration pass
        ptq.convert(model)
        q_acc = (np.asarray(model(paddle.to_tensor(Xdev)).numpy())
                 .argmax(-1) == ydev).mean()
        assert len(ptq.observers) > 0
        assert q_acc >= fp_acc - 0.02, (q_acc, fp_acc)
        assert q_acc >= 0.90, q_acc


class TestWeightOnlyInt8Decode:
    def test_int8_decode_close_to_bf16(self):
        from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny_config
        from paddle_tpu.generation import (_llama_decode_params,
                                           _cached_step_body, _llama_weights,
                                           _init_caches)
        paddle.seed(3)
        cfg = llama_tiny_config(max_position_embeddings=32)
        model = LlamaForCausalLM(cfg)
        model.eval()
        ids = jnp.asarray(np.random.RandomState(0).randint(
            1, cfg.vocab_size, (2, 8)), jnp.int32)

        outs = {}
        for tag, wo in (("fp", False), ("int8", True)):
            p = _llama_decode_params(model, weight_only_int8=wo)
            body = _cached_step_body(p, 16)
            w = _llama_weights(p)
            caches = _init_caches(p, 2, 16)
            logits, _ = body(w, ids, caches, 0)
            outs[tag] = np.asarray(logits, np.float32)
        # int8 weight quant error is small per channel; logits track the
        # fp path closely and greedy tokens agree on a separable model
        rel = (np.abs(outs["int8"] - outs["fp"]).max()
               / (np.abs(outs["fp"]).max() + 1e-9))
        assert rel < 0.08, rel
        assert (outs["int8"].argmax(-1) == outs["fp"].argmax(-1)).mean() \
            >= 0.9
