// ptpu_fusion — C++ StableHLO pattern-fusion pass (CINN parity).
//
// Reference capability: paddle/cinn/hlir/dialect/operator/transforms/ —
// ApplyCinnPass pattern-matches fusible subgraphs on the static program
// and swaps them for compiled JIT-kernel ops (SURVEY §2.1 "CINN fusion
// compiler", §7.1 L8). TPU-native reading: the static program IS the
// StableHLO module jax lowers; this pass pattern-matches attention /
// rmsnorm / swiglu regions in the MODULE TEXT, and rewrites the matched
// region into a func.call to a (Pallas) kernel function the Python
// driver lowers and hands in. The rewritten module is re-verified by
// MLIR (ir.Module.parse on the Python side) and compiled by PJRT.
//
// Two C entry points, driven by paddle_tpu/jit/fusion_cc.py:
//   ptpu_fusion_analyze(text)        -> JSON match report
//   ptpu_fusion_rewrite(text, plan)  -> rewritten module text
// The pass is dependency-free (no MLIR libs in this environment): it
// parses the one-op-per-line textual form the jax printer emits and is
// conservative — anything it does not recognize is left untouched.

#include <cstdlib>
#include <cstring>
#include <map>
#include <set>
#include <sstream>
#include <string>
#include <vector>

namespace {

struct Op {
  std::string result;                // "%13" ("" for return/non-value)
  std::string name;                  // "stablehlo.dot_general"
  std::vector<std::string> operands; // every %id on the rhs
  std::string line;                  // original text
  int idx = -1;                      // index into lines[]
};

struct Func {
  std::string header;  // the func.func line
  int begin = -1;      // line index of header
  int end = -1;        // line index of closing brace
  std::vector<Op> ops;
  std::map<std::string, int> def;       // %id -> op index in ops
  std::map<std::string, int> nuses;     // %id -> use count (incl. return)
  std::map<std::string, std::string> argtype;  // %argN -> tensor<...>
};

static std::string trim(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\r\n");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t\r\n");
  return s.substr(a, b - a + 1);
}

// every %identifier in `s`
static std::vector<std::string> percent_ids(const std::string& s) {
  std::vector<std::string> out;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] != '%') continue;
    size_t j = i + 1;
    while (j < s.size() &&
           (isalnum((unsigned char)s[j]) || s[j] == '_')) j++;
    if (j > i + 1) out.push_back(s.substr(i, j - i));
    i = j - 1;
  }
  return out;
}

static std::string op_name_of(const std::string& rhs) {
  size_t i = 0;
  while (i < rhs.size() && rhs[i] != ' ' && rhs[i] != '(') i++;
  return rhs.substr(0, i);
}

// trailing result type: text after the last "-> " or after " : " for
// same-type ops ("%9 = stablehlo.exponential %8 : tensor<...>")
static std::string result_type_of(const std::string& line) {
  size_t arrow = line.rfind("-> ");
  if (arrow != std::string::npos) {
    std::string t = trim(line.substr(arrow + 3));
    if (!t.empty() && t[0] == '(') {  // multi-result "(tensor<..>, ..)"
      return t;
    }
    return t;
  }
  size_t colon = line.rfind(" : ");
  if (colon != std::string::npos) return trim(line.substr(colon + 3));
  return "";
}

// parse "func.func public @main(%arg0: tensor<...>, ...)" arg types
static void parse_args(const std::string& header, Func* f) {
  size_t lp = header.find('(');
  if (lp == std::string::npos) return;
  // walk to matching ')' at depth 0 (types contain no parens)
  int depth = 0;
  size_t rp = lp;
  for (size_t i = lp; i < header.size(); ++i) {
    if (header[i] == '(') depth++;
    if (header[i] == ')') { depth--; if (depth == 0) { rp = i; break; } }
  }
  std::string args = header.substr(lp + 1, rp - lp - 1);
  std::stringstream ss(args);
  std::string piece;
  // split on commas at angle-bracket depth 0
  std::vector<std::string> pieces;
  int adepth = 0; std::string cur;
  for (char c : args) {
    if (c == '<' || c == '{') adepth++;
    if (c == '>' || c == '}') adepth--;
    if (c == ',' && adepth == 0) { pieces.push_back(cur); cur.clear(); }
    else cur += c;
  }
  if (!trim(cur).empty()) pieces.push_back(cur);
  for (auto& p : pieces) {
    std::string t = trim(p);
    size_t colon = t.find(':');
    if (colon == std::string::npos) continue;
    std::string id = trim(t.substr(0, colon));
    std::string ty = trim(t.substr(colon + 1));
    size_t brace = ty.find(" {");
    if (brace != std::string::npos) ty = ty.substr(0, brace);
    f->argtype[id] = ty;
  }
}

struct Module {
  std::vector<std::string> lines;
  std::vector<Func> funcs;
  int module_close = -1;  // index of final '}'
};

static Module parse_module(const std::string& text) {
  Module m;
  std::stringstream ss(text);
  std::string line;
  while (std::getline(ss, line)) m.lines.push_back(line);
  for (int i = (int)m.lines.size() - 1; i >= 0; --i) {
    if (trim(m.lines[i]) == "}") { m.module_close = i; break; }
  }
  for (int i = 0; i < (int)m.lines.size(); ++i) {
    std::string t = trim(m.lines[i]);
    if (t.rfind("func.func", 0) != 0) continue;
    Func f;
    f.header = t;
    f.begin = i;
    parse_args(t, &f);
    // body until the matching close — jax prints one brace depth
    for (int j = i + 1; j < (int)m.lines.size(); ++j) {
      std::string b = trim(m.lines[j]);
      if (b == "}") { f.end = j; break; }
      Op op;
      op.idx = j;
      op.line = m.lines[j];
      if (!b.empty() && b[0] == '%') {
        size_t eq = b.find(" = ");
        if (eq != std::string::npos) {
          op.result = trim(b.substr(0, eq));
          std::string rhs = b.substr(eq + 3);
          op.name = op_name_of(rhs);
          op.operands = percent_ids(rhs);
        }
      } else {
        op.name = op_name_of(b);
        op.operands = percent_ids(b);
      }
      if (!op.result.empty()) f.def[op.result] = (int)f.ops.size();
      for (auto& o : op.operands) f.nuses[o]++;
      f.ops.push_back(op);
    }
    m.funcs.push_back(f);
    i = f.end;
  }
  return m;
}

// ---------------------------------------------------------------------------
// matching
// ---------------------------------------------------------------------------
struct Match {
  std::string pattern;
  std::vector<std::string> operands;       // SSA ids, call order
  std::vector<std::string> operand_types;  // tensor<...>
  std::string result;                      // SSA id of final op
  std::string result_type;
  int final_line = -1;
  std::vector<int> chain_lines;            // interior lines to delete
  double scale = 1.0;
  double eps = 0.0;
  std::string extra_json;                  // raw JSON tail, e.g. "prog"
};

struct Ctx {
  const Func& f;
  explicit Ctx(const Func& f_) : f(f_) {}
  const Op* def(const std::string& id) const {
    auto it = f.def.find(id);
    return it == f.def.end() ? nullptr : &f.ops[it->second];
  }
  int uses(const std::string& id) const {
    auto it = f.nuses.find(id);
    return it == f.nuses.end() ? 0 : it->second;
  }
  std::string type_of(const std::string& id) const {
    auto at = f.argtype.find(id);
    if (at != f.argtype.end()) return at->second;
    const Op* d = def(id);
    return d ? result_type_of(d->line) : "";
  }
};

// producer of `id` if its op name matches; single-use enforced for
// interior links so deleting the chain is safe
static const Op* follow(const Ctx& c, const std::string& id,
                        const char* opname, bool need_single_use = true) {
  const Op* d = c.def(id);
  if (!d || d->name != std::string(opname)) return nullptr;
  if (need_single_use && c.uses(id) != 1) return nullptr;
  return d;
}

// resolve through stablehlo.convert (bf16 modules), collecting lines
static std::string through_converts(const Ctx& c, std::string id,
                                    std::vector<int>* chain) {
  for (;;) {
    const Op* d = c.def(id);
    if (!d || d->name != "stablehlo.convert" || c.uses(id) != 1) return id;
    if (chain) chain->push_back(d->idx);
    id = d->operands[0];
  }
}

static bool const_value(const Ctx& c, const std::string& id, double* out) {
  const Op* d = c.def(id);
  if (!d) return false;
  std::string src = d->line;
  if (d->name == "stablehlo.broadcast_in_dim") {
    const Op* k = c.def(d->operands[0]);
    if (!k) return false;
    src = k->line;
    if (k->name != "stablehlo.constant") return false;
  } else if (d->name != "stablehlo.constant") {
    return false;
  }
  size_t l = src.find("dense<");
  if (l == std::string::npos) return false;
  size_t r = src.find('>', l);
  std::string v = src.substr(l + 6, r - l - 6);
  // -inf bit patterns across dtypes: f32 0xFF800000, bf16 0xFF80,
  // f16 0xFC00
  if (v == "0xFF800000" || v == "0xFF80" || v == "0xFC00") {
    *out = -1.0 / 0.0;
    return true;
  }
  char* end = nullptr;
  *out = strtod(v.c_str(), &end);
  return end != v.c_str();
}

// contracting dims of a dot_general line: "contracting_dims = [3] x [2]"
static bool contracting_dims(const std::string& line, int* lhs, int* rhs) {
  size_t p = line.find("contracting_dims = [");
  if (p == std::string::npos) return false;
  *lhs = atoi(line.c_str() + p + 20);
  size_t x = line.find("] x [", p);
  if (x == std::string::npos) return false;
  *rhs = atoi(line.c_str() + x + 5);
  return true;
}

static void match_sdpa(const Ctx& c, std::vector<Match>* out) {
  for (const Op& fin : c.f.ops) {
    if (fin.name != "stablehlo.dot_general") continue;
    int cl, cr;
    if (!contracting_dims(fin.line, &cl, &cr) || cl != 3 || cr != 2)
      continue;
    if (fin.operands.size() < 2) continue;
    std::vector<int> chain;
    std::string probs = through_converts(c, fin.operands[0], &chain);
    std::string v_id = fin.operands[1];
    const Op* div = follow(c, probs, "stablehlo.divide");
    if (!div) continue;
    chain.push_back(div->idx);
    std::string exp_id = div->operands[0];
    // denom: broadcast([convert] broadcast([convert] reduce_add(exp)))
    // — bf16 modules interleave f32-accumulation converts
    std::string den = through_converts(c, div->operands[1], &chain);
    const Op* b1 = follow(c, den, "stablehlo.broadcast_in_dim");
    if (!b1) continue;
    chain.push_back(b1->idx);
    den = through_converts(c, b1->operands[0], &chain);
    const Op* b2 = follow(c, den, "stablehlo.broadcast_in_dim");
    if (!b2) continue;
    chain.push_back(b2->idx);
    den = through_converts(c, b2->operands[0], &chain);
    const Op* red = follow(c, den, "stablehlo.reduce");
    if (!red || red->line.find("applies stablehlo.add") == std::string::npos)
      continue;
    chain.push_back(red->idx);
    if (red->operands.empty()) continue;
    std::string red_src = red->operands[0];
    {
      // the reduce may read exp through an f32 convert; that convert is
      // USED only by the reduce, so it joins the chain
      const Op* cd = c.def(red_src);
      if (cd && cd->name == "stablehlo.convert" && c.uses(red_src) == 1 &&
          cd->operands[0] == exp_id) {
        chain.push_back(cd->idx);
        red_src = cd->operands[0];
      }
    }
    if (red_src != exp_id) continue;
    // exp_id used by divide AND reduce => 2 uses
    const Op* ex = c.def(exp_id);
    if (!ex || ex->name != "stablehlo.exponential" || c.uses(exp_id) != 2)
      continue;
    chain.push_back(ex->idx);
    const Op* sub = follow(c, ex->operands[0], "stablehlo.subtract");
    if (!sub) continue;
    chain.push_back(sub->idx);
    std::string logits = sub->operands[0];
    // max side: bcast(bcast(maximum(bcast(-inf), reduce_max(logits))))
    const Op* mb1 = follow(c, sub->operands[1], "stablehlo.broadcast_in_dim");
    if (!mb1) continue;
    chain.push_back(mb1->idx);
    const Op* mb2 = follow(c, mb1->operands[0], "stablehlo.broadcast_in_dim");
    if (!mb2) continue;
    chain.push_back(mb2->idx);
    std::string mx = mb2->operands[0];
    const Op* mxop = c.def(mx);
    if (!mxop) continue;
    if (mxop->name == "stablehlo.maximum") {
      if (c.uses(mx) != 1) continue;
      chain.push_back(mxop->idx);
      // one side is broadcast(-inf) constant, other the reduce
      std::string r;
      double cv;
      if (const_value(c, mxop->operands[0], &cv) && cv < -1e30)
        r = mxop->operands[1];
      else if (const_value(c, mxop->operands[1], &cv) && cv < -1e30)
        r = mxop->operands[0];
      else continue;
      mx = r;
      mxop = c.def(mx);
      if (!mxop) continue;
    }
    if (mxop->name != "stablehlo.reduce" ||
        mxop->line.find("applies stablehlo.maximum") == std::string::npos ||
        c.uses(mx) != 1)
      continue;
    chain.push_back(mxop->idx);
    if (mxop->operands.empty() || mxop->operands[0] != logits) continue;
    // logits used by subtract AND reduce_max => 2 uses
    const Op* lg = c.def(logits);
    if (!lg || c.uses(logits) != 2) continue;
    double scale = 1.0;
    if (lg->name == "stablehlo.multiply") {
      double cv;
      std::string other;
      if (const_value(c, lg->operands[1], &cv)) other = lg->operands[0];
      else if (const_value(c, lg->operands[0], &cv)) other = lg->operands[1];
      else continue;
      scale = cv;
      chain.push_back(lg->idx);
      logits = other;
      lg = c.def(logits);
      if (!lg || c.uses(logits) != 1) continue;
    }
    if (lg->name != "stablehlo.dot_general") continue;
    int dl, dr;
    if (!contracting_dims(lg->line, &dl, &dr) || dl != 3 || dr != 3)
      continue;
    chain.push_back(lg->idx);
    Match m;
    m.pattern = "sdpa";
    m.operands = {lg->operands[0], lg->operands[1], v_id};
    for (auto& o : m.operands) m.operand_types.push_back(c.type_of(o));
    m.result = fin.result;
    m.result_type = result_type_of(fin.line);
    m.final_line = fin.idx;
    m.chain_lines = chain;
    m.scale = scale;
    out->push_back(m);
  }
}

static void match_rmsnorm(const Ctx& c, std::vector<Match>* out) {
  for (const Op& rs : c.f.ops) {
    if (rs.name != "stablehlo.rsqrt") continue;
    std::vector<int> chain;
    chain.push_back(rs.idx);
    const Op* add = follow(c, rs.operands[0], "stablehlo.add");
    if (!add) continue;
    chain.push_back(add->idx);
    double eps;
    std::string varid;
    if (const_value(c, add->operands[1], &eps)) varid = add->operands[0];
    else if (const_value(c, add->operands[0], &eps)) varid = add->operands[1];
    else continue;
    const Op* div = follow(c, varid, "stablehlo.divide");
    if (!div) continue;
    chain.push_back(div->idx);
    double n;
    if (!const_value(c, div->operands[1], &n)) continue;
    const Op* bc = follow(c, div->operands[0], "stablehlo.broadcast_in_dim");
    if (!bc) continue;
    chain.push_back(bc->idx);
    const Op* red = follow(c, bc->operands[0], "stablehlo.reduce");
    if (!red || red->line.find("applies stablehlo.add") == std::string::npos)
      continue;
    chain.push_back(red->idx);
    const Op* sq = c.def(red->operands[0]);
    // chlo.square or multiply(x, x)
    if (!sq || c.uses(red->operands[0]) != 1) continue;
    std::string x32;
    if (sq->name == "chlo.square") x32 = sq->operands[0];
    else if (sq->name == "stablehlo.multiply" &&
             sq->operands.size() >= 2 &&
             sq->operands[0] == sq->operands[1]) x32 = sq->operands[0];
    else continue;
    chain.push_back(sq->idx);
    std::vector<int> cchain;
    std::string x_root = through_converts(c, x32, &cchain);
    // x32 may be used by square AND the normalize multiply
    // forward: rsqrt -> broadcast -> multiply(x, .) -> multiply(., w)
    if (c.uses(rs.result) != 1) continue;
    // find the broadcast consumer of rsqrt
    const Op* nb = nullptr;
    for (const Op& o : c.f.ops)
      for (auto& oid : o.operands)
        if (oid == rs.result) { nb = &o; break; }
    if (!nb || nb->name != "stablehlo.broadcast_in_dim" ||
        c.uses(nb->result) != 1)
      continue;
    chain.push_back(nb->idx);
    const Op* mul1 = nullptr;
    for (const Op& o : c.f.ops)
      for (auto& oid : o.operands)
        if (oid == nb->result) { mul1 = &o; break; }
    if (!mul1 || mul1->name != "stablehlo.multiply") continue;
    std::string xs = mul1->operands[0] == nb->result ? mul1->operands[1]
                                                     : mul1->operands[0];
    if (through_converts(c, xs, nullptr) != x_root && xs != x32) continue;
    if (c.uses(mul1->result) != 1) continue;
    chain.push_back(mul1->idx);
    // optional convert then multiply by broadcast(w)
    const Op* nxt = nullptr;
    std::string cur = mul1->result;
    for (const Op& o : c.f.ops)
      for (auto& oid : o.operands)
        if (oid == cur) { nxt = &o; break; }
    if (nxt && nxt->name == "stablehlo.convert" && c.uses(cur) == 1) {
      chain.push_back(nxt->idx);
      cur = nxt->result;
      const Op* nn = nullptr;
      for (const Op& o : c.f.ops)
        for (auto& oid : o.operands)
          if (oid == cur) { nn = &o; break; }
      nxt = nn;
    }
    if (!nxt || nxt->name != "stablehlo.multiply" || c.uses(cur) != 1)
      continue;
    std::string wside = nxt->operands[0] == cur ? nxt->operands[1]
                                                : nxt->operands[0];
    std::string w_id = wside;
    // peel the (possibly stacked) broadcasts jax emits for rank-lift
    for (;;) {
      const Op* wb = c.def(w_id);
      if (!wb || wb->name != "stablehlo.broadcast_in_dim" ||
          c.uses(w_id) != 1)
        break;
      chain.push_back(wb->idx);
      w_id = wb->operands[0];
    }
    // weight must be rank-1
    std::string wt = c.type_of(w_id);
    int commas = 0;
    size_t lt = wt.find('<');
    for (size_t i = lt; i < wt.size() && wt[i] != '>'; ++i)
      if (wt[i] == 'x') commas++;
    if (commas != 1) continue;  // tensor<Nxf32> has exactly one 'x'
    // the mean divisor must equal the hidden (last) dim of x — anything
    // else is NOT an RMS mean and must not be fused (semantics differ)
    {
      std::string xt = c.type_of(x_root);
      size_t gt = xt.rfind('x');
      size_t open = xt.find('<');
      if (gt == std::string::npos || open == std::string::npos) continue;
      size_t prev = xt.rfind('x', gt - 1);
      size_t dim_start = (prev == std::string::npos || prev < open)
                             ? open + 1 : prev + 1;
      int last_dim = atoi(xt.substr(dim_start, gt - dim_start).c_str());
      if (last_dim <= 0 || (double)last_dim != n) continue;
    }
    for (int ci : cchain) chain.push_back(ci);
    Match m;
    m.pattern = "rmsnorm";
    m.operands = {x_root, w_id};
    m.operand_types = {c.type_of(x_root), wt};
    m.result = nxt->result;
    m.result_type = result_type_of(nxt->line);
    m.final_line = nxt->idx;
    m.chain_lines = chain;
    m.eps = eps;
    out->push_back(m);
  }
}

static void match_swiglu(const Ctx& c, std::vector<Match>* out) {
  for (const Op& mul : c.f.ops) {
    if (mul.name != "stablehlo.multiply" || mul.operands.size() < 2)
      continue;
    for (int side = 0; side < 2; ++side) {
      const Op* call = c.def(mul.operands[side]);
      if (!call || call->name != "call") continue;
      if (call->line.find("@silu") == std::string::npos) continue;
      if (c.uses(mul.operands[side]) != 1) continue;
      std::string up = mul.operands[1 - side];
      Match m;
      m.pattern = "swiglu";
      m.operands = {call->operands[0], up};
      m.operand_types = {c.type_of(call->operands[0]), c.type_of(up)};
      m.result = mul.result;
      m.result_type = result_type_of(mul.line);
      m.final_line = mul.idx;
      m.chain_lines = {call->idx};
      out->push_back(m);
      break;
    }
  }
}

// interior results must not be used outside the chain+final
// ---------------------------------------------------------------------------
// generic producer-consumer fusion (CINN trivial-op parity; VERDICT r3
// item 4). Reference: paddle/cinn/operator_fusion/ merges ARBITRARY
// same-shape elementwise producer-consumer regions, not a pattern table.
// Here: grow maximal single-use-edge regions of same-type elementwise ops
// (the constraints that make deleting the region and calling one generated
// Pallas loop safe), require exactly one escaping value, and report the
// region's program so the Python driver can synthesize the kernel.
// ---------------------------------------------------------------------------
static std::string json_escape(const std::string& s);

static const std::set<std::string>& ew_ops() {
  static const std::set<std::string> s = {
      "stablehlo.add",         "stablehlo.subtract",
      "stablehlo.multiply",    "stablehlo.divide",
      "stablehlo.maximum",     "stablehlo.minimum",
      "stablehlo.exponential", "stablehlo.log",
      "stablehlo.tanh",        "stablehlo.logistic",
      "stablehlo.rsqrt",       "stablehlo.sqrt",
      "stablehlo.negate",      "stablehlo.abs",
      "stablehlo.power"};
  return s;
}

static void match_generic(const Ctx& c, const std::set<int>& taken,
                          std::vector<Match>* out) {
  // consumer index: ssa id -> indices of ops (incl. return) that read it
  std::map<std::string, std::vector<int>> cons;
  for (int i = 0; i < (int)c.f.ops.size(); ++i)
    for (auto& o : c.f.ops[i].operands) cons[o].push_back(i);

  std::set<int> visited;
  for (int i0 = 0; i0 < (int)c.f.ops.size(); ++i0) {
    const Op& seed = c.f.ops[i0];
    if (visited.count(i0) || taken.count(seed.idx)) continue;
    if (!ew_ops().count(seed.name)) continue;
    std::string T = result_type_of(seed.line);
    if (T.empty()) continue;

    std::set<int> region{i0};
    std::vector<int> work{i0};
    while (!work.empty()) {
      int oi = work.back();
      work.pop_back();
      const Op& op = c.f.ops[oi];
      for (auto& id : op.operands) {       // grow towards producers
        auto it = c.f.def.find(id);
        if (it == c.f.def.end()) continue;
        int pi = it->second;
        const Op& p = c.f.ops[pi];
        if (region.count(pi) || taken.count(p.idx)) continue;
        if (!ew_ops().count(p.name)) continue;
        if (result_type_of(p.line) != T) continue;
        if (c.uses(id) != 1) continue;     // interior edges single-use
        region.insert(pi);
        work.push_back(pi);
      }
      if (op.result.empty() || c.uses(op.result) != 1) continue;
      for (int qi : cons[op.result]) {     // grow towards consumers
        const Op& q = c.f.ops[qi];
        if (region.count(qi) || taken.count(q.idx)) continue;
        if (!ew_ops().count(q.name)) continue;
        if (result_type_of(q.line) != T) continue;
        region.insert(qi);
        work.push_back(qi);
      }
    }
    for (int oi : region) visited.insert(oi);
    if ((int)region.size() < 3) continue;  // not worth a kernel call

    // exactly one escaping value
    int fin = -1, n_escape = 0;
    for (int oi : region) {
      const Op& op = c.f.ops[oi];
      if (op.result.empty()) continue;
      int outside = 0;
      for (int qi : cons[op.result])
        if (!region.count(qi)) outside++;
      if (outside > 0) {
        fin = oi;
        n_escape++;
      }
    }
    if (n_escape != 1) continue;

    Match mt;
    mt.pattern = "generic";
    std::vector<std::string> ext;
    std::map<std::string, int> extidx;
    std::ostringstream prog;
    prog << "[";
    bool first = true;
    for (int oi = 0; oi < (int)c.f.ops.size(); ++oi) {  // SSA text order
      if (!region.count(oi)) continue;
      const Op& op = c.f.ops[oi];
      if (oi != fin) mt.chain_lines.push_back(op.idx);
      if (!first) prog << ", ";
      first = false;
      prog << "{\"op\": \"" << json_escape(op.name.substr(10))
           << "\", \"ins\": [";
      for (size_t k = 0; k < op.operands.size(); ++k) {
        const std::string& id = op.operands[k];
        auto dit = c.f.def.find(id);
        bool internal = dit != c.f.def.end() && region.count(dit->second);
        std::string tok;
        if (internal) {
          tok = id;
        } else {
          if (!extidx.count(id)) {
            extidx[id] = (int)ext.size();
            ext.push_back(id);
          }
          std::ostringstream es;
          es << "#" << extidx[id];
          tok = es.str();
        }
        prog << (k ? ", " : "") << "\"" << json_escape(tok) << "\"";
      }
      prog << "], \"out\": \"" << json_escape(op.result) << "\"}";
    }
    prog << "]";
    const Op& fop = c.f.ops[fin];
    mt.result = fop.result;
    mt.result_type = result_type_of(fop.line);
    mt.final_line = fop.idx;
    mt.operands = ext;
    for (auto& id : ext) mt.operand_types.push_back(c.type_of(id));
    mt.extra_json = std::string(", \"prog\": ") + prog.str();
    out->push_back(mt);
  }
}

static bool chain_is_closed(const Ctx& c, const Match& m) {
  std::set<int> span(m.chain_lines.begin(), m.chain_lines.end());
  span.insert(m.final_line);
  // count uses of each interior result across ALL ops; they must all
  // come from ops inside the span
  for (int li : m.chain_lines) {
    const Op* op = nullptr;
    for (const Op& o : c.f.ops) if (o.idx == li) { op = &o; break; }
    if (!op || op->result.empty()) continue;
    int inside = 0;
    for (const Op& o : c.f.ops) {
      if (!span.count(o.idx)) continue;
      for (auto& oid : o.operands) if (oid == op->result) inside++;
    }
    if (inside != c.uses(op->result)) return false;
  }
  return true;
}

static std::string json_escape(const std::string& s) {
  std::string o;
  for (char ch : s) {
    if (ch == '"' || ch == '\\') { o += '\\'; o += ch; }
    else o += ch;
  }
  return o;
}

}  // namespace

extern "C" {

void ptpu_free(char* p) { free(p); }

// JSON report: {"matches": [{"id":0,"pattern":"sdpa","operands":[...],
//   "operand_types":[...],"result":"%14","result_type":"tensor<..>",
//   "final_line":N,"chain_lines":[...],"scale":..,"eps":..}]}
char* ptpu_fusion_analyze(const char* module_text) {
  Module m = parse_module(module_text ? module_text : "");
  std::vector<Match> all;
  for (const Func& f : m.funcs) {
    // every function, not just @main: jax.export wraps the program in a
    // private func; helper funcs (e.g. @silu) are skipped by dint of
    // containing no full pattern
    Ctx c(f);
    std::vector<Match> ms;
    match_sdpa(c, &ms);
    match_rmsnorm(c, &ms);
    match_swiglu(c, &ms);
    std::set<int> claimed;
    for (auto& mt : ms) {
      if (!chain_is_closed(c, mt)) continue;
      bool overlap = claimed.count(mt.final_line) > 0;
      for (int li : mt.chain_lines) overlap |= claimed.count(li) > 0;
      if (overlap) continue;
      claimed.insert(mt.final_line);
      for (int li : mt.chain_lines) claimed.insert(li);
      all.push_back(mt);
    }
    // generic regions run AFTER the named patterns so a region never eats
    // the interior of an sdpa/rmsnorm/swiglu chain
    std::vector<Match> gs;
    match_generic(c, claimed, &gs);
    for (auto& mt : gs) {
      if (!chain_is_closed(c, mt)) continue;
      bool overlap = claimed.count(mt.final_line) > 0;
      for (int li : mt.chain_lines) overlap |= claimed.count(li) > 0;
      if (overlap) continue;
      claimed.insert(mt.final_line);
      for (int li : mt.chain_lines) claimed.insert(li);
      all.push_back(mt);
    }
  }
  std::ostringstream js;
  js << "{\"matches\": [";
  for (size_t i = 0; i < all.size(); ++i) {
    const Match& mt = all[i];
    if (i) js << ", ";
    js << "{\"id\": " << i << ", \"pattern\": \"" << mt.pattern << "\"";
    js << ", \"operands\": [";
    for (size_t j = 0; j < mt.operands.size(); ++j)
      js << (j ? ", " : "") << "\"" << json_escape(mt.operands[j]) << "\"";
    js << "], \"operand_types\": [";
    for (size_t j = 0; j < mt.operand_types.size(); ++j)
      js << (j ? ", " : "") << "\""
         << json_escape(mt.operand_types[j]) << "\"";
    js << "], \"result\": \"" << json_escape(mt.result) << "\"";
    js << ", \"result_type\": \"" << json_escape(mt.result_type) << "\"";
    js << ", \"final_line\": " << mt.final_line;
    js << ", \"chain_lines\": [";
    for (size_t j = 0; j < mt.chain_lines.size(); ++j)
      js << (j ? ", " : "") << mt.chain_lines[j];
    js << "], \"scale\": " << mt.scale << ", \"eps\": " << mt.eps
       << mt.extra_json << "}";
  }
  js << "]}";
  return strdup(js.str().c_str());
}

// plan format (one block per match, in analyze id order):
//   #MATCH <final_line> <funcname> <n_deleted_lines> <d0> <d1> ...
//   <replacement function text ... >
//   #END
// The call op is synthesized here from the analyze metadata re-derived
// from the final_line (operand list is passed in-line after funcname as
// comma-separated ids inside []).
char* ptpu_fusion_rewrite(const char* module_text, const char* plan) {
  Module m = parse_module(module_text ? module_text : "");
  std::vector<std::string> lines = m.lines;
  std::set<int> deleted;
  std::map<int, std::string> replacement;  // final_line -> call text
  std::string funcs_accum;

  std::stringstream ps(plan ? plan : "");
  std::string pl;
  while (std::getline(ps, pl)) {
    if (pl.rfind("#MATCH ", 0) != 0) continue;
    // #MATCH <final_line> <funcname> <result> <result_type> \t <operands
    // comma-joined> \t <operand_types comma-joined> \t <deleted
    // space-joined>
    std::string rest = pl.substr(7);
    std::vector<std::string> tabs;
    {
      std::string cur;
      for (char ch : rest) {
        if (ch == '\t') { tabs.push_back(cur); cur.clear(); }
        else cur += ch;
      }
      tabs.push_back(cur);
    }
    if (tabs.size() < 5) continue;
    std::stringstream h(tabs[0]);
    int final_line; std::string fname, result, rtype;
    h >> final_line >> fname >> result;
    rtype = tabs[1];
    std::string ops_join = tabs[2], tys_join = tabs[3], dels = tabs[4];
    // collect function text until #END
    std::string ftext, fl;
    while (std::getline(ps, fl)) {
      if (fl == "#END") break;
      ftext += fl; ftext += "\n";
    }
    funcs_accum += ftext;
    // deleted lines
    std::stringstream ds(dels);
    int d;
    while (ds >> d) deleted.insert(d);
    // synthesize the call
    std::ostringstream call;
    call << "    " << result << " = call @" << fname << "(";
    // ops_join comma-separated
    call << ops_join;
    call << ") : (";
    call << tys_join;
    call << ") -> " << rtype;
    replacement[final_line] = call.str();
  }

  std::ostringstream out;
  for (int i = 0; i < (int)lines.size(); ++i) {
    if (i == m.module_close && !funcs_accum.empty()) {
      out << funcs_accum;
    }
    if (deleted.count(i)) continue;
    auto rit = replacement.find(i);
    if (rit != replacement.end()) {
      out << rit->second << "\n";
      continue;
    }
    out << lines[i] << "\n";
  }
  return strdup(out.str().c_str());
}

}  // extern "C"
