"""Static per-kernel VMEM / HBM memory model (ISSUE 13 tentpole).

Built on the :mod:`kernelmodel` grid x BlockSpec evaluator: for every
registered oracle kernel this module publishes CANONICAL decode-shaped
bindings (the llama/gpt/moe/mla family shapes the engine actually
launches) and derives, purely from the committed AST,

  - the per-core VMEM footprint of one launch: resolvable block bytes
    (doubled when the index_map references a grid dim — Pallas keeps a
    revolving double buffer for re-fetched operands) plus
    ``scratch_shapes`` accumulators.  Unresolvable parts are COUNTED,
    not guessed, so every footprint is an explicit lower bound;
  - the HBM transfer bytes of one launch (``fetch runs x block bytes``,
    the same accounting `observability/costmodel.py` states in closed
    form), which PF406 cross-checks against the registered
    ``CostEstimate`` within :data:`COST_DRIFT_RTOL`;
  - producer/consumer tiling signatures across the decode-layer kernel
    chain, which PF404 turns into the fusion-opportunity worklist for
    ROADMAP item 1 (mega-kernel decode).

The flash/flashmask in_specs ride through the tuple-unpacked ``_specs``
helpers, invisible to the flow-insensitive ``Env``; they are rebuilt by
recording the ``order == 'qk'`` branch over the helper's scope (the same
technique `tests/test_costmodel.py` committed for the flash pin).

Pure stdlib (`ast` only): the cost registry is loaded from
``observability/costmodel.py`` BY FILE PATH, so nothing here ever
imports jax.  Degrade to unknown, never guess.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from . import kernelmodel as km
from .callgraph import PackageIndex
from .kernelmodel import KernelCallSite

__all__ = [
    "VMEM_BYTES_PER_CORE", "COST_DRIFT_RTOL", "DTYPE_WIDTHS",
    "CANONICAL", "FAMILY_SHAPES", "DECODE_CHAIN",
    "load_costmodel", "canonical_sites", "site_bindings", "grid_ok",
    "site_footprint", "derive_transfer", "derive_cost_bytes",
    "fusion_candidates", "rebuild_helper_specs", "resolved_value",
]

#: Pallas VMEM budget per TensorCore (v4/v5 generations: ~16 MiB).
VMEM_BYTES_PER_CORE = 16 * 1024 * 1024

#: PF406 / perf_gate shared tolerance: vmemmodel-derived bytes and the
#: registered CostEstimate must agree within this relative error.  ONE
#: constant — tools/perf_gate.py imports it, so the two gates cannot
#: drift apart.
COST_DRIFT_RTOL = 0.05

DTYPE_WIDTHS: Dict[str, int] = {
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "int8": 1, "uint8": 1,
    "bool_": 1,
}

_COSTMODEL_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "observability", "costmodel.py")


def load_costmodel():
    """The cost registry, loaded by file path (pure python + math; going
    through the package would drag in jax). None when unavailable."""
    name = "_paddlelint_costmodel"
    if name in sys.modules:
        return sys.modules[name]
    try:
        spec = importlib.util.spec_from_file_location(
            name, _COSTMODEL_PATH)
        if spec is None or spec.loader is None:
            return None
        mod = importlib.util.module_from_spec(spec)
        # dataclasses resolves cls.__module__ through sys.modules at
        # class-creation time; register before exec
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        sys.modules.pop(name, None)
        return None


# ---------------------------------------------------------------------------
# family shapes + canonical per-site bindings
# ---------------------------------------------------------------------------
#
# The real model configs the engine serves (models/llama.py,
# models/gpt.py, models/moe_llm.py, models/deepseek.py).  PF405 sweeps
# every canonical site's grid divisibility under its applicable
# families, not just the canonical symbols.

FAMILY_SHAPES: Dict[str, Dict[str, int]] = {
    "llama": dict(hidden=4096, intermediate=14336, heads=32, kv_heads=8,
                  head_dim=128),
    "gpt": dict(hidden=4096, intermediate=16384, heads=32, kv_heads=32,
                head_dim=128),
    "moe": dict(hidden=4096, intermediate=14336, heads=32, kv_heads=8,
                head_dim=128, experts=8, top_k=2),
    "mla": dict(hidden=5120, heads=16, lora_rank=512, rope_dim=64),
}

# One entry per registered oracle kernel, keyed by the qualname of the
# function that owns its pallas_call.  Fields:
#   kernel       cost-registry name (ops/oracles.py name)
#   bindings     Name -> int for the site's block/grid symbols, decode-
#                shaped (T = decode batch rows; page_size 32; D 128)
#   in_widths /  dtype bytes per in/out spec, in source order (prefetch
#   out_widths   operands are excluded from in_specs, matching Pallas)
#   cost_kwargs  shapes handed to cost(kernel, ...) for PF406
#   mode         "exact": compare hbm read+write; "activations": the
#                site's resolvable specs cover only the activation side
#                (paged v2 keeps K/V behind memory_space=ANY manual
#                DMA), so compare against breakdown["activations"]
#   any_inputs   in-spec indices EXPECTED to evaluate to None (ANY)
#   rebuild      in_specs live behind the module's `_specs` helper;
#                rebuild them with the order='qk' branch recorded
#   token_tiled  the launch sweeps the token axis (PF404 chain signat.)
#   families     PF405 family sweep: family name -> binding overrides
CANONICAL: Dict[str, Dict[str, Any]] = {
    # -- ops/fused.py ------------------------------------------------------
    "_rms_forward": dict(
        kernel="fused_rms_norm",
        bindings=dict(T=8, bt=8, H=4096),
        in_widths=[2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=4096),
        token_tiled=True,
        families={"llama": dict(H=4096), "gpt": dict(H=4096)},
    ),
    "fused_layer_norm": dict(
        kernel="fused_layer_norm",
        bindings=dict(T=8, bt=8, H=4096),
        in_widths=[2, 2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=4096),
        token_tiled=True,
        families={"gpt": dict(H=4096)},
    ),
    "_brln_forward": dict(
        kernel="fused_bias_residual_layer_norm",
        bindings=dict(T=8, bt=8, H=4096),
        in_widths=[2, 2, 2, 2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=4096),
        token_tiled=True,
        families={"gpt": dict(H=4096)},
    ),
    "_moe_dc_forward": dict(
        kernel="fused_moe_dispatch_combine",
        bindings=dict(T=8, bt=8, K=2, E=8, C=64),
        in_widths=[4, 4, 4], out_widths=[4, 4],
        cost_kwargs=dict(T=8, K=2, E=8, C=64),
        token_tiled=True,
        families={"moe": dict(E=8, K=2)},
    ),
    # fused_rope launches _rope_forward once for q and once for k; the
    # cost entry covers the PAIR, so the canonical binding folds both
    # head counts into one conceptual launch (H = Hq + Hk = 40) — the
    # cos/sin fetch then matches the single trig read the cost states.
    "_rope_forward": dict(
        kernel="fused_rope",
        bindings=dict(B=4, S=256, bs=256, H=40, D=128),
        in_widths=[2, 2, 2], out_widths=[2],
        cost_kwargs=dict(B=4, S=256, H=32, Hk=8, D=128),
        token_tiled=False,
        families={"llama": dict(H=40, D=128)},
    ),
    "fused_rope_append": dict(
        kernel="fused_rope_append",
        bindings=dict(T=8, Hq=32, KV=8, D=128, psz=32, d2=64),
        in_widths=[2, 2, 2, 2, 2, 2, 2], out_widths=[2, 2, 2],
        cost_kwargs=dict(T=8, Hq=32, KV=8, D=128, page_size=32),
        token_tiled=True,
        families={"llama": dict(Hq=32, KV=8, D=128),
                  "gpt": dict(Hq=32, KV=32, D=128)},
    ),
    "fused_append_rows": dict(
        kernel="fused_append_rows",
        bindings=dict(T=8, KV=8, D=128, psz=32),
        in_widths=[2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, KV=8, D=128, page_size=32),
        token_tiled=True,
        families={"mla": dict(KV=1, D=576)},
    ),
    "_swiglu_forward": dict(
        kernel="swiglu",
        bindings=dict(T=8, bt=8, H=14336),
        in_widths=[2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=14336),
        token_tiled=True,
        families={"llama": dict(H=14336), "gpt": dict(H=16384)},
    ),
    # -- ops/pallas_flash.py / pallas_flashmask.py -------------------------
    "_flash_fwd_impl": dict(
        kernel="flash_sdpa",
        bindings=dict(B=1, H=8, Sq=1024, Sk=1024, D=128,
                      bq=512, bk=512, nq=2, nk=2),
        in_widths=[4, 4, 2, 2, 2], out_widths=[2, 4],
        cost_kwargs=dict(B=1, H=8, Sq=1024, Sk=1024, D=128),
        rebuild=True,
        token_tiled=False,
    ),
    # the startend row-index mask rows and the SMEM skip map are not in
    # the closed-form cost (which carries flash's seg term instead);
    # both are stats-sized against the K/V stream, so the site lands
    # inside COST_DRIFT_RTOL rather than exactly on the formula.
    "_flashmask_fwd_impl": dict(
        kernel="flashmask_sdpa",
        bindings=dict(B=1, H=8, Sq=1024, Sk=1024, D=128,
                      bq=512, bk=512, nq=2, nk=2),
        in_widths=[4, 4, 4, 4, 4, 2, 2, 2], out_widths=[2, 4],
        cost_kwargs=dict(B=1, H=8, Sq=1024, Sk=1024, D=128),
        rebuild=True,
        token_tiled=False,
    ),
    # -- ops/pallas_paged.py / pallas_ragged.py / pallas_mla.py ------------
    "paged_decode_attention": dict(
        kernel="paged_decode_attention",
        bindings=dict(B=8, KV=8, rep=4, D=128, nj=8, page_size=32),
        in_widths=[2, 2, 2], out_widths=[2],
        cost_kwargs=dict(B=8, H=32, KV=8, D=128, context=256,
                         page_size=32, pages_per_seq=8),
        token_tiled=False,
        families={"llama": dict(KV=8, rep=4, D=128)},
    ),
    "paged_decode_attention_v2": dict(
        kernel="paged_decode_attention_v2",
        bindings=dict(B=8, KV=8, rep=4, D=128, G=2, psz=32),
        in_widths=[2, 2, 2], out_widths=[2],
        cost_kwargs=dict(B=8, H=32, KV=8, D=128, context=256,
                         page_size=32, pages_per_seq=8),
        mode="activations",
        any_inputs=(1, 2),
        token_tiled=False,
    ),
    "ragged_paged_attention": dict(
        kernel="ragged_paged_attention",
        bindings=dict(T=8, rep=4, D=128, KV=8, S=8, nj=8, psz=32),
        in_widths=[2, 2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=32, KV=8, D=128, S=8, pages_per_seq=8,
                         page_size=32),
        token_tiled=False,
        families={"llama": dict(KV=8, rep=4, D=128)},
    ),
    "mla_decode_attention": dict(
        kernel="mla_decode_attention",
        bindings=dict(B=8, nh=16, r=512, dr=64, block_t=128, nj=4),
        in_widths=[2, 2, 2, 2], out_widths=[2],
        cost_kwargs=dict(B=8, nh=16, r=512, dr=64, context=512,
                         block_t=128),
        token_tiled=False,
        families={"mla": dict(nh=16, r=512, dr=64)},
    ),
    # -- ops/pallas_gmm.py / quant.py --------------------------------------
    # gmm: one m-block, one n-block (the cost's nn factor is then 1 and
    # the pl.when group-elision lower bound coincides with grid x block)
    "_gmm_fwd_impl": dict(
        kernel="gmm",
        bindings=dict(nm=1, nn=1, G=8, bm=128, bn=128, K=4096, Mp=128),
        in_widths=[2, 2], out_widths=[2],
        cost_kwargs=dict(M=128, K=4096, N=128, G=8,
                         block_m=128, block_n=128),
        token_tiled=False,
        families={"moe": dict(G=8, K=4096)},
    ),
    # int4_dequantize: tensor-parallel shard shapes; K=1024 keeps the
    # whole-column f32 out block (K x bn x 4B, doubled) inside VMEM
    "int4_dequantize": dict(
        kernel="int4_dequantize",
        bindings=dict(K2=512, Np=1024, bn=1024),
        in_widths=[1, 4], out_widths=[4],
        cost_kwargs=dict(K=1024, N=1024),
        token_tiled=False,
        families={"llama": dict(K2=512, Np=1024)},
    ),
    # weight_only_linear (int8 path): N=1792 is the 8-way tensor-
    # parallel shard of llama's 14336 — the whole [K, N] int8 slab is
    # VMEM-resident (index_map refs no grid dim: fetched once)
    "_wol_int8_fwd_impl": dict(
        kernel="weight_only_linear",
        bindings=dict(M=128, bm=128, K=4096, N=1792),
        in_widths=[2, 1, 4], out_widths=[2],
        cost_kwargs=dict(M=128, K=4096, N=1792,
                         algo="weight_only_int8"),
        token_tiled=False,
        families={"llama": dict(K=4096, N=1792)},
    ),
    # -- ops/pallas_megadecode.py (ISSUE 14 mega-kernel back half) ---------
    # 8-way tensor-parallel shard shapes, like _wol_int8_fwd_impl: H=512
    # is llama's 4096/8, I=1792 its 14336/8 — the whole weight slab is
    # VMEM-resident (constant index_maps: fetched once per launch).
    "_oproj_norm_forward": dict(
        kernel="fused_oproj_norm",
        bindings=dict(T=8, bt=8, Ko=512, H=512),
        in_widths=[2, 2, 2, 4, 2, 2, 2], out_widths=[2, 2],
        cost_kwargs=dict(T=8, Ko=512, H=512),
        token_tiled=True,
        families={"llama": dict(Ko=512, H=512)},
    ),
    "_oproj_norm_int4": dict(
        kernel="fused_oproj_norm",
        bindings=dict(T=8, bt=8, Ko2=256, H=512),
        in_widths=[2, 2, 2, 1, 4, 2, 2, 2], out_widths=[2, 2],
        cost_kwargs=dict(T=8, Ko=512, H=512, algo="weight_only_int4"),
        token_tiled=True,
        families={"llama": dict(Ko2=256, H=512)},
    ),
    "_ffn_forward": dict(
        kernel="fused_ffn",
        bindings=dict(T=8, bt=8, H=512, I=1792, Ku=512),
        in_widths=[2, 2, 2, 4, 2, 4, 2, 4, 2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=512, I=1792),
        token_tiled=True,
        families={"llama": dict(H=512, I=1792)},
    ),
    "_ffn_int4": dict(
        kernel="fused_ffn",
        bindings=dict(T=8, bt=8, H=512, H2=256, I=1792, I2=896),
        in_widths=[2, 2, 2, 1, 4, 1, 4, 1, 4, 2, 2], out_widths=[2],
        cost_kwargs=dict(T=8, H=512, I=1792, algo="weight_only_int4"),
        token_tiled=True,
        families={"llama": dict(H2=256, I2=896)},
    ),
    # -- ops/pallas_megafront.py (ISSUE 20 mega-kernel front half) ---------
    # 8-way shard hidden (H=512) against the FULL qkv out width
    # N=(Hq+2KV)*D — out channels don't shard with the contraction; the
    # concatenated slab is VMEM-resident (constant index_map, one fetch)
    # while the token row, trig rows and page blocks sweep with t.
    "_qkv_rope_append_fwd": dict(
        kernel="fused_qkv_rope_append",
        bindings=dict(T=8, H=512, N=6144, heads=32, KV=8, D=128,
                      psz=32, d2=64),
        in_widths=[2, 2, 4, 2, 2, 2, 2, 2], out_widths=[2, 2, 2],
        cost_kwargs=dict(T=8, H=512, Hq=32, KV=8, D=128, page_size=32),
        token_tiled=True,
        families={"llama": dict(H=512, N=6144, heads=32, KV=8, D=128),
                  "gpt": dict(KV=32, N=12288)},
    ),
    "_qkv_rope_append_int4": dict(
        kernel="fused_qkv_rope_append",
        bindings=dict(T=8, H2=256, N=6144, heads=32, KV=8, D=128,
                      psz=32),
        in_widths=[2, 2, 1, 4, 2, 2, 2, 2], out_widths=[2, 2, 2],
        cost_kwargs=dict(T=8, H=512, Hq=32, KV=8, D=128, page_size=32,
                         algo="weight_only_int4"),
        token_tiled=True,
        families={"llama": dict(H2=256, N=6144)},
    ),
    # MLA front: q [H, nh*(dn+dr)] and kv_a [H, r+dr] concatenate into
    # one slab; the pool row is [latent | rope-key] (Dc = r + dr)
    "_mla_qkv_rope_append_fwd": dict(
        kernel="fused_qkv_rope_append",
        bindings=dict(T=8, H=640, N=3648, r=512, dd2=32, heads=16,
                      dh=192, psz=32, Dc=576),
        in_widths=[2, 2, 4, 2, 2, 2, 2], out_widths=[2, 2],
        cost_kwargs=dict(T=8, H=640, Hq=16, page_size=32,
                         nope_dim=128, rope_dim=64, lora_rank=512),
        token_tiled=True,
        families={"mla": dict(H=640, N=3648, r=512, heads=16)},
    ),
}

#: The decode-layer kernel chain in launch order (PF404 walks adjacent
#: pairs).  ISSUE 14 collapsed the back half into the two megadecode
#: launches — o-proj + residual + norm, then the whole FFN — and ISSUE
#: 20 consumed the front-half seam: the qkv projection matmuls, rope,
#: and the paged K/V scatter now live in one fused_qkv_rope_append
#: launch, so the old fused_rms_norm -> fused_rope_append advisory
#: (whose only obstacle was the 8-rows-vs-1 retile) is RESOLVED — the
#: fused kernel emits q at the attention consumer's one-token
#: granularity, and fused_rope_append stays registered for the
#: standalone op / fallback path.  The advisories that remain standing
#: are justified seams, not oversights:
#:   - fused_rms_norm -> fused_qkv_rope_append 'retile': the norm
#:     still runs a bt=8 row block while the fused front sweeps one
#:     token per step; folding the norm in is the registered seam for
#:     the ROADMAP <=4-launch follow-on (a [T, H] x [H, (Hq+2KV)D]
#:     slab plus the norm row block co-resides at the family shapes —
#:     the obstacle is purely the 8-vs-1 retile);
#:   - fused_oproj_norm -> fused_ffn 'aligned': the deliberate two-
#:     kernel cut — the o-proj slab plus all three FFN slabs exceed the
#:     16 MiB budget even 8-way sharded, so only the [T, H] residual +
#:     normed pair crosses HBM between them (down from four
#:     intermediates in the unfused chain).
DECODE_CHAIN: List[str] = [
    "fused_rms_norm", "fused_qkv_rope_append", "ragged_paged_attention",
    "fused_oproj_norm", "fused_ffn",
]

_CHAIN_SITE: Dict[str, str] = {
    "fused_rms_norm": "_rms_forward",
    "fused_rope_append": "fused_rope_append",
    "fused_qkv_rope_append": "_qkv_rope_append_fwd",
    "ragged_paged_attention": "ragged_paged_attention",
    "fused_oproj_norm": "_oproj_norm_forward",
    "fused_ffn": "_ffn_forward",
}


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def site_bindings(entry: Dict[str, Any],
                  family: Optional[str] = None) -> Dict[str, int]:
    b = dict(entry["bindings"])
    if family is not None:
        b.update(entry.get("families", {}).get(family, {}))
    return b


def resolved_value(expr: ast.AST, env: km.Env,
                   bindings: Dict[str, int]) -> Optional[int]:
    """Evaluate `expr` with the site's own assignments taking precedence
    over the canonical bindings: a literal ``bn = 64`` in the file beats
    the published shape (that is the defect PF403/PF405 exist to catch);
    an unresolvable chain (``bn = next(...)``) falls back to bindings."""
    v = km.eval_int_expr(env.resolve(expr), bindings)
    if v is None:
        v = km.eval_int_expr(expr, bindings)
    return v


def canonical_sites(index: PackageIndex) -> Dict[str, KernelCallSite]:
    """qualname -> call site for every CANONICAL kernel present in the
    analyzed set (each owning function holds exactly one pallas_call)."""
    out: Dict[str, KernelCallSite] = {}
    for site in km.collect_kernel_calls(index):
        qn = site.qualname
        if qn in CANONICAL and qn not in out:
            out[qn] = site
    return out


def grid_ok(site: KernelCallSite, bindings: Dict[str, int]) -> bool:
    """The grid evaluates and every ``a // b`` component divides exactly
    (a mis-gridded launch makes byte accounting meaningless — PF405 owns
    that finding; PF401/PF406 skip)."""
    if km.grid_values(site, bindings) is None:
        return False
    for e in site.grid_elts or []:
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.FloorDiv):
            a = km.eval_int_expr(e.left, bindings)
            d = km.eval_int_expr(e.right, bindings)
            if a is None or not d or a % d:
                return False
    return True


def _flatten_spec_list(expr: Optional[ast.AST],
                       env: km.Env) -> Optional[List[ast.AST]]:
    """Evaluate a ``[a] + [b] * 4 + [...]`` spec-list expression to its
    element ASTs (the flashmask `_specs` return shape)."""
    expr = env.resolve(expr)
    if isinstance(expr, (ast.List, ast.Tuple)):
        return list(expr.elts)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _flatten_spec_list(expr.left, env)
        right = _flatten_spec_list(expr.right, env)
        if left is None or right is None:
            return None
        return left + right
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):
        base = _flatten_spec_list(expr.left, env)
        n = km.eval_int_expr(expr.right, {})
        if base is None or n is None or n < 0:
            return None
        return base * n
    return None


def rebuild_helper_specs(site: KernelCallSite, helper: str = "_specs"
                         ) -> Tuple[Optional[List[km.BlockSpecModel]],
                                    Optional[List[km.BlockSpecModel]]]:
    """Rebuild (in_specs, out_specs) for sites whose specs ride through
    the module's tuple-unpacked `_specs` helper.  Records the
    ``order == 'qk'`` branch body over the helper's env (Env is
    flow-insensitive; without this the else-branch maps would win) and
    flattens the returned list expression."""
    mi = site.mi
    fi = mi.functions.get(helper)
    if fi is None:
        return None, None
    env = km.Env(mi, fi)
    branch = next((n for n in ast.walk(fi.node) if isinstance(n, ast.If)),
                  None)
    if branch is not None:
        for stmt in branch.body:
            env._record(stmt)
    ret = next((n for n in ast.walk(fi.node)
                if isinstance(n, ast.Return)), None)
    if ret is None or not isinstance(ret.value, ast.Tuple) \
            or not ret.value.elts:
        return None, None
    elts = _flatten_spec_list(ret.value.elts[0], env)
    if elts is None:
        return None, None
    in_specs = []
    for e in elts:
        spec = km.build_block_spec(e, mi, fi, env)
        if spec is None:
            return None, None
        in_specs.append(spec)
    out_specs = None
    if site.out_specs is not None:
        out_specs = [km.build_block_spec(s.node, mi, fi, env) or s
                     for s in site.out_specs]
    return in_specs, out_specs


def _site_specs(site: KernelCallSite, entry: Dict[str, Any]
                ) -> Tuple[Optional[List[km.BlockSpecModel]],
                           Optional[List[km.BlockSpecModel]]]:
    if entry.get("rebuild"):
        return rebuild_helper_specs(site)
    return site.in_specs, site.out_specs


# ---------------------------------------------------------------------------
# VMEM footprint
# ---------------------------------------------------------------------------

def _scratch_bytes(site: KernelCallSite,
                   bindings: Dict[str, int]) -> Tuple[int, int]:
    """(bytes, unresolved entries) for the VMEM/SMEM scratch shapes.
    Semaphores and ANY-space scratch carry no VMEM block."""
    total = 0
    unresolved = 0
    for expr in site.scratch or []:
        if not (isinstance(expr, ast.Call)
                and km._last_name(expr.func) in ("VMEM", "SMEM")
                and expr.args):
            continue
        width = DTYPE_WIDTHS.get(km.scratch_dtype_name(expr) or "")
        shape = km._seq_elts(expr.args[0])
        if width is None or shape is None:
            unresolved += 1
            continue
        elems = 1
        for e in shape:
            v = km.eval_int_expr(e, bindings)
            if v is None:
                elems = None
                break
            elems *= v
        if elems is None:
            unresolved += 1
        else:
            total += elems * width
    return total, unresolved


def site_footprint(site: KernelCallSite, entry: Dict[str, Any],
                   bindings: Optional[Dict[str, int]] = None
                   ) -> Dict[str, int]:
    """Per-core VMEM bytes of one launch under the canonical bindings:
    each resolvable non-ANY block (x2 when its index_map references a
    grid dim — the revolving fetch buffer), SMEM blocks excluded, plus
    scratch accumulators.  ``unresolved`` counts the parts that did not
    evaluate — the footprint is a documented lower bound."""
    b = dict(bindings) if bindings is not None else site_bindings(entry)
    in_specs, out_specs = _site_specs(site, entry)
    total = 0
    unresolved = 0
    grid_len = site.grid_len or 0
    for specs, widths in ((in_specs, entry.get("in_widths", [])),
                          (out_specs, entry.get("out_widths", []))):
        for i, spec in enumerate(specs or []):
            if spec.memory_space in ("ANY", "SMEM"):
                continue
            width = widths[i] if i < len(widths) else None
            if width is None or spec.block_shape is None:
                unresolved += 1
                continue
            elems = 1
            for e in spec.block_shape:
                v = km.eval_int_expr(e, b)
                if v is None:
                    elems = None
                    break
                elems *= v
            if elems is None:
                unresolved += 1
                continue
            mult = 1
            if spec.index_map is not None and \
                    km.index_map_grid_refs(spec.index_map, grid_len):
                mult = 2
            total += elems * width * mult
    sb, su = _scratch_bytes(site, b)
    return {"bytes": total + sb, "unresolved": unresolved + su}


# ---------------------------------------------------------------------------
# HBM transfer derivation + cost cross-check (PF406)
# ---------------------------------------------------------------------------

def derive_transfer(site: KernelCallSite, entry: Dict[str, Any],
                    bindings: Optional[Dict[str, int]] = None
                    ) -> Optional[Dict[str, int]]:
    """{'read': bytes, 'write': bytes, 'unresolved': n} for one launch
    under the canonical bindings, or None when the grid itself does not
    evaluate.  In-spec indices listed in ``any_inputs`` are expected to
    opt out (manual-DMA operands) and are not counted unresolved."""
    b = dict(bindings) if bindings is not None else site_bindings(entry)
    grid = km.grid_values(site, b)
    if grid is None or site.grid_len is None:
        return None
    in_specs, out_specs = _site_specs(site, entry)
    skip_in = set(entry.get("any_inputs", ()))
    res = {"read": 0, "write": 0, "unresolved": 0}
    for specs, widths, key, skip in (
            (in_specs, entry.get("in_widths", []), "read", skip_in),
            (out_specs, entry.get("out_widths", []), "write", set())):
        for i, spec in enumerate(specs or []):
            width = widths[i] if i < len(widths) else None
            elems = km.spec_transfer_elems(spec, grid, site.grid_len, b)
            if elems is None or width is None:
                if i not in skip:
                    res["unresolved"] += 1
                continue
            res[key] += elems * width
    return res


def derive_cost_bytes(index: PackageIndex,
                      cost_module=None) -> List[Dict[str, Any]]:
    """One record per CANONICAL kernel present in `index`: the
    AST-derived HBM bytes vs the registered CostEstimate.  status is
    'ok' / 'drift', or 'skipped:<why>' when the comparison is not
    meaningful (absent site, failed grid divisibility — PF405 owns that
    — or an unresolvable spec)."""
    cm = cost_module if cost_module is not None else load_costmodel()
    sites = canonical_sites(index)
    records: List[Dict[str, Any]] = []
    for qn, entry in CANONICAL.items():
        site = sites.get(qn)
        if site is None:
            continue
        rec: Dict[str, Any] = {
            "kernel": entry["kernel"], "qualname": qn,
            "path": site.mi.rel, "line": site.line,
        }
        b = site_bindings(entry)
        if not grid_ok(site, b):
            rec["status"] = "skipped:grid"
            records.append(rec)
            continue
        t = derive_transfer(site, entry, b)
        if t is None or t["unresolved"]:
            rec["status"] = "skipped:unresolved"
            records.append(rec)
            continue
        derived = t["read"] + t["write"]
        rec["derived"] = derived
        if cm is None:
            rec["status"] = "skipped:costmodel"
            records.append(rec)
            continue
        try:
            est = cm.cost(entry["kernel"], **entry["cost_kwargs"])
        except Exception:
            rec["status"] = "skipped:cost-error"
            records.append(rec)
            continue
        if entry.get("mode") == "activations":
            expected = (est.breakdown or {}).get("activations")
        else:
            expected = est.bytes_read + est.bytes_written
        if not expected:
            rec["status"] = "skipped:cost-empty"
            records.append(rec)
            continue
        rel = abs(derived - expected) / expected
        rec.update(expected=expected, rel_err=rel,
                   status="ok" if rel <= COST_DRIFT_RTOL else "drift")
        records.append(rec)
    return records


# ---------------------------------------------------------------------------
# fusion opportunities (PF404)
# ---------------------------------------------------------------------------

def _leading_sweep(spec: Optional[km.BlockSpecModel],
                   grid_len: Optional[int]) -> Optional[ast.AST]:
    """The block's leading extent when the spec is a leading-axis sweep:
    index_map returns ``(g, 0, ..., 0)`` with g referencing a grid dim.
    None otherwise."""
    if spec is None or spec.block_shape is None or spec.index_map is None:
        return None
    rets = spec.index_map.returns
    if not rets:
        return None
    comps = rets[0]
    if len(comps) != len(spec.block_shape):
        return None
    for c in comps[1:]:
        if km._int_const(c) != 0:
            return None
    if not km.index_map_grid_refs(spec.index_map, grid_len or 0):
        return None
    return spec.block_shape[0]


def fusion_candidates(index: PackageIndex) -> List[Dict[str, Any]]:
    """Adjacent DECODE_CHAIN pairs whose producer out-tiling and
    consumer in-tiling are both token-axis sweeps — each one is an HBM
    round-trip a fused kernel would elide.  class 'aligned' (identical
    leading block extents: fusable as-is) or 'retile' (both token-swept
    but at different granularity)."""
    sites = canonical_sites(index)
    out: List[Dict[str, Any]] = []
    for prod, cons in zip(DECODE_CHAIN, DECODE_CHAIN[1:]):
        pq, cq = _CHAIN_SITE[prod], _CHAIN_SITE[cons]
        pe, ce = CANONICAL[pq], CANONICAL[cq]
        ps, cs = sites.get(pq), sites.get(cq)
        if ps is None or cs is None:
            continue
        if not (pe.get("token_tiled") and ce.get("token_tiled")):
            continue
        p_spec = (ps.out_specs or [None])[0]
        c_spec = (cs.in_specs or [None])[0]
        p_lead = _leading_sweep(p_spec, ps.grid_len)
        c_lead = _leading_sweep(c_spec, cs.grid_len)
        if p_lead is None or c_lead is None:
            continue
        pv = km.eval_int_expr(p_lead, site_bindings(pe))
        cv = km.eval_int_expr(c_lead, site_bindings(ce))
        klass = "aligned" if (pv is not None and pv == cv) else "retile"
        out.append({
            "producer": prod, "consumer": cons, "class": klass,
            "site": ps, "detail": f"fuse:{prod}->{cons}",
        })
    return out
