"""Inference engine (SURVEY §2.1 'Inference engine', §3.6): jit.save →
jax.export artifact → jit.load / paddle_infer-parity Predictor."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.inference import Config, create_predictor


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)
        self.bn = nn.BatchNorm1D(16)

    def forward(self, x):
        return self.fc2(paddle.tanh(self.bn(self.fc1(x))))


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path_factory.mktemp("export") / "model")
    jit.save(net, prefix, input_spec=[((2, 8), "float32")])
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()
    return prefix, x, ref


def test_save_writes_all_artifacts(artifact):
    import os
    prefix, _, _ = artifact
    assert os.path.exists(prefix + ".pdparams")
    assert os.path.exists(prefix + ".jaxexport")
    assert os.path.exists(prefix + ".stablehlo.txt")
    with open(prefix + ".stablehlo.txt") as f:
        text = f.read()
    assert "stablehlo" in text or "module" in text


def test_jit_load_roundtrip(artifact):
    prefix, x, ref = artifact
    translated = jit.load(prefix)
    out = translated(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)
    with pytest.raises(RuntimeError):
        translated.train()


def test_predictor_handle_api(artifact):
    prefix, x, ref = artifact
    cfg = Config(prefix)
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    assert len(names) == 1
    h = pred.get_input_handle(names[0])
    assert h.shape() == [2, 8]
    h.copy_from_cpu(x)
    pred.run()
    out_h = pred.get_output_handle(pred.get_output_names()[0])
    np.testing.assert_allclose(out_h.copy_to_cpu(), ref, rtol=1e-5,
                               atol=1e-6)


def test_predictor_positional_run(artifact):
    prefix, x, ref = artifact
    pred = create_predictor(Config(prefix))
    outs = pred.run([x])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)


def test_predictor_missing_artifact(tmp_path):
    with pytest.raises(FileNotFoundError):
        create_predictor(Config(str(tmp_path / "nope")))


def test_bn_uses_running_stats_in_export(artifact):
    """Export must bake eval-mode BN (running stats), not batch stats."""
    prefix, x, ref = artifact
    pred = create_predictor(Config(prefix))
    # different batch with same first row: same first-row output only if
    # BN used running stats (batch stats would couple the rows)
    x2 = x.copy()
    x2[1] += 100.0
    out2 = pred.run([x2])[0]
    np.testing.assert_allclose(out2[0], ref[0], rtol=1e-4, atol=1e-5)
