"""LazyGuard — construct Layers without materializing parameters (ref:
python/paddle/base/lazy_init.py LazyGuard / LazyInitHelper).

Inside `with LazyGuard():`, nn.Layer.create_parameter records the
(initializer, shape, dtype) triple on a placeholder Parameter whose
`_data` is a jax.ShapeDtypeStruct — no device or host buffer exists.
`materialize(layer, shard_fn=...)` then runs the recorded initializers,
optionally `jax.device_put`-ing each result with a caller-chosen
sharding, so a model larger than one host's memory can be born directly
sharded over the mesh (the reference pairs LazyGuard with auto-parallel
shard_tensor the same way)."""

from __future__ import annotations

import threading
from typing import Callable, Optional

import numpy as np

__all__ = ["LazyGuard", "lazy_enabled", "materialize"]

_state = threading.local()


class LazyGuard:
    def __enter__(self):
        self._prev = getattr(_state, "on", False)
        _state.on = True
        return self

    def __exit__(self, *exc):
        _state.on = self._prev
        return False


def lazy_enabled() -> bool:
    return getattr(_state, "on", False)


_lazy_param_cls = None


def _get_lazy_cls():
    """Parameter subclass that fails eager access with a pointer to
    materialize() instead of an opaque ShapeDtypeStruct AttributeError."""
    global _lazy_param_cls
    if _lazy_param_cls is not None:
        return _lazy_param_cls
    import jax
    from ..nn.layer.layers import Parameter

    class _LazyParameter(Parameter):
        def _still_lazy(self):
            return isinstance(self._data, jax.ShapeDtypeStruct)

        def _lazy_err(self, what):
            raise RuntimeError(
                f"cannot {what} a lazy Parameter created under LazyGuard "
                f"(shape {tuple(self._data.shape)}); run "
                f"paddle_tpu.framework.lazy.materialize(layer) first")

        def numpy(self):
            if self._still_lazy():
                self._lazy_err("read")
            return super().numpy()

        @property
        def place(self):
            if self._still_lazy():
                self._lazy_err("query the place of")
            return Parameter.place.fget(self)

        def __repr__(self):
            if self._still_lazy():
                return (f"LazyParameter(shape={list(self._data.shape)}, "
                        f"dtype={self._data.dtype}, uninitialized)")
            return super().__repr__()

    # flatten like a Parameter once materialized (never flattened lazy)
    jax.tree_util.register_pytree_node(
        _LazyParameter,
        lambda p: ((p._data,), (p.stop_gradient,)),
        _unflatten_lazy)
    _lazy_param_cls = _LazyParameter
    return _LazyParameter


def _unflatten_lazy(aux, children):
    cls = _get_lazy_cls()
    p = cls.__new__(cls)
    p._data = children[0]
    p.stop_gradient = aux[0]
    p._grad = None
    p._node = None
    p.name = None
    p.persistable = True
    p._retain_grad = False
    p._hooks = []
    p.trainable = not aux[0]
    return p


def _make_lazy_parameter(init, shape, dt):
    import jax
    from ..core.dtypes import convert_dtype

    Parameter = _get_lazy_cls()
    p = Parameter.__new__(Parameter)
    p._data = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                   np.dtype(convert_dtype(dt) or dt))
    p.stop_gradient = False
    p._grad = None
    p._node = None
    p.name = None
    p.persistable = True
    p._retain_grad = False
    p._hooks = []
    p.trainable = True
    p._lazy_init = (init, list(shape), dt)
    return p


def materialize(layer, shard_fn: Optional[Callable] = None) -> None:
    """Run the deferred initializers of every still-lazy Parameter in
    `layer` (in place). shard_fn(name, param) -> jax.sharding.Sharding
    or None; when it returns a sharding the initialized array is
    device_put with it before binding."""
    import jax

    for name, p in layer.named_parameters():
        lazy = getattr(p, "_lazy_init", None)
        if lazy is None:
            continue
        if not isinstance(p._data, jax.ShapeDtypeStruct):
            # someone bound real data after construction (e.g. a direct
            # `weight._data = ...` init); respect it
            del p._lazy_init
            continue
        init, shape, dt = lazy
        data = init(shape, dt)
        data = data._data if hasattr(data, "_data") else data
        if shard_fn is not None:
            sharding = shard_fn(name, p)
            if sharding is not None:
                data = jax.device_put(data, sharding)
        p._data = data
        del p._lazy_init
        # demote to a plain Parameter: materialized params behave (and
        # pytree-flatten) exactly like eagerly-created ones
        from ..nn.layer.layers import Parameter
        if type(p).__name__ == "_LazyParameter":
            p.__class__ = Parameter
