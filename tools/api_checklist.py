"""Generate docs/API_CHECKLIST.md — the flat-namespace parity audit
(VERDICT r2 item 5; ref surface: python/paddle/__init__.py +
python/paddle/tensor/__init__.py method mounts).

Provenance: /root/reference has been an empty mount every round, so the
upstream name universe cannot be machine-diffed; this audit instead (a)
enumerates OUR surface exhaustively by defining module, (b) hand-curates
the upstream names known to be absent with an explicit reason/mapping
each, and (c) flags the names we expose that upstream does not (so the
count is honest in both directions).

Run:  python tools/api_checklist.py          (writes docs/API_CHECKLIST.md)
      python tools/api_checklist.py --diff /root/reference
                                             (reference-contact protocol:
                                              the session the mount has
                                              content, machine-diff the real
                                              upstream flat namespace against
                                              ours, re-verify the ABSENT
                                              hand-curation, and write
                                              docs/REF_DIFF.md)
"""

from __future__ import annotations

import ast
import os
import sys
import types
from collections import defaultdict

sys.path.insert(0, "/root/repo")

# names this build exposes flat that upstream's flat namespace does not —
# counted OUT of the parity number
EXTENSIONS = {
    "Generator": "framework RNG generator (upstream: paddle.base core "
                 "Generator, not exported flat)",
    "convert_dtype": "dtype-string normalizer (upstream keeps it in "
                     "paddle.base.data_feeder)",
    "gaussian": "alias of the tensor.random sampler (upstream keeps it "
                "under paddle.tensor.random)",
    "pad_nd": "N-d pad helper (upstream: nn.functional.pad only)",
    "softplus_math": "softplus used by tensor.math (upstream: "
                     "nn.functional.softplus only)",
    "bool_": "non-shadowing alias of paddle.bool",
    "to_tensor": None,  # upstream HAS to_tensor — keep in parity count
}
EXTENSIONS.pop("to_tensor")

# upstream flat names deliberately absent here, each with its mapping or
# UNSUPPORTED citation
ABSENT = {
    "pir": "module — superseded by the jaxpr/StableHLO program form "
           "(paddle_tpu.jit traced programs, paddle_tpu.static); see "
           "docs/PARITY.md PIR row",
    "base": "legacy fluid namespace — split into paddle_tpu.core + "
            "paddle_tpu.static here",
    "decomposition": "PIR decomposition pass module — JAX primitive "
                     "lowering plays this role (docs/PARITY.md)",
}

MODULE_ROLES = {
    "core": "tensor/dispatch/autograd internals (upstream paddle.base)",
    "generation": "text-generation engines (upstream: PaddleNLP "
                  "GenerationMixin)",
    "models": "model zoo (upstream: PaddleNLP/PaddleOCR model packages)",
    "native": "ctypes bindings to the C++ runtime pieces",
    "ops": "Pallas/XLA kernel library (upstream phi kernels)",
    "trainer": "pretrain step builder (upstream: PaddleNLP Trainer)",
    "flags": "FLAGS registry (upstream paddle.base.core flags)",
    "resilience": "fault injection + checkpoint integrity + recovery "
                  "policies (docs/RESILIENCE.md; upstream: fleet "
                  "elastic/checkpoint hooks)",
    "distributed": "upstream namesake package + `distributed.watchdog` "
                   "(collective flight recorder, hang watchdog, "
                   "cross-rank desync diagnosis — docs/RESILIENCE.md; "
                   "upstream: ProcessGroupNCCL watchdog/async error "
                   "handling)",
    "analysis": "paddlelint static-analysis suite: TPU/JAX hazard rules "
                "PT001-PT006 over the package source (docs/ANALYSIS.md; "
                "CLI tools/paddlelint.py; no upstream equivalent — "
                "covers tracer-leak/retrace/host-sync classes JAX adds)",
    "serving": "continuous-batching engine: paged KV block allocator "
               "(refcount/COW prefix sharing), FCFS in-flight scheduler, "
               "fixed-shape jitted decode over the paged kernel "
               "(docs/SERVING.md; upstream: FastDeploy/PaddleNLP "
               "PagedAttention serving)",
    "observability": "metrics registry + `observability.tracing` "
                     "per-request/per-step span timelines: SLO "
                     "histograms (TTFT/TPOT/e2e/queue-wait) with "
                     "percentile helpers, chrome-trace export "
                     "correlated with host-profiler spans "
                     "(docs/OBSERVABILITY.md; upstream: paddle "
                     "monitoring hooks / profiler RecordEvent)",
    "profiler": "paddle.profiler parity: host RecordEvent tracer + "
                "device XPlane capture, scheduler, chrome export, and "
                "`profiler.statistic.summarize` per-op/step-phase/"
                "memory summary tables (upstream: paddle.profiler + "
                "profiler_statistic.py)",
}


def _our_flat_names():
    import paddle_tpu as p
    return sorted(n for n in dir(p) if not n.startswith("_")
                  and not isinstance(getattr(p, n), types.ModuleType))


def _ref_flat_names(ref_root: str):
    """Extract the upstream flat-name universe WITHOUT importing paddle
    (the reference is CUDA/torch-built and unimportable here): AST-parse
    python/paddle/__init__.py for __all__ plus every top-level
    `from X import a, b` / `import m` binding, the same set `dir(paddle)`
    would show sans underscore names. Returns (flat_names, module_names,
    init_path); module bindings (`from . import nn`, `import paddle.X`)
    are bucketed separately so they diff against OUR modules, not our
    flat functions."""
    init = os.path.join(ref_root, "python", "paddle", "__init__.py")
    if not os.path.isfile(init):
        return None, None, init
    tree = ast.parse(open(init, encoding="utf-8").read())
    names, mod_names, all_names = set(), set(), None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if t.id == "__all__":
                        try:
                            all_names = set(ast.literal_eval(node.value))
                        except ValueError:
                            pass
                    elif not t.id.startswith("_"):
                        names.add(t.id)
        elif isinstance(node, ast.ImportFrom):
            # `from . import nn` (module is None) binds submodules;
            # `from .tensor.math import add` binds objects
            is_mod = node.module is None and node.level >= 1
            for a in node.names:
                bound = a.asname or a.name
                if bound != "*" and not bound.startswith("_"):
                    (mod_names if is_mod else names).add(bound)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    bound = a.asname
                elif a.name.startswith("paddle."):
                    # `import paddle.X` inside paddle/__init__ registers X
                    # as an attribute of the package — the surface name
                    # dir(paddle) shows is X, not `paddle`
                    bound = a.name.split(".")[1]
                else:
                    bound = a.name.split(".")[0]
                if not bound.startswith("_") and bound != "paddle":
                    mod_names.add(bound)
    if all_names:
        # __all__ is the authoritative public surface when present;
        # names already seen as module bindings stay in the module bucket
        names |= {n for n in all_names
                  if not n.startswith("_") and n not in mod_names}
    return names, mod_names, init


def diff_against_reference(ref_root: str) -> int:
    """Reference-contact protocol (VERDICT r4 item 8): the day the mount
    stops being empty, this produces the real missing-name list in minutes
    and converts the self-audit into a machine audit."""
    import paddle_tpu as p
    ref_names, ref_mods, init = _ref_flat_names(ref_root)
    if ref_names is None:
        print(f"reference mount has no {init} — still empty/absent; "
              f"nothing to diff (this is the expected state while the "
              f"mount is empty; re-run the session it appears)")
        return 1
    ours = set(_our_flat_names())
    our_mods = {n for n in dir(p) if not n.startswith("_")
                and isinstance(getattr(p, n), types.ModuleType)}
    ref_universe = ref_names | ref_mods
    # already-triaged names (the ABSENT table) are excluded from the
    # actionable missing list and verified separately below
    missing = sorted(ref_names - ours - our_mods - set(ABSENT))
    missing_mods = sorted(ref_mods - our_mods - ours - set(ABSENT))
    extra = sorted(ours - ref_universe)         # we have, upstream doesn't
    absent_confirmed = sorted(n for n in ABSENT if n in ref_universe)
    absent_stale = sorted(n for n in ABSENT if n not in ref_universe)
    out = []
    w = out.append
    w("# REF_DIFF — machine diff vs the real reference flat namespace")
    w("")
    w(f"Source: `{init}` ({len(ref_names)} public names + "
      f"{len(ref_mods)} module bindings).")
    w("")
    w(f"**Missing here ({len(missing)})** — upstream-flat names this build "
      f"does not expose, ABSENT table already subtracted (triage each: "
      f"implement, alias, or move to the ABSENT table with a mapping):")
    w("")
    w(" ".join(f"`{n}`" for n in missing) or "(none)")
    w("")
    w(f"**Missing submodules ({len(missing_mods)})** — upstream module "
      f"bindings with no namesake package here:")
    w("")
    w(" ".join(f"`{n}`" for n in missing_mods) or "(none)")
    w("")
    w(f"**Extra here ({len(extra)})** — candidates for the EXTENSIONS "
      f"table:")
    w("")
    w(" ".join(f"`{n}`" for n in extra) or "(none)")
    w("")
    w(f"**ABSENT hand-curation check:** {len(absent_confirmed)} confirmed "
      f"upstream-present (correctly listed), {len(absent_stale)} stale "
      f"(listed as known-absent but not in the real surface — remove): "
      + (", ".join(f"`{n}`" for n in absent_stale) or "none stale"))
    w("")
    with open("/root/repo/docs/REF_DIFF.md", "w") as f:
        f.write("\n".join(out))
    print(f"wrote docs/REF_DIFF.md: {len(missing)} missing, {len(extra)} "
          f"extra, ABSENT check {len(absent_confirmed)} ok/"
          f"{len(absent_stale)} stale")
    return 0


def main() -> None:
    import paddle_tpu as p
    from paddle_tpu.core.tensor import Tensor

    flat = {}
    modules = {}
    for n in sorted(dir(p)):
        if n.startswith("_"):
            continue
        o = getattr(p, n)
        if isinstance(o, types.ModuleType):
            modules[n] = o
        else:
            flat[n] = o

    by_home = defaultdict(list)
    for n, o in flat.items():
        home = getattr(o, "__module__", None) or type(o).__module__
        home = home.replace("paddle_tpu.", "") if home else "value"
        if n in EXTENSIONS:
            home = "(extension)"
        by_home[home].append(n)

    methods = sorted(n for n in dir(Tensor) if not n.startswith("_"))
    import paddle_tpu.linalg as linalg_mod
    linalg_fns = sorted(n for n in dir(linalg_mod) if not n.startswith("_")
                        and callable(getattr(linalg_mod, n)))

    n_ext = sum(1 for n in flat if n in EXTENSIONS)
    n_parity = len(flat) - n_ext

    out = []
    w = out.append
    w("# Flat-namespace API checklist (generated by tools/api_checklist.py)")
    w("")
    w("Ref surface: `python/paddle/__init__.py` (+ tensor method mounts in "
      "`python/paddle/tensor/__init__.py`). The reference mount is empty "
      "every round, so this audit enumerates our surface exhaustively and "
      "hand-curates the known-absent upstream names — auditable in both "
      "directions.")
    w("")
    w(f"**Counts: {n_parity} parity flat names + {n_ext} extensions "
      f"= {len(flat)} flat non-module names; {len(modules)} top-level "
      f"modules; {len(methods)} Tensor methods/properties; "
      f"{len(linalg_fns)} paddle.linalg functions. "
      f"Known-absent upstream flat names: {len(ABSENT)} (each mapped "
      f"below).**")
    w("")
    w("## Flat names by defining module")
    w("")
    for home in sorted(by_home):
        names = sorted(by_home[home])
        w(f"### {home} ({len(names)})")
        w("")
        w(" ".join(f"`{n}`" for n in names))
        w("")
    w("## Upstream flat names absent here (with mapping)")
    w("")
    w("| name | resolution |")
    w("|---|---|")
    for n, why in sorted(ABSENT.items()):
        w(f"| `{n}` | {why} |")
    w("")
    w("## Extensions (exposed here, not upstream-flat)")
    w("")
    w("| name | note |")
    w("|---|---|")
    for n, why in sorted(EXTENSIONS.items()):
        w(f"| `{n}` | {why} |")
    w("")
    w("## Top-level modules")
    w("")
    w("| module | role |")
    w("|---|---|")
    for n in sorted(modules):
        role = MODULE_ROLES.get(n, "upstream namesake package")
        w(f"| `{n}` | {role} |")
    w("")
    w("## Tensor methods/properties")
    w("")
    w(" ".join(f"`{n}`" for n in methods))
    w("")

    with open("/root/repo/docs/API_CHECKLIST.md", "w") as f:
        f.write("\n".join(out))
    print(f"wrote docs/API_CHECKLIST.md: {n_parity} parity + {n_ext} ext "
          f"flat, {len(modules)} modules, {len(methods)} methods")


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--diff":
        if len(sys.argv) < 3:
            print("usage: python tools/api_checklist.py --diff "
                  "<reference-root>", file=sys.stderr)
            sys.exit(2)
        sys.exit(diff_against_reference(sys.argv[2]))
    main()
