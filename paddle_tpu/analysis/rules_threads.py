"""PT006: unguarded shared state touched from a background thread.

The thread region is everything reachable from a ``threading.Thread(
target=...)`` entry point (watchdog monitor loops, heartbeat senders,
async checkpoint writers, DataLoader producers). Inside that region, a
write to module-level mutable state — ``global X`` rebinding, ``X[k] = v``,
``X.append(...)`` — races with the main thread unless it happens under a
``with <lock>:`` block.

Thread-safe containers are excluded by construction: module globals bound
to ``threading.Lock/RLock/Event/Condition/local`` or ``queue.Queue``
(their ctors are tracked by the index) never need an external lock.
Lock detection is name-based on the ``with`` subject: any ``Name`` or
attribute whose identifier ends in ``lock``/``mutex`` or is a tracked
Lock-typed global counts as a guard.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .callgraph import PackageIndex, FunctionInfo, ModuleInfo, _last_name
from .model import Config, Finding, register_rule

register_rule("PT006", "module-level mutable state written from a "
                       "background thread without the owning lock",
              severity="warning", module=__name__)

_MUTATORS = {"append", "add", "pop", "update", "setdefault", "extend",
             "remove", "clear", "insert", "discard", "popleft",
             "appendleft", "__setitem__"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "Barrier"}
# ctor names whose instances are themselves safe to touch without a lock
_SAFE_INSTANCE_CTORS = {"Lock", "RLock", "Event", "Condition", "Semaphore",
                        "BoundedSemaphore", "Barrier", "local", "Queue",
                        "SimpleQueue", "LifoQueue", "PriorityQueue"}


def _is_lock_expr(node: ast.AST, mi: ModuleInfo) -> bool:
    if isinstance(node, ast.Call):
        # `with lock_factory():` / `with self._lock:`-style `.acquire()` —
        # judge by the callee name
        return _is_lock_expr(node.func, mi)
    name = None
    if isinstance(node, ast.Name):
        name = node.id
        if name in mi.global_safe_types \
                and mi.global_safe_types[name] in _LOCK_CTORS:
            return True
    elif isinstance(node, ast.Attribute):
        name = node.attr
    if name is None:
        return False
    low = name.lower()
    return low.endswith("lock") or low.endswith("mutex") \
        or low in ("acquire", "locked")


def _declared_globals(fi: FunctionInfo) -> Set[str]:
    out: Set[str] = set()
    if isinstance(fi.node, ast.Lambda):
        return out
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _root_name(node: ast.AST):
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _check_function(fi: FunctionInfo, mi: ModuleInfo,
                    findings: List[Finding]) -> None:
    if isinstance(fi.node, ast.Lambda):
        return
    declared = _declared_globals(fi)

    def shared(name) -> bool:
        if name is None or name not in mi.module_globals:
            return False
        if mi.global_safe_types.get(name) in _SAFE_INSTANCE_CTORS:
            return False
        return True

    def report(node, name: str, what: str) -> None:
        findings.append(Finding(
            "PT006", "warning", mi.rel, node.lineno, node.col_offset,
            fi.qualname,
            f"module global `{name}` {what} from a background-thread "
            f"path without holding a lock",
            hint="wrap the write in `with <owning lock>:` (or move the "
                 "state into a Queue/threading.local)",
            detail=f"write:{name}"))

    def visit(node, lock_depth: int) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.With):
                guarded = any(_is_lock_expr(item.context_expr, mi)
                              for item in child.items)
                visit(child, lock_depth + (1 if guarded else 0))
                continue
            if lock_depth == 0:
                if isinstance(child, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)):
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    for t in targets:
                        if t is None:
                            continue
                        if isinstance(t, ast.Name):
                            # plain name rebind races only via `global`
                            if t.id in declared and shared(t.id):
                                report(child, t.id, "rebound")
                        else:
                            root = _root_name(t)
                            if shared(root):
                                report(child, root,
                                       "mutated (item/attr store)")
                elif isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute) \
                        and child.func.attr in _MUTATORS:
                    root = _root_name(child.func.value)
                    if shared(root):
                        report(child, root,
                               f"mutated (`.{child.func.attr}`)")
            visit(child, lock_depth)

    visit(fi.node, 0)


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    if not cfg.wants("PT006"):
        return []
    findings: List[Finding] = []
    for key in sorted(index.thread_region):
        fi = index.functions.get(key)
        if fi is None:
            continue
        _check_function(fi, index.modules[fi.modname], findings)
    return findings
