"""paddle_tpu.distributed — the distributed stack (SURVEY §2.3).

Core design: ONE device mesh (jax.sharding.Mesh) carries every parallelism
axis (pp/dp/sharding/sep/mp); GSPMD inserts the collectives the reference
issues through NCCL process groups. P1-P5/P10/P11/P13 here; P6 (pipeline),
P7 (MoE), P9 (ring attention) in their own modules.
"""

from . import env  # noqa: F401
from .env import get_rank, get_world_size  # noqa: F401

from .mesh import (AXIS_ORDER, HybridTopology, ProcessMesh,  # noqa: F401
                   build_hybrid_mesh, get_mesh, mesh_context, sanitize_spec,
                   set_mesh)
from .auto_parallel import (Partial, Replicate, Shard, dtensor_from_fn,  # noqa: F401
                            get_placements, mark_sharding, reshard,
                            shard_layer, shard_tensor)
from .collective import (ReduceOp, all_gather, all_reduce, alltoall,  # noqa: F401
                         barrier, broadcast, get_group, new_group, reduce,
                         reduce_scatter, stream, wait)
from . import watchdog  # noqa: F401
from .watchdog import CollectiveTimeout  # noqa: F401
from .parallel_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa: F401
                              RowParallelLinear, VocabParallelEmbedding,
                              annotate_sequence_parallel)
from .pp_schedule import generate_schedule  # noqa: F401
from .spawn import spawn  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from .ring_attention import (RingFlashAttention, ring_attention,  # noqa: F401
                             ulysses_attention)
from .sharding import (DygraphShardingOptimizer,  # noqa: F401
                       HybridParallelOptimizer, group_sharded_parallel,
                       save_group_sharded_model)
from . import fleet  # noqa: F401


def init_parallel_env():
    """ref: paddle.distributed.init_parallel_env — multi-host bring-up.
    Single-host (this dev environment): no-op beyond returning the env; on
    pods, jax.distributed.initialize is driven by the launcher (SURVEY §3.1
    TCPStore rendezvous ⇒ coordination service)."""
    # the real join happens in paddle_tpu._bootstrap at package import
    # (before any jnp value initialises the backend — COORDINATOR_ADDRESS
    # is the jax coordination port the launcher published through the
    # TCPStore, distinct from the PADDLE_MASTER store port); this explicit
    # call is the parity surface and a late-env fallback
    from .._bootstrap import maybe_initialize
    maybe_initialize()
    return None
